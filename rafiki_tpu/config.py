"""Code-level configuration constants (analogue of reference rafiki/config.py).

Environment-variable-first, mirroring the reference's config tiers
(SURVEY.md §5.6): deployment config comes from the environment; these are the
in-code defaults. Path-like values are resolved *lazily* (module
``__getattr__``) so tests and the placement layer can repoint
``RAFIKI_WORKDIR`` at runtime.
"""

import os


def _env_int(name: str, default: int) -> int:
    return int(os.environ.get(name, default))


def _env_float(name: str, default: float) -> float:
    return float(os.environ.get(name, default))


SUPERADMIN_EMAIL = os.environ.get("SUPERADMIN_EMAIL", "superadmin@rafiki")
SUPERADMIN_PASSWORD = os.environ.get("SUPERADMIN_PASSWORD", "rafiki")

APP_SECRET = os.environ.get("APP_SECRET", "rafiki-tpu-dev-secret")
TOKEN_TTL_HOURS = _env_int("TOKEN_TTL_HOURS", 24)

# Serving fleet shape per inference job — reference parity: 2 best trials
# x 2 replicas each (reference rafiki/config.py:10-11). The predictor
# load-balances within a trial's replicas and ensembles across trials.
INFERENCE_MAX_BEST_TRIALS = _env_int("INFERENCE_MAX_BEST_TRIALS", 2)
INFERENCE_WORKER_REPLICAS_PER_TRIAL = _env_int(
    "INFERENCE_WORKER_REPLICAS_PER_TRIAL", 2
)

# Continuous-batching predictor knobs. The reference's serving pipeline had a
# hard p50 floor of ~0.25-0.5 s from sleep-polling (reference rafiki/config.py:14,17
# and predictor/predictor.py:46-59); here queries are handed to the batcher via
# condition variables and flushed either when the batch fills or after
# PREDICT_BATCH_DEADLINE_MS, whichever is first. Deadline 0 = serve whatever
# has queued the moment the worker is free: under load batches fill by
# themselves (queries accumulate during the previous dispatch — continuous
# batching self-paces), so an artificial coalescing wait only adds latency
# at low load. Multi-query requests stay one batch via submit_many. Raise
# the deadline only if single-query clients swamp dispatch overhead.
PREDICT_MAX_BATCH_SIZE = _env_int("PREDICT_MAX_BATCH_SIZE", 64)
PREDICT_BATCH_DEADLINE_MS = _env_float("PREDICT_BATCH_DEADLINE_MS", 0.0)
PREDICT_TIMEOUT_S = _env_float("PREDICT_TIMEOUT_S", 30.0)

# -- serving-plane overload control (docs/failure-model.md, "Overload
# faults"). All four knobs resolve lazily (module __getattr__ below) so
# tests and operators can retune a live deployment's next queue/server
# without re-importing:
#   RAFIKI_PREDICT_QUEUE_DEPTH      per-worker inbox cap; submits beyond it
#                                   raise QueueFullError -> the doors shed
#                                   with 429 + Retry-After instead of
#                                   growing an unbounded backlog (0 = uncapped)
#   RAFIKI_PREDICT_MAX_INFLIGHT     concurrently-admitted requests per
#                                   serving door; excess is shed with 503
#                                   before it can pile up handler threads
#                                   (0 = unbounded)
#   RAFIKI_PREDICT_HEDGE_SUPPRESS_DEPTH
#                                   a sibling replica whose queue depth
#                                   exceeds this never receives a hedge
#                                   batch — duplicate work onto an already
#                                   saturated replica is how overload
#                                   metastasizes ("The Tail at Scale")
#   RAFIKI_PREDICT_DRAIN_S          PredictorServer.stop() waits this long
#                                   for in-flight handlers before closing

DEFAULT_TRIAL_COUNT = _env_int("DEFAULT_TRIAL_COUNT", 5)

ADMIN_HOST = os.environ.get("ADMIN_HOST", "127.0.0.1")
ADMIN_PORT = _env_int("ADMIN_PORT", 3000)

SERVICE_DEPLOY_TIMEOUT_S = _env_float("SERVICE_DEPLOY_TIMEOUT_S", 60.0)

# -- fleet health (docs/failure-model.md) -----------------------------------
# Heartbeats: the admin-side HostAgentPlacementManager probes each agent's
# /healthz every AGENT_HEARTBEAT_INTERVAL_S; AGENT_DOWN_THRESHOLD
# consecutive misses marks the host DOWN (queues evicted, services
# errored/rescheduled). 0 disables the monitor thread.
AGENT_HEARTBEAT_INTERVAL_S = _env_float("RAFIKI_AGENT_HEARTBEAT_S", 5.0)
AGENT_DOWN_THRESHOLD = _env_int("RAFIKI_AGENT_DOWN_THRESHOLD", 3)
AGENT_HEARTBEAT_TIMEOUT_S = _env_float("RAFIKI_AGENT_HEARTBEAT_TIMEOUT_S", 2.0)
# Transport retry (idempotent agent calls only): up to AGENT_RETRY_MAX
# re-attempts on transport failure, exponential backoff from
# AGENT_RETRY_BACKOFF_S with full jitter.
AGENT_RETRY_MAX = _env_int("RAFIKI_AGENT_RETRY_MAX", 2)
AGENT_RETRY_BACKOFF_S = _env_float("RAFIKI_AGENT_RETRY_BACKOFF_S", 0.1)
# Circuit breaker: AGENT_BREAKER_THRESHOLD consecutive transport failures
# open an agent's circuit; calls then fail fast (no 10 s socket timeout)
# until a half-open probe succeeds after AGENT_BREAKER_COOLDOWN_S.
AGENT_BREAKER_THRESHOLD = _env_int("RAFIKI_AGENT_BREAKER_THRESHOLD", 3)
AGENT_BREAKER_COOLDOWN_S = _env_float("RAFIKI_AGENT_BREAKER_COOLDOWN_S", 5.0)


def workdir() -> str:
    return os.environ.get("RAFIKI_WORKDIR", os.path.abspath("."))


# Filesystem layout (shared volume in the reference, local dirs here).
# Resolved lazily against the current environment on every access.
_DYNAMIC_PATHS = {
    "WORKDIR": lambda: workdir(),
    "DATA_DIR": lambda: os.environ.get(
        "RAFIKI_DATA_DIR", os.path.join(workdir(), "data")
    ),
    "PARAMS_DIR": lambda: os.environ.get(
        "RAFIKI_PARAMS_DIR", os.path.join(workdir(), "params")
    ),
    "LOGS_DIR": lambda: os.environ.get(
        "RAFIKI_LOGS_DIR", os.path.join(workdir(), "logs")
    ),
    # connection string: RAFIKI_DB_URL (e.g. postgresql://...) wins over the
    # sqlite file path, so EVERY call site that passes config.DB_PATH honors
    # the URL
    "DB_PATH": lambda: (
        os.environ.get("RAFIKI_DB_URL")
        or os.environ.get("RAFIKI_DB_PATH")
        or os.path.join(workdir(), "rafiki.sqlite3")
    ),
    # per-job predictor listeners: lazily resolved so a deployment (or a
    # test) can flip RAFIKI_PREDICTOR_PORTS before deploying a job
    "PREDICTOR_PORTS": lambda: (
        os.environ.get("RAFIKI_PREDICTOR_PORTS", "0") == "1"),
    "PREDICTOR_HOST": lambda: (
        os.environ.get("RAFIKI_PREDICTOR_HOST", "127.0.0.1")),
    # overload-control knobs (commented where declared above)
    "PREDICT_QUEUE_DEPTH": lambda: _env_int(
        "RAFIKI_PREDICT_QUEUE_DEPTH", 256),
    "PREDICT_MAX_INFLIGHT": lambda: _env_int(
        "RAFIKI_PREDICT_MAX_INFLIGHT", 64),
    "PREDICT_HEDGE_SUPPRESS_DEPTH": lambda: _env_int(
        "RAFIKI_PREDICT_HEDGE_SUPPRESS_DEPTH", PREDICT_MAX_BATCH_SIZE),
    "PREDICT_DRAIN_S": lambda: _env_float("RAFIKI_PREDICT_DRAIN_S", 5.0),
    # -- prediction result cache + single-flight coalescing (docs/
    # performance.md "Prediction caching & single-flight"). Lazy so a
    # live deployment's NEXT request picks up a retune. OFF by default:
    # serving identical answers to identical queries is a behavior
    # change the operator opts into (a template whose predict is
    # deliberately stochastic would be silently de-randomized):
    #   RAFIKI_PREDICT_CACHE=1          serve repeated identical queries
    #                                   from a bounded in-process cache
    #                                   keyed (query digest, job, served
    #                                   model version) — invalidated on
    #                                   deploy/rollback/recovery
    #                                   adoption, excluded for
    #                                   TEXT_GENERATION and ensembled-
    #                                   stochastic jobs
    #   RAFIKI_PREDICT_CACHE_TTL_S=30   entry lifetime; <=0 disables
    #                                   fills (doctor WARNs with the
    #                                   cache on)
    #   RAFIKI_PREDICT_CACHE_MAX_BYTES=67108864  byte cap, LRU-evicted
    #                                   (doctor WARNs past the host-
    #                                   memory heuristic)
    #   RAFIKI_PREDICT_SINGLEFLIGHT=1   0 = concurrent identical misses
    #                                   each pay their own forward
    #                                   instead of sharing the leader's
    #                                   (only consulted while the cache
    #                                   is on)
    "PREDICT_CACHE": lambda: os.environ.get(
        "RAFIKI_PREDICT_CACHE", "0") == "1",
    "PREDICT_CACHE_TTL_S": lambda: _env_float(
        "RAFIKI_PREDICT_CACHE_TTL_S", 30.0),
    "PREDICT_CACHE_MAX_BYTES": lambda: _env_int(
        "RAFIKI_PREDICT_CACHE_MAX_BYTES", 64 * 1024 * 1024),
    "PREDICT_SINGLEFLIGHT": lambda: os.environ.get(
        "RAFIKI_PREDICT_SINGLEFLIGHT", "1") != "0",
    # -- control-plane crash recovery (docs/failure-model.md, "Control-
    # plane faults"). A fresh Admin on an existing store reconciles the
    # DB against what is actually running before opening its doors:
    #   RAFIKI_RECOVER_ADOPT=1            0 = never adopt surviving
    #                                     workers on restart; they are
    #                                     fenced (stopped) and train
    #                                     services rescheduled instead
    #                                     (doctor WARNs while set)
    #   RAFIKI_RECOVER_PROBE_TIMEOUT_S=5  per-agent inventory probe budget
    #   RAFIKI_RECOVER_RETRY_MAX=4        metadata-store retries during
    #                                     reconcile (bounded, jittered)
    #   RAFIKI_RECOVER_RETRY_BACKOFF_S=0.2  backoff base for those retries
    # -- training-plane trial fault tolerance (docs/failure-model.md,
    # "Training-plane faults"). Lazy so tests/operators retune a live
    # worker's NEXT trial without re-importing:
    #   RAFIKI_TRIAL_RETRY_MAX=2        infra-class faults (INFRA/MEM/
    #                                   STALL) re-run under the same
    #                                   trial id up to this many times
    #                                   (0 = every fault burns budget;
    #                                   doctor WARNs)
    #   RAFIKI_TRIAL_RETRY_BACKOFF_S=0.5  backoff base for those
    #                                   re-runs (exponential, jittered)
    #   RAFIKI_TRIAL_QUARANTINE_K=3     user-class faults on near-
    #                                   identical knobs before that
    #                                   signature is quarantined
    #   RAFIKI_TRIAL_REPROPOSE_MAX=8    proposals rejected per slot for
    #                                   matching a quarantined signature
    #                                   before the worker accepts one
    #   RAFIKI_TRIAL_FAULT_LIMIT=5      consecutive user-class faults on
    #                                   DISTINCT knobs that error the
    #                                   whole job early (0 disables)
    #   RAFIKI_PENDING_FEEDBACK_MAX=256 cap on queued advisor feedback
    #                                   awaiting retry (drop-oldest)
    # (RAFIKI_TRIAL_STALL_S lives in sdk/sandbox.py: the no-frame
    # deadline on sandbox children.)
    # -- vectorized trial execution (docs/performance.md, "Vectorized
    # trial execution"). Lazy like the other trial knobs:
    #   RAFIKI_TRIAL_VMAP=1           0 = kill switch: never train a
    #                                 population of proposals as one
    #                                 vmapped program, even for templates
    #                                 that advertise population_spec
    #   RAFIKI_TRIAL_VMAP_K=4         proposals drained per vectorized
    #                                 round (also settable per job via
    #                                 budget TRIAL_VMAP_K; capped by the
    #                                 template's PopulationSpec
    #                                 max_members); <2 disables in effect
    "TRIAL_VMAP": lambda: os.environ.get("RAFIKI_TRIAL_VMAP", "1") != "0",
    "TRIAL_VMAP_K": lambda: _env_int("RAFIKI_TRIAL_VMAP_K", 4),
    "TRIAL_RETRY_MAX": lambda: _env_int("RAFIKI_TRIAL_RETRY_MAX", 2),
    "TRIAL_RETRY_BACKOFF_S": lambda: _env_float(
        "RAFIKI_TRIAL_RETRY_BACKOFF_S", 0.5),
    "TRIAL_QUARANTINE_K": lambda: _env_int("RAFIKI_TRIAL_QUARANTINE_K", 3),
    "TRIAL_REPROPOSE_MAX": lambda: _env_int("RAFIKI_TRIAL_REPROPOSE_MAX", 8),
    "TRIAL_FAULT_LIMIT": lambda: _env_int("RAFIKI_TRIAL_FAULT_LIMIT", 5),
    "PENDING_FEEDBACK_MAX": lambda: _env_int(
        "RAFIKI_PENDING_FEEDBACK_MAX", 256),
    # -- elastic serving autoscaler (docs/failure-model.md, "Overload
    # adaptation"). All knobs resolve lazily so tests and operators can
    # retune a live control loop; the loop itself is OFF by default —
    # existing deployments keep their static replica counts:
    #   RAFIKI_AUTOSCALE=1              start the admin-side control loop
    #   RAFIKI_AUTOSCALE_INTERVAL_S=2   decision-loop tick interval
    #   RAFIKI_AUTOSCALE_WINDOW_S=15    signal window a decision looks at
    #   RAFIKI_AUTOSCALE_SHED_THRESHOLD=3   shed events inside the window
    #                                   that read "sustained overload"
    #   RAFIKI_AUTOSCALE_DEPTH_HIGH=8   mean backlog depth that scales up
    #   RAFIKI_AUTOSCALE_DEPTH_LOW=1    max backlog depth that still
    #                                   counts as idle (hysteresis: LOW
    #                                   must sit well under HIGH)
    #   RAFIKI_AUTOSCALE_MIN_REPLICAS=1 never drain below this many live
    #                                   replicas per job
    #   RAFIKI_AUTOSCALE_MAX_REPLICAS=8 never grow past this many
    #   RAFIKI_AUTOSCALE_STEP=1         replicas per decision (bounded
    #                                   step — the loop cannot stampede)
    #   RAFIKI_AUTOSCALE_COOLDOWN_UP_S=5    quiet time after ANY action
    #                                   before the next scale-up
    #   RAFIKI_AUTOSCALE_COOLDOWN_DOWN_S=30 ... before the next
    #                                   scale-down (longer: flapping down
    #                                   is worse than holding spare
    #                                   capacity a little while)
    #   RAFIKI_AUTOSCALE_DRAIN_S=10     bounded graceful-drain window per
    #                                   removed replica (stop admitting,
    #                                   flush its queue, then destroy)
    #   RAFIKI_AUTOSCALE_TRAIN_FLOOR=1  chips the serving plane may never
    #                                   borrow into: at least this many
    #                                   chips stay free (or training's)
    #                                   whatever the surge
    #   RAFIKI_AUTOSCALE_FAIR=1         per-job weighted fair admission at
    #                                   shared doors (off by default)
    #   RAFIKI_AUTOSCALE_FAIR_WINDOW_S=10   half-life of the per-tenant
    #                                   admitted-query charge decay
    #   RAFIKI_AUTOSCALE_FAIR_BURST=32  admitted queries a tenant may run
    #                                   past its fair share before 429s
    #   RAFIKI_AUTOSCALE_FAIR_WEIGHTS=  "appA=3,appB=1" (unlisted
    #                                   tenants weigh 1)
    # -- generative serving (docs/serving-generation.md). Lazy like the
    # other serving knobs so a live deployment's NEXT worker/stream picks
    # up a retune:
    #   RAFIKI_GEN_MAX_SLOTS=8          co-resident sequences per
    #                                   generation worker (the KV cache is
    #                                   preallocated at this width; doctor
    #                                   WARNs past the memory heuristic)
    #   RAFIKI_GEN_MAX_TOKENS=64        per-request decode budget cap (a
    #                                   request asking more is clamped)
    #   RAFIKI_GEN_STREAM_TIMEOUT_S=10  door-side inter-token stall
    #                                   timeout: a stream with no delta
    #                                   for this long ends with a typed
    #                                   terminal error frame
    #   RAFIKI_GEN_OCCUPANCY_HIGH=0.85  mean slot occupancy over the
    #                                   autoscaler window that reads
    #                                   "generation slots saturated" and
    #                                   scales the job up
    #   RAFIKI_GEN_KV_PAGED=1           0 = legacy contiguous ring per
    #                                   slot (the A/B baseline); 1 = the
    #                                   block/paged KV allocator for
    #                                   templates that advertise the
    #                                   paged methods (worker/kv_paging)
    #   RAFIKI_GEN_KV_BLOCK_TOKENS=16   K/V rows per pool page — the
    #                                   paging granularity (doctor WARNs
    #                                   on degenerate sizes)
    #   RAFIKI_GEN_KV_POOL_BLOCKS=0     pages in the pool; 0 = auto-size
    #                                   to the legacy ring's capacity
    #                                   (slots x ceil(max_context/block))
    #                                   so paged-vs-ring A/B runs at
    #                                   equal KV memory
    #   RAFIKI_GEN_PREFIX_CACHE=1       0 = never share prompt-prefix
    #                                   blocks across streams (hit/miss
    #                                   counters and the doctor surface a
    #                                   disabled cache under shared-
    #                                   prefix traffic)
    #   RAFIKI_GEN_PREFILL_CHUNK=64     prompt tokens ingested per
    #                                   scheduler round (paged path): a
    #                                   long-prompt join interleaves with
    #                                   decode rounds instead of stalling
    #                                   resident streams (0 = one-shot
    #                                   prefill)
    #   RAFIKI_GEN_SAMPLING=1           0 = greedy-only serving: requests
    #                                   carrying temperature/top_k/top_p/
    #                                   seed get a typed 400 instead of a
    #                                   silent greedy answer (kill switch)
    #   RAFIKI_GEN_SPEC=1               0 = never speculate; 1 = draft-
    #                                   verify speculative decoding on the
    #                                   paged path whenever the job has a
    #                                   draft model (GEN_DRAFT_TRIAL
    #                                   budget) and the template verifies
    #   RAFIKI_GEN_SPEC_K=4             draft tokens proposed per round;
    #                                   the verify forward is k+1 wide,
    #                                   so k also sizes the per-round KV
    #                                   write burst (doctor WARNs past 8)
    #   RAFIKI_GEN_SPEC_MIN_RATE=0.3    acceptance rate below which the
    #                                   doctor reads "the draft is not
    #                                   earning its keep" (observability
    #                                   threshold only — serving never
    #                                   auto-disables on it)
    # -- stream continuity (docs/failure-model.md "Stream continuity"):
    # the door journals each live stream and resumes it on a sibling
    # replica when its replica dies or hands the stream back:
    #   RAFIKI_GEN_RESUME_MAX=3         resume attempts per stream before
    #                                   the fault surfaces to the client
    #                                   (0 disables resume entirely —
    #                                   drain handoffs then become
    #                                   client-visible errors; doctor
    #                                   WARNs with the autoscaler on)
    #   RAFIKI_GEN_RESUME_BACKOFF_S=0.05  base of the jittered resume
    #                                   backoff (attempt n sleeps up to
    #                                   base*2^n, capped by the request
    #                                   deadline)
    #   RAFIKI_GEN_JOURNAL_MAX_KB=64    per-stream journal byte cap
    #                                   (prompt + committed tokens); a
    #                                   stream outgrowing it keeps
    #                                   streaming but loses resume
    #                                   eligibility (doctor WARNs when
    #                                   the cap cannot hold a worst-case
    #                                   GEN_MAX_TOKENS stream)
    #   RAFIKI_GEN_JOURNAL_TTL_S=600    journal entry TTL: a stream older
    #                                   than this is never resumed (a
    #                                   wedged multi-hour stream must not
    #                                   replay forever)
    "GEN_MAX_SLOTS": lambda: _env_int("RAFIKI_GEN_MAX_SLOTS", 8),
    "GEN_SAMPLING": lambda: os.environ.get(
        "RAFIKI_GEN_SAMPLING", "1") != "0",
    "GEN_SPEC": lambda: os.environ.get("RAFIKI_GEN_SPEC", "1") != "0",
    "GEN_SPEC_K": lambda: _env_int("RAFIKI_GEN_SPEC_K", 4),
    "GEN_SPEC_MIN_RATE": lambda: _env_float(
        "RAFIKI_GEN_SPEC_MIN_RATE", 0.3),
    "GEN_KV_PAGED": lambda: os.environ.get(
        "RAFIKI_GEN_KV_PAGED", "1") != "0",
    "GEN_KV_BLOCK_TOKENS": lambda: _env_int(
        "RAFIKI_GEN_KV_BLOCK_TOKENS", 16),
    "GEN_KV_POOL_BLOCKS": lambda: _env_int("RAFIKI_GEN_KV_POOL_BLOCKS", 0),
    "GEN_PREFIX_CACHE": lambda: os.environ.get(
        "RAFIKI_GEN_PREFIX_CACHE", "1") != "0",
    "GEN_PREFILL_CHUNK": lambda: _env_int("RAFIKI_GEN_PREFILL_CHUNK", 64),
    "GEN_MAX_TOKENS": lambda: _env_int("RAFIKI_GEN_MAX_TOKENS", 64),
    "GEN_STREAM_TIMEOUT_S": lambda: _env_float(
        "RAFIKI_GEN_STREAM_TIMEOUT_S", 10.0),
    "GEN_OCCUPANCY_HIGH": lambda: _env_float(
        "RAFIKI_GEN_OCCUPANCY_HIGH", 0.85),
    "GEN_RESUME_MAX": lambda: _env_int("RAFIKI_GEN_RESUME_MAX", 3),
    "GEN_RESUME_BACKOFF_S": lambda: _env_float(
        "RAFIKI_GEN_RESUME_BACKOFF_S", 0.05),
    "GEN_JOURNAL_MAX_KB": lambda: _env_int(
        "RAFIKI_GEN_JOURNAL_MAX_KB", 64),
    "GEN_JOURNAL_TTL_S": lambda: _env_float(
        "RAFIKI_GEN_JOURNAL_TTL_S", 600.0),
    "AUTOSCALE": lambda: os.environ.get("RAFIKI_AUTOSCALE", "0") == "1",
    "AUTOSCALE_INTERVAL_S": lambda: _env_float(
        "RAFIKI_AUTOSCALE_INTERVAL_S", 2.0),
    "AUTOSCALE_WINDOW_S": lambda: _env_float(
        "RAFIKI_AUTOSCALE_WINDOW_S", 15.0),
    "AUTOSCALE_SHED_THRESHOLD": lambda: _env_int(
        "RAFIKI_AUTOSCALE_SHED_THRESHOLD", 3),
    "AUTOSCALE_DEPTH_HIGH": lambda: _env_float(
        "RAFIKI_AUTOSCALE_DEPTH_HIGH", 8.0),
    "AUTOSCALE_DEPTH_LOW": lambda: _env_float(
        "RAFIKI_AUTOSCALE_DEPTH_LOW", 1.0),
    "AUTOSCALE_MIN_REPLICAS": lambda: _env_int(
        "RAFIKI_AUTOSCALE_MIN_REPLICAS", 1),
    "AUTOSCALE_MAX_REPLICAS": lambda: _env_int(
        "RAFIKI_AUTOSCALE_MAX_REPLICAS", 8),
    "AUTOSCALE_STEP": lambda: _env_int("RAFIKI_AUTOSCALE_STEP", 1),
    "AUTOSCALE_COOLDOWN_UP_S": lambda: _env_float(
        "RAFIKI_AUTOSCALE_COOLDOWN_UP_S", 5.0),
    "AUTOSCALE_COOLDOWN_DOWN_S": lambda: _env_float(
        "RAFIKI_AUTOSCALE_COOLDOWN_DOWN_S", 30.0),
    "AUTOSCALE_DRAIN_S": lambda: _env_float("RAFIKI_AUTOSCALE_DRAIN_S", 10.0),
    "AUTOSCALE_TRAIN_FLOOR": lambda: _env_int(
        "RAFIKI_AUTOSCALE_TRAIN_FLOOR", 1),
    "AUTOSCALE_FAIR": lambda: os.environ.get(
        "RAFIKI_AUTOSCALE_FAIR", "0") == "1",
    "AUTOSCALE_FAIR_WINDOW_S": lambda: _env_float(
        "RAFIKI_AUTOSCALE_FAIR_WINDOW_S", 10.0),
    "AUTOSCALE_FAIR_BURST": lambda: _env_float(
        "RAFIKI_AUTOSCALE_FAIR_BURST", 32.0),
    "AUTOSCALE_FAIR_WEIGHTS": lambda: os.environ.get(
        "RAFIKI_AUTOSCALE_FAIR_WEIGHTS", ""),
    # -- cold-start resilience (docs/failure-model.md "Cold-start
    # faults"). The persistent XLA executable cache makes a replacement
    # process's jit programs a disk read instead of a compile; the warm
    # standby pool makes scale-up/replacement an add_worker route instead
    # of a deploy. Lazy like every serving knob:
    #   RAFIKI_COMPILE_CACHE=1          0 disables the persistent compile
    #                                   cache everywhere (workers still
    #                                   warm up, every boot is cold)
    #   RAFIKI_COMPILE_CACHE_DIR=       shared executable-cache root
    #                                   (default WORKDIR/xla_cache); keyed
    #                                   per topology underneath — see
    #                                   sdk/compile_cache.py
    #   RAFIKI_COMPILE_CACHE_CPU=1      opt the CPU backend in (entries
    #                                   are machine-feature-tied; safe on
    #                                   one box, default off)
    #   RAFIKI_COMPILE_CACHE_MIN_COMPILE_S=0.5  only persist programs
    #                                   whose compile took at least this
    #                                   long (0 = persist everything —
    #                                   what the drills/bench use on CPU)
    #   RAFIKI_COMPILE_WARM_THRESHOLD_S=1.0  warm/cold classification
    #                                   fallback when the JAX cache-event
    #                                   listeners are unavailable: a boot
    #                                   whose total warm-up compile time
    #                                   stays under this reads warm
    #   RAFIKI_AUTOSCALE_WARM_POOL=0    K pre-loaded, pre-warmed standby
    #                                   replicas kept per RUNNING
    #                                   inference job (0 = off). Standbys
    #                                   hold chips via the arbiter's
    #                                   borrow book: the training floor
    #                                   still outranks them and reclaim
    #                                   drains them FIRST
    #   RAFIKI_AUTOSCALE_WARM_POOL_INTERVAL_S=5  maintenance-loop tick
    #   RAFIKI_AUTOSCALE_WARM_RETRY_MAX=3  consecutive standby-placement
    #                                   failures per job before the pool
    #                                   reports that job degraded and
    #                                   pauses retries
    #   RAFIKI_AUTOSCALE_WARM_RETRY_COOLDOWN_S=30  how long a degraded
    #                                   job's refill stays paused
    "COMPILE_CACHE": lambda: os.environ.get(
        "RAFIKI_COMPILE_CACHE", "1") != "0",
    "COMPILE_CACHE_DIR": lambda: os.environ.get(
        "RAFIKI_COMPILE_CACHE_DIR", ""),
    "COMPILE_CACHE_CPU": lambda: os.environ.get(
        "RAFIKI_COMPILE_CACHE_CPU", "") != "",
    "COMPILE_CACHE_MIN_COMPILE_S": lambda: _env_float(
        "RAFIKI_COMPILE_CACHE_MIN_COMPILE_S", 0.5),
    "COMPILE_WARM_THRESHOLD_S": lambda: _env_float(
        "RAFIKI_COMPILE_WARM_THRESHOLD_S", 1.0),
    "AUTOSCALE_WARM_POOL": lambda: _env_int(
        "RAFIKI_AUTOSCALE_WARM_POOL", 0),
    "AUTOSCALE_WARM_POOL_INTERVAL_S": lambda: _env_float(
        "RAFIKI_AUTOSCALE_WARM_POOL_INTERVAL_S", 5.0),
    "AUTOSCALE_WARM_RETRY_MAX": lambda: _env_int(
        "RAFIKI_AUTOSCALE_WARM_RETRY_MAX", 3),
    "AUTOSCALE_WARM_RETRY_COOLDOWN_S": lambda: _env_float(
        "RAFIKI_AUTOSCALE_WARM_RETRY_COOLDOWN_S", 30.0),
    # -- safe live rollouts (docs/failure-model.md "Rollout faults").
    # admin/rollout.py updates a RUNNING inference job to a new trial in
    # place: one canary replica judged over a trailing window, then a
    # rolling replace in bounded batches, with automatic rollback on SLO
    # breach / canary crash / deploy timeout. Lazy so a live rollout's
    # NEXT phase picks up a retune:
    #   RAFIKI_ROLLOUT_CANARY_FRACTION=0.1  traffic fraction routed to
    #                                   the canary replica while it is
    #                                   judged (0..1)
    #   RAFIKI_ROLLOUT_JUDGE_WINDOW_S=10  trailing window the SLO judge
    #                                   compares canary vs incumbent over
    #   RAFIKI_ROLLOUT_MIN_REQUESTS=5   canary requests needed before an
    #                                   error-rate/latency verdict counts
    #                                   (an idle job proceeds after
    #                                   3x the window with a low-traffic
    #                                   note instead of stalling forever)
    #   RAFIKI_ROLLOUT_ERR_DELTA=0.1    max (canary - incumbent) error
    #                                   rate before automatic rollback
    #   RAFIKI_ROLLOUT_P95_FACTOR=3.0   canary p95 past incumbent p95 x
    #                                   this factor is an SLO breach
    #   RAFIKI_ROLLOUT_BATCH=1          replicas replaced per rolling
    #                                   batch (place new, drain old)
    "ROLLOUT_CANARY_FRACTION": lambda: _env_float(
        "RAFIKI_ROLLOUT_CANARY_FRACTION", 0.1),
    "ROLLOUT_JUDGE_WINDOW_S": lambda: _env_float(
        "RAFIKI_ROLLOUT_JUDGE_WINDOW_S", 10.0),
    "ROLLOUT_MIN_REQUESTS": lambda: _env_int(
        "RAFIKI_ROLLOUT_MIN_REQUESTS", 5),
    "ROLLOUT_ERR_DELTA": lambda: _env_float(
        "RAFIKI_ROLLOUT_ERR_DELTA", 0.1),
    "ROLLOUT_P95_FACTOR": lambda: _env_float(
        "RAFIKI_ROLLOUT_P95_FACTOR", 3.0),
    "ROLLOUT_BATCH": lambda: _env_int("RAFIKI_ROLLOUT_BATCH", 1),
    "RECOVER_ADOPT": lambda: os.environ.get(
        "RAFIKI_RECOVER_ADOPT", "1") != "0",
    "RECOVER_PROBE_TIMEOUT_S": lambda: _env_float(
        "RAFIKI_RECOVER_PROBE_TIMEOUT_S", 5.0),
    "RECOVER_RETRY_MAX": lambda: _env_int("RAFIKI_RECOVER_RETRY_MAX", 4),
    "RECOVER_RETRY_BACKOFF_S": lambda: _env_float(
        "RAFIKI_RECOVER_RETRY_BACKOFF_S", 0.2),
    # -- drift closed loop (docs/failure-model.md "Model drift faults").
    # admin/drift.py watches each RUNNING inference job's serving plane
    # for input-distribution shift / confidence decay, launches ONE
    # bounded warm-started retrain, and auto-rolls-out a better candidate
    # through the SLO-judged rollout. Lazy so the NEXT monitor tick picks
    # up a retune:
    #   RAFIKI_DRIFT=1                  enable the closed loop (off by
    #                                   default: monitor, retrain, and
    #                                   rollout all stay dormant)
    #   RAFIKI_DRIFT_INTERVAL_S=2       seconds between monitor ticks
    #   RAFIKI_DRIFT_WINDOW_S=10        trailing sample window the
    #                                   monitor evaluates each tick
    #   RAFIKI_DRIFT_BASELINE_WINDOW_S=10  window frozen as the baseline
    #                                   after enable/rollout (doctor
    #                                   WARNs when shorter than the
    #                                   monitor window)
    #   RAFIKI_DRIFT_MIN_SAMPLES=20    requests needed in a window before
    #                                   a baseline freezes or a verdict
    #                                   counts (idle jobs never flap)
    #   RAFIKI_DRIFT_THRESHOLD=0.5     novelty fraction (share of the
    #                                   current window's digests absent
    #                                   from the baseline population)
    #                                   that counts as distribution shift
    #   RAFIKI_DRIFT_CONF_DROP=0.2     mean top-probability decay vs the
    #                                   baseline that counts as score/
    #                                   confidence drift (probability
    #                                   tasks only)
    #   RAFIKI_DRIFT_SKEW_DELTA=0.4    growth of the single most frequent
    #                                   digest's traffic share vs baseline
    #                                   that counts as skew (one caller
    #                                   dominating a shared door)
    #   RAFIKI_DRIFT_RETRAIN_BUDGET=3  MODEL_TRIAL_COUNT for the
    #                                   auto-retrain (0 = monitor-only:
    #                                   events fire, nothing launches)
    #   RAFIKI_DRIFT_COOLDOWN_S=60     base per-job cooldown after a
    #                                   retrain resolves; doubles per
    #                                   consecutive rollback (capped x16)
    #   RAFIKI_DRIFT_LAUNCH_RETRY_MAX=2  retrain-launch retries (one per
    #                                   tick) before the loop parks with
    #                                   a typed event
    "DRIFT": lambda: os.environ.get("RAFIKI_DRIFT", "0") == "1",
    "DRIFT_INTERVAL_S": lambda: _env_float("RAFIKI_DRIFT_INTERVAL_S", 2.0),
    "DRIFT_WINDOW_S": lambda: _env_float("RAFIKI_DRIFT_WINDOW_S", 10.0),
    "DRIFT_BASELINE_WINDOW_S": lambda: _env_float(
        "RAFIKI_DRIFT_BASELINE_WINDOW_S", 10.0),
    "DRIFT_MIN_SAMPLES": lambda: _env_int("RAFIKI_DRIFT_MIN_SAMPLES", 20),
    "DRIFT_THRESHOLD": lambda: _env_float("RAFIKI_DRIFT_THRESHOLD", 0.5),
    "DRIFT_CONF_DROP": lambda: _env_float("RAFIKI_DRIFT_CONF_DROP", 0.2),
    "DRIFT_SKEW_DELTA": lambda: _env_float("RAFIKI_DRIFT_SKEW_DELTA", 0.4),
    "DRIFT_RETRAIN_BUDGET": lambda: _env_int(
        "RAFIKI_DRIFT_RETRAIN_BUDGET", 3),
    "DRIFT_COOLDOWN_S": lambda: _env_float("RAFIKI_DRIFT_COOLDOWN_S", 60.0),
    "DRIFT_LAUNCH_RETRY_MAX": lambda: _env_int(
        "RAFIKI_DRIFT_LAUNCH_RETRY_MAX", 2),
    # -- control-plane HA (admin/lease.py, admin/standby.py;
    #    docs/failure-model.md "Control-plane HA") --------------------------
    #   RAFIKI_ADMIN_HA=0              leased leadership on boot: the admin
    #                                   acquires the control_lease row (or
    #                                   refuses to start as leader). Off by
    #                                   default: a solo admin needs no lease
    #   RAFIKI_ADMIN_LEASE_TTL_S=10    leadership lease TTL; a leader that
    #                                   cannot renew self-fences at TTL, a
    #                                   standby promotes after it
    #   RAFIKI_ADMIN_LEASE_RENEW_S=0   renewal period (0 = TTL/3)
    #   RAFIKI_ADMIN_LEASE_ACQUIRE_TIMEOUT_S=30  how long a booting leader
    #                                   waits out a predecessor's lease
    #   RAFIKI_ADMIN_ADDRS=            comma list of admin host:port for
    #                                   client failover (leader + standbys)
    #   RAFIKI_ADMIN_FAILOVER_TIMEOUT_S=20  how long Client._call keeps
    #                                   walking the address list before the
    #                                   typed AdminUnavailableError
    #   RAFIKI_ADMIN_STANDBY_POLL_S=0  standby lease-watch period
    #                                   (0 = the renewal period)
    #   RAFIKI_RECOVERY_REPORT_KEEP=5  epoch-suffixed recovery-e<N>.json
    #                                   reports kept per LOGS_DIR
    "ADMIN_HA": lambda: _env_int("RAFIKI_ADMIN_HA", 0),
    "ADMIN_LEASE_TTL_S": lambda: _env_float("RAFIKI_ADMIN_LEASE_TTL_S", 10.0),
    "ADMIN_LEASE_RENEW_S": lambda: _env_float(
        "RAFIKI_ADMIN_LEASE_RENEW_S", 0.0),
    "ADMIN_LEASE_ACQUIRE_TIMEOUT_S": lambda: _env_float(
        "RAFIKI_ADMIN_LEASE_ACQUIRE_TIMEOUT_S", 30.0),
    "ADMIN_ADDRS": lambda: os.environ.get("RAFIKI_ADMIN_ADDRS", ""),
    "ADMIN_FAILOVER_TIMEOUT_S": lambda: _env_float(
        "RAFIKI_ADMIN_FAILOVER_TIMEOUT_S", 20.0),
    "ADMIN_STANDBY_POLL_S": lambda: _env_float(
        "RAFIKI_ADMIN_STANDBY_POLL_S", 0.0),
    "RECOVERY_REPORT_KEEP": lambda: _env_int(
        "RAFIKI_RECOVERY_REPORT_KEEP", 5),
}


def __getattr__(name: str) -> str:
    if name in _DYNAMIC_PATHS:
        return _DYNAMIC_PATHS[name]()
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


# -- env-knob declaration point (docs/static-analysis.md, FWK101) -----------
# Every RAFIKI_* environment name the package reads MUST appear in this
# file — the framework self-lint (analysis/framework.py) fails tier-1 on
# any read site whose knob is missing here. Knobs config.py itself reads
# above are declared implicitly; these catalogs cover names read at
# their point of use in other modules (lazy/module-local knobs).
#
# ENV_KNOBS are operator-facing: the lint additionally requires each to
# be catalogued in scripts/env.sh and documented under docs/.
ENV_KNOBS = (
    # control-plane / placement
    "RAFIKI_ADMIN_HOST", "RAFIKI_ADMIN_PORT", "RAFIKI_PLACEMENT",
    "RAFIKI_AGENTS", "RAFIKI_AGENT_KEY", "RAFIKI_AGENT_INSECURE",
    "RAFIKI_AGENT_HOST", "RAFIKI_AGENT_PORT", "RAFIKI_AGENT_CHIPS",
    "RAFIKI_LOG_LEVEL",
    # data plane / serving
    "RAFIKI_BROKER", "RAFIKI_SHM_RING_BYTES", "RAFIKI_WIRE_BINARY",
    "RAFIKI_SERVE_INT8",
    # training / JAX backend
    "RAFIKI_COMPILE_CACHE_DIR", "RAFIKI_COMPILE_CACHE_CPU",
    "RAFIKI_COMPILE_CACHE", "RAFIKI_COMPILE_CACHE_MIN_COMPILE_S",
    "RAFIKI_COMPILE_WARM_THRESHOLD_S",
    "RAFIKI_TRAINER_CACHE_CAP", "RAFIKI_SCAN_EPOCH",
    "RAFIKI_SCAN_EPOCH_MAX_BYTES", "RAFIKI_FLASH_THRESHOLD_BYTES",
    "RAFIKI_NATIVE_CACHE", "RAFIKI_VISIBLE_DEVICES",
    "RAFIKI_BACKEND_PROBE_TIMEOUT_S", "RAFIKI_BACKEND_PROBE_LOCK",
    "RAFIKI_BACKEND_PROBE_STALE_S",
    # sandbox
    "RAFIKI_SANDBOX", "RAFIKI_SANDBOX_UID", "RAFIKI_SANDBOX_UID_BASE",
    "RAFIKI_SANDBOX_UID_RANGE", "RAFIKI_SANDBOX_GID",
    "RAFIKI_SANDBOX_KEEP_GID0", "RAFIKI_SANDBOX_MEM_MB",
    "RAFIKI_SANDBOX_NOFILE", "RAFIKI_SANDBOX_NETNS",
    "RAFIKI_SANDBOX_WIDEN_NONOWNED", "RAFIKI_TRIAL_STALL_S",
    # trials / advisor
    "RAFIKI_ADVISOR_RETRY_S", "RAFIKI_TRIAL_VMAP_K_WARN",
    "RAFIKI_INSTALL_DEPS", "RAFIKI_PIP_ARGS",
    # observability
    "RAFIKI_METRICS", "RAFIKI_METRICS_RING_S", "RAFIKI_TRACE_SAMPLE",
    "RAFIKI_TRACE_SLOW_MS", "RAFIKI_TRACE_EXEMPLAR_MAX_MB",
    "RAFIKI_PROFILE",
    # static analysis (this PR)
    "RAFIKI_VERIFY_TEMPLATES",
)

# ENV_INTERNAL are platform plumbing the placement layer writes into
# child-process environments (worker bootstrap contract) — declared so
# the lint knows them, exempt from the operator catalogs.
ENV_INTERNAL = (
    "RAFIKI_SERVICE_ID", "RAFIKI_ADMIN_ADDR", "RAFIKI_CHIP_GRANT",
    "RAFIKI_TRIAL_IDS", "RAFIKI_ORPHAN_SURVIVE",
)

# How long Admin.predict may reuse a resolved app->predictor route without
# re-reading the control-plane DB (serving hot path; see admin.predict).
PREDICT_ROUTE_TTL_S = _env_float("PREDICT_ROUTE_TTL_S", 5.0)

# Request-body ceiling on the dedicated predictor port: one absurd
# Content-Length must not allocate server memory (predictor/server.py
# refuses with 413 before reading).
PREDICT_MAX_BODY_MB = _env_float("PREDICT_MAX_BODY_MB", 64.0)

# Same guard on the admin REST door — higher default because model
# uploads legitimately carry template bytes (base64 in JSON).
ADMIN_MAX_BODY_MB = _env_float("ADMIN_MAX_BODY_MB", 256.0)
