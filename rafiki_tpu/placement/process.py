"""Process-level service placement: workers as child processes.

The reference deployed every dynamic worker as a Docker Swarm *container*
with env-var plumbing and a restart-on-failure policy (reference
rafiki/container/docker_swarm.py:122-148, scripts/start_worker.py:15-25).
`ProcessPlacementManager` is the TPU-host analogue: each service is a child
**process** launched on `python -m rafiki_tpu.worker.bootstrap` with

- its chip grant in ``RAFIKI_CHIP_GRANT`` (indices into jax.devices() — the
  analogue of ``CUDA_VISIBLE_DEVICES``, reference docker_swarm.py:122-126),
- its payload ids (`sub_train_job_id` / `inference_job_id`+`trial_id`) in
  env, the way the reference forwarded ``RAFIKI_SERVICE_ID`` etc.
  (reference services_manager.py:307-318),
- the metadata store reached by every process through the same SQLite/WAL
  file, and the serving data plane through the native shm queues
  (cache/shm_broker.py) — created owner-side here at placement time, so the
  child only ever attaches,
- HPO coordination through the admin REST API (advisor/remote.py), keeping
  the shared-GP semantics across *processes*.

Restart-on-failure parity: a child exiting non-zero while not being stopped
is relaunched up to ``max_restarts`` times (reference
container_manager.py:23-25); chips are released only when the child is
actually gone.

Status protocol: the child itself marks its service RUNNING (on ready) /
STOPPED / ERRORED in the store, like the reference's in-container bootstrap
(reference utils/service.py:10-46, 94-105). The monitor thread here is the
backstop for children that die without writing (SIGKILL, interpreter
crash).
"""

from __future__ import annotations

import logging
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional

from rafiki_tpu import config
from rafiki_tpu.constants import ServiceStatus, ServiceType
from rafiki_tpu.placement.manager import (
    ChipAllocator,
    InsufficientChipsError,
    PlacementManager,
    ServiceContext,
    StatusFn,
)

logger = logging.getLogger(__name__)

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


class _ProcRunner:
    def __init__(self, manager: "ProcessPlacementManager", ctx: ServiceContext,
                 env: Dict[str, str], log_path: str):
        self.manager = manager
        self.ctx = ctx
        self.env = env
        self.log_path = log_path
        self.proc: Optional[subprocess.Popen] = None
        self._proc_lock = threading.Lock()
        self.thread = threading.Thread(
            target=self._run, name=f"proc-svc-{ctx.service_id[:8]}",
            daemon=True)

    def _spawn(self) -> subprocess.Popen:
        os.makedirs(os.path.dirname(self.log_path), exist_ok=True)
        logf = open(self.log_path, "ab")
        try:
            proc = subprocess.Popen(
                [sys.executable, "-m", "rafiki_tpu.worker.bootstrap"],
                env=self.env, cwd=_REPO_ROOT,
                stdout=logf, stderr=subprocess.STDOUT,
                start_new_session=True,
            )
        finally:
            logf.close()  # the child holds its own fd now
        return proc

    def _run(self) -> None:
        mgr = self.manager
        try:
            restarts = 0
            rc: Optional[int] = None
            while not self.ctx.stop_event.is_set():
                with self._proc_lock:
                    self.proc = self._spawn()
                # record the child's pid so a restarted control plane can
                # adopt (or fence) it; refreshed on every restart
                if mgr.db is not None:
                    try:
                        mgr.db.update_service_pid(
                            self.ctx.service_id, self.proc.pid)
                    except Exception:
                        logger.exception("pid record failed for %s",
                                         self.ctx.service_id)
                rc = self._wait_current()
                if self.ctx.stop_event.is_set() or rc == 0:
                    break
                logger.error(
                    "service %s process exited rc=%s (log: %s)",
                    self.ctx.service_id, rc, self.log_path)
                restarts += 1
                if restarts > mgr.max_restarts:
                    self._report_final(ServiceStatus.ERRORED)
                    return
            self._report_final(
                ServiceStatus.STOPPED if (rc == 0 or rc is None)
                else ServiceStatus.ERRORED)
        finally:
            self.manager._on_runner_exit(self.ctx)

    def _wait_current(self) -> Optional[int]:
        with self._proc_lock:
            proc = self.proc
        if proc is None:
            return None
        while True:
            try:
                return proc.wait(timeout=0.5)
            except subprocess.TimeoutExpired:
                if self.ctx.stop_event.is_set():
                    return self._terminate(proc)

    def _terminate(self, proc: subprocess.Popen) -> Optional[int]:
        """SIGTERM -> child marks its own status and exits; SIGKILL after
        the grace period."""
        try:
            proc.terminate()
        except ProcessLookupError:
            return proc.poll()
        try:
            return proc.wait(timeout=self.manager.stop_grace_s)
        except subprocess.TimeoutExpired:
            logger.warning("service %s ignored SIGTERM; killing",
                           self.ctx.service_id)
            try:
                proc.kill()
            except ProcessLookupError:
                pass
            return proc.wait(timeout=5)

    def _report_final(self, status_from_rc: str) -> None:
        """Report the service's terminal status through on_status — ALWAYS,
        even when the child already wrote its own row: the orchestration
        side-effects (refresh_train_job_status etc.) live behind the
        callback, and in process mode nobody else fires them after the last
        worker exits. The child's self-written status wins over the
        rc-derived one (it knows stop-vs-crash better than the exit code)."""
        mgr = self.manager
        final = status_from_rc
        try:
            if mgr.db is not None:
                svc = mgr.db.get_service(self.ctx.service_id)
                if svc is not None and svc["status"] in (
                        ServiceStatus.STOPPED, ServiceStatus.ERRORED):
                    final = svc["status"]
            if mgr.on_status:
                mgr.on_status(self.ctx.service_id, final)
        except Exception:
            logger.exception("final status report failed for %s",
                             self.ctx.service_id)


def _pid_is_worker(pid: Optional[int],
                   service_id: Optional[str] = None) -> bool:
    """Is ``pid`` an alive rafiki worker bootstrap — and, when
    ``service_id`` is given, THE bootstrap of that exact service? Guards
    against pid reuse two ways: the cmdline must be a worker bootstrap,
    and the child's environment must carry the matching
    ``RAFIKI_SERVICE_ID`` (a recycled pid belonging to a *different*
    service's worker must never be adopted or signalled)."""
    if not pid:
        return False
    try:
        os.kill(pid, 0)
    except (OSError, ProcessLookupError):
        return False
    try:
        with open(f"/proc/{pid}/cmdline", "rb") as f:
            if b"rafiki_tpu.worker.bootstrap" not in f.read():
                return False
        if service_id is not None:
            with open(f"/proc/{pid}/environ", "rb") as f:
                env_blob = f.read()
            return (b"RAFIKI_SERVICE_ID=" + service_id.encode()
                    ) in env_blob.split(b"\0")
        return True
    except OSError:
        # no /proc (or unreadable): cannot verify — treat as not ours
        return False


def terminate_worker_pid(pid: int, service_id: str,
                         grace_s: float) -> None:
    """Identity-pinned kill escalation for a non-child worker process:
    SIGTERM, bounded wait for exit, then SIGKILL — re-verifying
    `_pid_is_worker(pid, service_id)` before EVERY signal so a recycled
    pid is never touched. ``grace_s <= 0`` means fire-and-forget SIGTERM
    (no SIGKILL escalation: the child deserves its clean store write).
    The single copy of this escalation; the adopted-child watcher and
    the recovery fence both use it."""
    if not _pid_is_worker(pid, service_id=service_id):
        return
    try:
        os.kill(pid, signal.SIGTERM)
    except (OSError, ProcessLookupError):
        return
    if grace_s <= 0:
        return
    deadline = time.monotonic() + grace_s
    while time.monotonic() < deadline:
        if not _pid_is_worker(pid, service_id=service_id):
            return
        time.sleep(0.1)
    if _pid_is_worker(pid, service_id=service_id):
        logger.warning("worker %s (pid %d) ignored SIGTERM; killing",
                       service_id[:8], pid)
        try:
            os.kill(pid, signal.SIGKILL)
        except (OSError, ProcessLookupError):
            pass


class _AdoptedRunner:
    """Watcher over a child that SURVIVED a control-plane restart (the
    bootstrap's start_new_session keeps workers alive when the admin
    dies). Mirrors _ProcRunner's contract — stop_event -> SIGTERM ->
    SIGKILL, terminal status reported through on_status (the child's
    self-written DB row wins) — without owning a Popen handle."""

    def __init__(self, manager: "ProcessPlacementManager",
                 ctx: ServiceContext, pid: int):
        self.manager = manager
        self.ctx = ctx
        self.pid = pid
        self.proc = None  # list_services reads .proc on spawned runners
        self.thread = threading.Thread(
            target=self._run, name=f"adopted-svc-{ctx.service_id[:8]}",
            daemon=True)

    def _alive(self) -> bool:
        # identity-verified, not just kill(pid, 0): this runner cannot
        # reap its non-child, so the pid CAN be recycled under it — a
        # recycled pid (different process) must read as "our worker is
        # gone", and must never be signalled
        return _pid_is_worker(self.pid, service_id=self.ctx.service_id)

    def _run(self) -> None:
        mgr = self.manager
        try:
            while self._alive():
                if self.ctx.stop_event.wait(0.5):
                    self._terminate()
                    break
            # the child writes its own terminal row; rc is unknowable
            # here, so default to STOPPED and let the row override
            self._report_final()
        finally:
            mgr._on_runner_exit(self.ctx)

    def _terminate(self) -> None:
        terminate_worker_pid(self.pid, self.ctx.service_id,
                             self.manager.stop_grace_s)

    def _report_final(self) -> None:
        mgr = self.manager
        final = ServiceStatus.STOPPED
        try:
            if mgr.db is not None:
                svc = mgr.db.get_service(self.ctx.service_id)
                if svc is not None and svc["status"] in (
                        ServiceStatus.STOPPED, ServiceStatus.ERRORED):
                    final = svc["status"]
                elif not self.ctx.stop_event.is_set():
                    # died on its own without writing (SIGKILL): backstop
                    final = ServiceStatus.ERRORED
            if mgr.on_status:
                mgr.on_status(self.ctx.service_id, final)
        except Exception:
            logger.exception("final status report failed for adopted %s",
                             self.ctx.service_id)


class ProcessPlacementManager(PlacementManager):
    """Places services as child processes on this host.

    Requirements: a file-backed store (``db.path`` != ':memory:') shared via
    SQLite WAL, and for serving, a `ShmBroker` whose segments the children
    attach to. ``admin_addr`` (host, port) of a running AdminServer enables
    cross-process HPO coordination; without it train workers fall back to a
    process-local advisor (the reference's uncoordinated-parallel-HPO
    behavior) with a warning.
    """

    def __init__(
        self,
        db=None,
        broker=None,
        admin_addr: Optional[tuple] = None,
        allocator: Optional[ChipAllocator] = None,
        on_status: Optional[StatusFn] = None,
        max_restarts: int = 3,
        stop_grace_s: float = 15.0,
        orphan_survivable: bool = False,
    ):
        """``orphan_survivable``: set by an ADMIN-embedded engine (single-
        host process placement) so its TRAIN children outlive a control-
        plane crash and can be adopted by pid on restart (the orphan
        watchdog then exits on a terminal store row instead of on
        reparenting — worker/bootstrap.py). Agent-embedded engines keep
        the default: an agent's death is a HOST failure, and its children
        must die fast so the PR-1 reschedule never double-runs a service
        id."""
        self.db = db
        self.broker = broker
        self.admin_addr = admin_addr
        self.allocator = allocator or ChipAllocator()
        self.on_status = on_status
        self.max_restarts = max_restarts
        self.stop_grace_s = stop_grace_s
        self.orphan_survivable = orphan_survivable
        self._lock = threading.Lock()
        self._runners: Dict[str, _ProcRunner] = {}
        # runners detached by destroy_service(wait=False) whose children
        # may still be in the SIGTERM->SIGKILL grace window; stop_all()
        # must wait these out — otherwise an exiting admin kills its own
        # daemon monitor threads mid-escalation and orphans a child that
        # ignored SIGTERM (e.g. one stuck inside a long XLA dispatch)
        self._dying: List[_ProcRunner] = []

    # -- PlacementManager --------------------------------------------------

    def create_service(
        self,
        service_id: str,
        service_type: str,
        run_fn=None,  # declarative launch: the payload travels in `extra`
        n_chips: int = 0,
        extra: Optional[Dict[str, Any]] = None,
        best_effort_chips: bool = False,
    ) -> ServiceContext:
        if self.db is None or self.db.path == ":memory:":
            raise RuntimeError(
                "ProcessPlacementManager needs a file-backed Database "
                "(children open the same SQLite/WAL file)")
        extra = dict(extra or {})
        try:
            chips = self.allocator.allocate(n_chips) if n_chips > 0 else []
        except InsufficientChipsError:
            if not best_effort_chips:
                raise
            chips = []
        ctx = ServiceContext(
            service_id=service_id,
            service_type=service_type,
            chips=chips,
            stop_event=threading.Event(),
            extra=extra,
        )
        try:
            env = self._child_env(ctx)
        except Exception:
            self.allocator.release(chips)
            raise
        if service_type == ServiceType.INFERENCE and self.broker is not None:
            # owner-side data-plane provisioning: create the query segment
            # now so the child (and the predictor fan-out) can attach
            self.broker.register_worker(extra["inference_job_id"], service_id)
        log_path = os.path.join(
            config.LOGS_DIR, f"service-{service_id}.log")
        runner = _ProcRunner(self, ctx, env, log_path)
        with self._lock:
            self._runners[service_id] = runner
        runner.thread.start()
        return ctx

    def adopt_pid(self, service_id: str, service_type: str, pid: int,
                  extra: Optional[Dict[str, Any]] = None,
                  chips: Optional[List[int]] = None) -> bool:
        """Adopt a worker child that survived a control-plane restart
        (its service row carries the pid): verify it is alive AND one of
        ours, reclaim its chip grant, and watch it exactly like a spawned
        child — destroy_service/stop_all SIGTERM it, its exit fires
        on_status with the row it wrote itself. Returns False when the
        pid is gone or unverifiable (caller respawns or errors)."""
        if not _pid_is_worker(pid, service_id=service_id):
            return False
        chips = list(chips or [])
        self.allocator.claim(chips)
        ctx = ServiceContext(
            service_id=service_id,
            service_type=service_type,
            chips=chips,
            stop_event=threading.Event(),
            extra=dict(extra or {}),
        )
        runner = _AdoptedRunner(self, ctx, pid)
        with self._lock:
            self._runners[service_id] = runner
        runner.thread.start()
        logger.info("adopted surviving worker %s (pid %d)",
                    service_id[:8], pid)
        return True

    def list_services(self) -> List[Dict[str, Any]]:
        """This host's LIVE executors, for the restart-reconciliation
        inventory (placement/agent.py GET /inventory). Finished runners
        already wrote their terminal rows and are not running-set."""
        with self._lock:
            runners = dict(self._runners)
        out = []
        for sid, r in runners.items():
            if not r.thread.is_alive():
                continue
            proc = getattr(r, "proc", None)
            out.append({
                "service_id": sid,
                "service_type": r.ctx.service_type,
                "status": "RUNNING",
                "chips": list(r.ctx.chips),
                "pid": (proc.pid if proc is not None
                        else getattr(r, "pid", None)),
            })
        return out

    def destroy_service(self, service_id: str, wait: bool = True) -> None:
        with self._lock:
            runner = self._runners.pop(service_id, None)
            # only track runners whose monitor thread still runs: appending
            # an already-finished runner would leak it (its _on_runner_exit
            # has already fired and won't prune it again)
            if runner is not None and runner.thread.is_alive():
                self._dying.append(runner)
        if runner is None:
            return  # tolerate concurrent deletion
        runner.ctx.stop_event.set()
        if wait:
            runner.thread.join(timeout=self.stop_grace_s + 10)
        if (self.broker is not None
                and runner.ctx.service_type == ServiceType.INFERENCE):
            job_id = runner.ctx.extra.get("inference_job_id")
            if job_id:
                try:
                    self.broker.unregister_worker(job_id, service_id)
                except Exception:
                    logger.exception("broker unregister failed for %s",
                                     service_id)

    def stop_all(self) -> None:
        with self._lock:
            ids = list(self._runners)
        for sid in ids:
            self.destroy_service(sid)
        # reap runners detached earlier with wait=False: their monitor
        # threads may still be escalating SIGTERM->SIGKILL, and the caller
        # (admin shutdown) exits right after this returns
        with self._lock:
            dying = list(self._dying)
        for runner in dying:
            runner.thread.join(timeout=self.stop_grace_s + 10)
        with self._lock:
            # sweep entries whose exit raced the is_alive() append guard
            self._dying = [r for r in self._dying if r.thread.is_alive()]

    # -- internals ---------------------------------------------------------

    def _on_runner_exit(self, ctx: ServiceContext) -> None:
        self.allocator.release(ctx.chips)
        with self._lock:
            self._dying = [r for r in self._dying
                           if r.ctx.service_id != ctx.service_id]

    def _child_env(self, ctx: ServiceContext) -> Dict[str, str]:
        env = dict(os.environ)
        env["PYTHONPATH"] = os.pathsep.join(
            p for p in (_REPO_ROOT, env.get("PYTHONPATH")) if p)
        env["RAFIKI_SERVICE_ID"] = ctx.service_id
        env["RAFIKI_SERVICE_TYPE"] = ctx.service_type
        # the store may be a postgresql:// URL (multi-host control plane);
        # only filesystem paths get absolutized
        db_ref = self.db.path
        env["RAFIKI_DB_PATH"] = (
            db_ref if "://" in db_ref else os.path.abspath(db_ref))
        env["RAFIKI_WORKDIR"] = config.WORKDIR
        env["RAFIKI_CHIP_GRANT"] = ",".join(str(c) for c in ctx.chips)
        # the process-wide fallback must not fight the explicit grant
        env.pop("RAFIKI_VISIBLE_DEVICES", None)
        if self.admin_addr is not None:
            env["RAFIKI_ADMIN_ADDR"] = f"{self.admin_addr[0]}:{self.admin_addr[1]}"
        if ctx.service_type == ServiceType.TRAIN:
            env["RAFIKI_SUB_TRAIN_JOB_ID"] = ctx.extra["sub_train_job_id"]
            if self.orphan_survivable:
                # control-plane crash recovery: this TRAIN child should
                # outlive its admin parent and be adopted by pid on
                # restart (INFERENCE children never survive — their shm
                # data plane dies with the parent)
                env["RAFIKI_ORPHAN_SURVIVE"] = "1"
        elif ctx.service_type == ServiceType.INFERENCE:
            env["RAFIKI_INFERENCE_JOB_ID"] = ctx.extra["inference_job_id"]
            env["RAFIKI_TRIAL_ID"] = ctx.extra["trial_id"]
            if ctx.extra.get("trial_ids"):
                # fused ensemble group (budget ENSEMBLE_FUSED)
                env["RAFIKI_TRIAL_IDS"] = ",".join(ctx.extra["trial_ids"])
            # a broker without an shm namespace reports prefix=None
            # (e.g. FleetBroker over the in-process broker) — treat it
            # the same as no broker at all, with an explicit error
            prefix = getattr(self.broker, "prefix", None)
            if prefix is None:
                raise RuntimeError(
                    "process-mode inference needs the shm broker "
                    "(RAFIKI_BROKER=shm) so worker processes can attach "
                    "to the serving data plane")
            env["RAFIKI_BROKER_PREFIX"] = prefix
        else:
            raise ValueError(
                f"unsupported process service type {ctx.service_type!r}")
        return env
