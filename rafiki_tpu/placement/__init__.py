"""Placement layer (L4): chip-affine executor placement on TPU VM hosts
(replaces the reference's Docker Swarm container manager,
reference rafiki/container/)."""

from rafiki_tpu.placement.manager import (  # noqa: F401
    ChipAllocator,
    InsufficientChipsError,
    LocalPlacementManager,
    PlacementManager,
    ServiceContext,
)
