"""Chip-affine service placement.

The reference deploys every dynamic worker as a Docker Swarm service pinned to
a node with free GPUs, tracked via node labels, and passes
``CUDA_VISIBLE_DEVICES`` (reference rafiki/container/docker_swarm.py:53-70,
99-172). A TPU host can't be time-sliced that way — chips are exclusive to a
process — so the TPU-native equivalent is an in-process *executor* model:

- ``ChipAllocator`` owns the host's device inventory (indices into
  ``jax.devices()``) — the analogue of the ``available_gpus`` node label;
- services are Python entrypoints run on daemon threads with an explicit
  *chip grant*; executors build their ``Mesh`` from exactly the granted
  devices (see rafiki_tpu.parallel.mesh), so concurrent trials occupy
  disjoint sub-slices of the host's mesh;
- the restart-on-failure contract of the reference's container layer
  (reference container_manager.py:23-25) is kept: a crashing service is
  relaunched up to ``max_restarts`` times.

``PlacementManager`` is the ABC seam (reference container_manager.py:14) so a
multi-host TPU-VM manager can replace the local one without touching the
orchestration core.
"""

from __future__ import annotations

import abc
import logging
import threading
import traceback
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

logger = logging.getLogger(__name__)


class InsufficientChipsError(Exception):
    pass


class ChipAllocator:
    """Per-host chip bookkeeping (analogue of the reference's
    `available_gpus`/`num_services` node labels,
    reference docker_swarm.py:153-169)."""

    def __init__(self, device_indices: Optional[List[int]] = None):
        if device_indices is None:
            import jax

            from rafiki_tpu.parallel.mesh import visible_devices

            all_devs = jax.devices()
            device_indices = [all_devs.index(d) for d in visible_devices()]
        self._lock = threading.Lock()
        self._free: List[int] = list(device_indices)
        self._total = list(device_indices)

    @property
    def total_chips(self) -> int:
        return len(self._total)

    @property
    def free_chips(self) -> int:
        with self._lock:
            return len(self._free)

    def allocate(self, n: int) -> List[int]:
        with self._lock:
            if n > len(self._free):
                raise InsufficientChipsError(
                    f"Requested {n} chips, only {len(self._free)} free"
                )
            grant, self._free = self._free[:n], self._free[n:]
            return grant

    def release(self, chips: List[int]) -> None:
        with self._lock:
            for c in chips:
                if c in self._total and c not in self._free:
                    self._free.append(c)
            self._free.sort()

    def claim(self, chips: List[int]) -> None:
        """Mark SPECIFIC chip indices busy (control-plane recovery: an
        adopted worker already holds its grant — the fresh allocator must
        not hand those chips to anyone else). Indices not in this host's
        inventory, or already busy, are ignored."""
        with self._lock:
            self._free = [c for c in self._free if c not in set(chips)]


@dataclass
class ServiceContext:
    """Handed to a service entrypoint: identity, chip grant, stop signal."""

    service_id: str
    service_type: str
    chips: List[int]
    stop_event: threading.Event
    extra: Dict[str, Any] = field(default_factory=dict)
    on_ready: Optional[Callable[[], None]] = None

    @property
    def stopping(self) -> bool:
        return self.stop_event.is_set()

    def ready(self) -> None:
        """Services call this once initialized (model loaded, job info read)
        — only then is the service reported RUNNING, so the deploy-time wait
        and rollback actually gate on successful startup."""
        if self.on_ready:
            self.on_ready()

    def devices(self) -> List[Any]:
        """The granted jax devices (all visible devices if the grant is
        empty — the CPU-fallback analogue of the reference's no-GPU path)."""
        import jax

        from rafiki_tpu.parallel.mesh import visible_devices

        if not self.chips:
            return visible_devices()
        all_devs = jax.devices()
        return [all_devs[i] for i in self.chips]


RunFn = Callable[[ServiceContext], None]
StatusFn = Callable[[str, str], None]  # (service_id, status)


class PlacementManager(abc.ABC):
    """ABC seam for service deployment (reference container_manager.py:14-46)."""

    @abc.abstractmethod
    def create_service(
        self,
        service_id: str,
        service_type: str,
        run_fn: RunFn,
        n_chips: int = 0,
        extra: Optional[Dict[str, Any]] = None,
    ) -> ServiceContext:
        ...

    @abc.abstractmethod
    def destroy_service(self, service_id: str, wait: bool = True) -> None:
        ...


class _ServiceRunner:
    def __init__(
        self,
        ctx: ServiceContext,
        run_fn: RunFn,
        on_status: Optional[StatusFn],
        max_restarts: int,
        on_exit: Optional[Callable[[], None]] = None,
    ):
        self.ctx = ctx
        self.run_fn = run_fn
        self.on_status = on_status
        self.max_restarts = max_restarts
        self.on_exit = on_exit
        ctx.on_ready = lambda: self._status("RUNNING")
        self.thread = threading.Thread(
            target=self._run, name=f"svc-{ctx.service_id[:8]}", daemon=True
        )

    def _status(self, status: str) -> None:
        if self.on_status:
            try:
                self.on_status(self.ctx.service_id, status)
            except Exception:
                logger.exception("status callback failed")

    def _run(self) -> None:
        # RUNNING is reported by ctx.ready() from inside run_fn, after the
        # service has actually initialized — a run_fn that crashes on startup
        # lands ERRORED without ever having claimed to run
        try:
            restarts = 0
            while not self.ctx.stop_event.is_set():
                try:
                    self.run_fn(self.ctx)
                    break  # clean exit
                except Exception:
                    logger.error(
                        "service %s crashed:\n%s",
                        self.ctx.service_id,
                        traceback.format_exc(),
                    )
                    restarts += 1
                    if restarts > self.max_restarts:
                        self._status("ERRORED")
                        return
                    # restart-on-failure, like the swarm restart policy
            self._status("STOPPED")
        finally:
            # chips are released here — only once the thread has actually
            # stopped touching its granted devices, whatever the exit path
            # (clean, stopped, or errored past max_restarts)
            if self.on_exit:
                self.on_exit()


class LocalPlacementManager(PlacementManager):
    """Runs services as daemon threads on this host with chip grants."""

    def __init__(
        self,
        allocator: Optional[ChipAllocator] = None,
        on_status: Optional[StatusFn] = None,
        max_restarts: int = 3,
    ):
        self.allocator = allocator or ChipAllocator()
        self.on_status = on_status
        self.max_restarts = max_restarts
        self._lock = threading.Lock()
        self._runners: Dict[str, _ServiceRunner] = {}

    def create_service(
        self,
        service_id: str,
        service_type: str,
        run_fn: RunFn,
        n_chips: int = 0,
        extra: Optional[Dict[str, Any]] = None,
        best_effort_chips: bool = False,
    ) -> ServiceContext:
        """Deploy a service. With ``best_effort_chips``, a grant that can't be
        satisfied falls back to no exclusive grant (shared devices) instead of
        failing — used for serving executors that should prefer, but not
        require, their own chip."""
        try:
            chips = self.allocator.allocate(n_chips) if n_chips > 0 else []
        except InsufficientChipsError:
            if not best_effort_chips:
                raise
            chips = []
        ctx = ServiceContext(
            service_id=service_id,
            service_type=service_type,
            chips=chips,
            stop_event=threading.Event(),
            extra=extra or {},
        )
        runner = _ServiceRunner(
            ctx,
            run_fn,
            self.on_status,
            self.max_restarts,
            on_exit=lambda: self.allocator.release(ctx.chips),
        )
        with self._lock:
            self._runners[service_id] = runner
        runner.thread.start()
        return ctx

    def destroy_service(self, service_id: str, wait: bool = True) -> None:
        with self._lock:
            runner = self._runners.pop(service_id, None)
        if runner is None:
            return  # tolerate concurrent deletion (reference
            # services_manager.py:274-277 logged and moved on)
        runner.ctx.stop_event.set()
        if wait:
            runner.thread.join(timeout=30)
        # chip release happens in the runner's exit hook, once the thread is
        # actually off the devices

    def list_services(self) -> List[Dict[str, Any]]:
        """Enumerate this host's LIVE executors — the inventory a
        restarted control plane reconciles the store against
        (placement/agent.py GET /inventory; docs/failure-model.md
        "Control-plane faults"). Finished runners (their terminal rows
        are already in the store) are not part of the running-set."""
        with self._lock:
            runners = dict(self._runners)
        return [
            {
                "service_id": sid,
                "service_type": r.ctx.service_type,
                "status": "RUNNING",
                "chips": list(r.ctx.chips),
                # inventory schema parity with the process engine: thread
                # executors have no pid of their own
                "pid": None,
            }
            for sid, r in runners.items()
            if r.thread.is_alive()
        ]

    def stop_all(self) -> None:
        with self._lock:
            ids = list(self._runners)
        for sid in ids:
            self.destroy_service(sid)
