"""Per-host placement agent: `python -m rafiki_tpu.placement.agent`.

The multi-host analogue of the reference's per-node Docker Engine: the
reference's admin drove a Swarm that placed containers onto nodes by their
``available_gpus``/``num_services`` labels (reference
rafiki/container/docker_swarm.py:53-90, 99-172). Here each TPU-VM host runs
ONE agent process that owns the host's chip inventory and launches worker
*processes* with chip grants through the local ProcessPlacementManager
(placement/process.py) — the same engine the single-host deployment uses,
now standing behind a small HTTP API the admin's
HostAgentPlacementManager (placement/hosts.py) drives:

    GET  /healthz              liveness
    GET  /inventory            {host, total_chips, free_chips, n_services,
                                services: [{service_id, service_type,
                                status, chips, pid}]} — the running-set a
                                restarted admin reconciles against
    POST /services             {service_id, service_type, n_chips,
                                best_effort_chips, extra} -> {chips}
    POST /services/<id>/stop   {wait} -> {}
    POST /predict_relay/<job>/<worker>   {queries} -> {predictions}

Config via env:

    RAFIKI_AGENT_HOST / RAFIKI_AGENT_PORT   bind address (default 127.0.0.1:0)
    RAFIKI_AGENT_CHIPS                      comma-sep device indices this
                                            host contributes (default: all)
    RAFIKI_AGENT_KEY                        shared secret, REQUIRED: requests
                                            must carry X-Rafiki-Agent-Key
                                            (scripts/start_agent.sh generates
                                            one); RAFIKI_AGENT_INSECURE=1 is
                                            the explicit keyless opt-out
    RAFIKI_DB_PATH                          the shared metadata store (the
                                            reference assumed a shared FS /
                                            NFS the same way,
                                            docs architecture.rst:60-64)
    RAFIKI_WORKDIR                          data/params/logs root
    RAFIKI_ADMIN_ADDR                       host:port of the AdminServer for
                                            HPO coordination + status events

Serving across hosts (the reference placed inference workers on any swarm
node, reference rafiki/admin/services_manager.py:204-239): agents place
INFERENCE executors too. The shm data plane stays host-local — the agent
process owns the segments its inference workers attach to — and the
admin-side predictor reaches them through this server's
``/predict_relay`` route, which submits a whole relayed batch to the
worker's local queue and answers when the worker resolves it
(cache/fleet.py holds the admin-side half). PREDICT itself never leaves
the admin process.
"""

from __future__ import annotations

import json
import logging
import os
import re
import signal
import socket
import sys
import threading
from http.server import BaseHTTPRequestHandler
from typing import Any, Dict, Optional

from rafiki_tpu.cache import wire
from rafiki_tpu.constants import ServiceType
from rafiki_tpu.placement.manager import ChipAllocator, InsufficientChipsError
from rafiki_tpu.placement.process import ProcessPlacementManager
from rafiki_tpu.utils import chaos
from rafiki_tpu.utils.agent_http import ADMIN_EPOCH_HEADER, STALE_EPOCH_STATUS
from rafiki_tpu.utils.jsonutil import json_default
from rafiki_tpu.utils.reqfields import LowLatencyHandler, SeveringHTTPServer

logger = logging.getLogger(__name__)

_SERVICE_STOP = re.compile(r"^/services/(?P<sid>[^/]+)/stop$")
_PREDICT_RELAY = re.compile(
    r"^/predict_relay/(?P<job>[^/]+)/(?P<wid>[^/]+)$")


class AgentServer:
    """HTTP facade over a host-local ProcessPlacementManager."""

    def __init__(self, engine: ProcessPlacementManager,
                 host: str = "127.0.0.1", port: int = 0,
                 key: Optional[str] = None,
                 allow_insecure: bool = False):
        self.engine = engine
        self.host = host
        self.port = port
        self.key = key
        # Secure by default (verdict r4: an open fleet plane let any
        # network peer create services / relay predictions — the
        # reference's analogue boundary was the swarm overlay network,
        # reference rafiki/container/docker_swarm.py:128-148). Keyless
        # operation must be requested EXPLICITLY (RAFIKI_AGENT_INSECURE=1).
        self.allow_insecure = allow_insecure
        self.hostname = socket.gethostname()
        self._httpd: Optional[SeveringHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        # control-plane HA epoch fence (docs/failure-model.md
        # "Control-plane HA"): the highest admin leadership epoch this
        # agent has seen. Any authenticated call carrying the epoch
        # header ratchets it up; mutating calls from a LOWER epoch — a
        # paused/partitioned ex-leader that resumed — are refused typed
        # (STALE_EPOCH_STATUS), so a stale admin can never double-place
        # or tear down a service on this host.
        self._epoch_lock = threading.Lock()
        self._admin_epoch = 0  # guarded-by: _epoch_lock

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "AgentServer":
        server = self

        class Handler(LowLatencyHandler):
            def do_GET(self):
                server._dispatch(self, "GET")

            def do_POST(self):
                server._dispatch(self, "POST")

        self._httpd = SeveringHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
            # a stopped agent must go dark like a killed host: sever
            # established keep-alive connections, don't keep answering
            # the admin's pooled sessions from orphaned handler threads
            self._httpd.sever()
        self.engine.stop_all()

    # -- request handling --------------------------------------------------

    def _dispatch(self, handler: BaseHTTPRequestHandler, method: str) -> None:
        try:
            path = handler.path.split("?", 1)[0].rstrip("/")
            rule = chaos.hit(chaos.SITE_AGENT, path)
            if rule is not None:
                # deterministic fault injection (RAFIKI_CHAOS): lets tier-1
                # tests watch this agent "die" or stall on schedule
                if rule.action == chaos.ACTION_DROP:
                    handler.close_connection = True
                    return  # no response: callers see a transport error
                if rule.action == chaos.ACTION_ERROR:
                    # the request body is unread; keep-alive framing
                    # would desync, so the conn dies with the response
                    # (what a genuinely faulting agent does anyway)
                    handler.close_connection = True
                    return self._respond(handler, rule.code,
                                         {"error": "chaos-injected error"})
                chaos.sleep_for(rule)
            # the body is read BEFORE any refusal (bad key, stale epoch)
            # can answer: an early response over HTTP/1.1 keep-alive with
            # the body still buffered desyncs the connection — the
            # admin's pooled session would parse leftover bytes as the
            # next request line. Decode stays below; refused requests
            # only pay the (bounded) read.
            from rafiki_tpu import config as _config
            from rafiki_tpu.utils.reqfields import read_bounded_body

            raw, berr = read_bounded_body(
                handler, _config.PREDICT_MAX_BODY_MB)
            if berr:
                return self._respond(handler, berr[0], {"error": berr[1]})
            if method == "GET" and path == "/healthz":
                # liveness stays unauthenticated (monitors/doctor probes).
                # wire_versions advertises the binary codec versions this
                # agent decodes — the admin-side relay (cache/fleet.py)
                # probes it once before shipping binary frames, so an old
                # agent keeps receiving JSON
                with self._epoch_lock:
                    seen_epoch = self._admin_epoch
                return self._respond(handler, 200, {
                    "host": self.hostname, "status": "ok",
                    # the fence state, for the doctor's epoch-skew check
                    "admin_epoch": seen_epoch,
                    "wire_versions": sorted(wire.SUPPORTED_VERSIONS)})
            if method == "GET" and path == "/metrics":
                # Prometheus exposition stays unauthenticated like
                # /healthz: counters/gauges only, standard scraper
                # contract (utils/metrics.py holds the one copy of the
                # response path shared by all three doors)
                from rafiki_tpu.utils.metrics import serve_http

                serve_http(handler,
                           (handler.path.split("?", 1) + [""])[1])
                return
            if self.key:
                import hmac

                provided = handler.headers.get("X-Rafiki-Agent-Key") or ""
                if not hmac.compare_digest(provided, self.key):
                    return self._respond(handler, 401,
                                         {"error": "bad agent key"})
            elif not self.allow_insecure:
                return self._respond(handler, 403, {
                    "error": "agent has no key configured and "
                             "RAFIKI_AGENT_INSECURE=1 was not set — "
                             "refusing all placement/relay requests"})
            # epoch fence (after auth, so only keyed admins can ratchet).
            # Placement mutations (/services, /services/<id>/stop) from a
            # lower epoch than the highest seen are refused typed; once an
            # epoch has been seen, an epoch-LESS mutation is refused too —
            # in an HA fleet "no epoch" is indistinguishable from "older
            # than every epoch". Data-plane relays stay unfenced: an
            # ex-leader's predictor finishing in-flight reads must not
            # fail client requests.
            call_epoch: Optional[int] = None
            epoch_hdr = handler.headers.get(ADMIN_EPOCH_HEADER)
            if epoch_hdr is not None:
                try:
                    call_epoch = int(epoch_hdr)
                except ValueError:
                    return self._respond(handler, 400, {
                        "error": "malformed admin epoch header"})
            with self._epoch_lock:
                if call_epoch is not None and call_epoch > self._admin_epoch:
                    self._admin_epoch = call_epoch
                seen_epoch = self._admin_epoch
            mutating = method == "POST" and (
                path == "/services" or _SERVICE_STOP.match(path) is not None)
            if (mutating and seen_epoch > 0
                    and (call_epoch is None or call_epoch < seen_epoch)):
                return self._respond(handler, STALE_EPOCH_STATUS, {
                    "error": f"stale admin epoch "
                             f"{call_epoch if call_epoch is not None else 0}"
                             f" < {seen_epoch}: a newer admin holds the "
                             "leadership lease; refusing mutation",
                    "admin_epoch": seen_epoch})
            body: Dict[str, Any] = {}
            binary_req = False
            if raw:
                ctype = ((handler.headers.get("Content-Type") or "")
                         .split(";")[0].strip().lower())
                if ctype == wire.CONTENT_TYPE or wire.is_frame(raw):
                    # binary wire frame (cache/wire.py): ndarrays decode
                    # as zero-copy views; the response answers in kind
                    try:
                        body = wire.decode(raw)
                    except wire.WireFormatError as e:
                        return self._respond(handler, 400, {
                            "error": f"bad wire frame: {e}"})
                    if not isinstance(body, dict):
                        return self._respond(handler, 400, {
                            "error": "wire frame body must be an object"})
                    binary_req = True
                else:
                    body = json.loads(raw or b"{}")

            if method == "GET" and path == "/inventory":
                alloc = self.engine.allocator
                # `services` enumerates what is ACTUALLY running on this
                # host — the ground truth a restarted admin reconciles
                # the metadata store against (adopt / reschedule / fence;
                # docs/failure-model.md "Control-plane faults")
                list_fn = getattr(self.engine, "list_services", None)
                return self._respond(handler, 200, {
                    "host": self.hostname,
                    "total_chips": alloc.total_chips,
                    "free_chips": alloc.free_chips,
                    "n_services": len(self.engine._runners),
                    "admin_epoch": seen_epoch,
                    "services": list_fn() if callable(list_fn) else [],
                })
            if method == "POST" and path == "/services":
                stype = body.get("service_type")
                if stype not in (ServiceType.TRAIN, ServiceType.INFERENCE):
                    return self._respond(handler, 400, {
                        "error": f"agents place TRAIN/INFERENCE services, "
                                 f"not {stype!r} (PREDICT runs in the "
                                 f"admin process)"})
                if (stype == ServiceType.INFERENCE
                        and self.engine.broker is None):
                    return self._respond(handler, 503, {
                        "error": "this agent has no serving data plane "
                                 "(native shm broker unavailable)"})
                try:
                    ctx = self.engine.create_service(
                        body["service_id"], body["service_type"],
                        n_chips=int(body.get("n_chips", 0)),
                        best_effort_chips=bool(body.get("best_effort_chips")),
                        extra=body.get("extra") or {},
                    )
                except InsufficientChipsError as e:
                    return self._respond(handler, 503, {"error": str(e)})
                return self._respond(handler, 200, {"chips": ctx.chips})
            m = _SERVICE_STOP.match(path) if method == "POST" else None
            if m:
                self.engine.destroy_service(
                    m.group("sid"), wait=bool(body.get("wait", False)))
                return self._respond(handler, 200, {})
            m = _PREDICT_RELAY.match(path) if method == "POST" else None
            if m:
                return self._predict_relay(
                    handler, m.group("job"), m.group("wid"), body,
                    binary=binary_req)
            self._respond(handler, 404, {"error": f"no route {method} {path}"})
        except Exception:
            # traceback stays in the agent log; the wire gets a generic
            # 500 (FWK402: internal text never leaves the door)
            logger.exception("agent request failed")
            self._respond(handler, 500, {"error": "internal agent error"})

    def _predict_relay(self, handler, job_id: str, worker_id: str,
                       body: Dict[str, Any], binary: bool = False) -> None:
        """Data-plane hop for a remote predictor (cache/fleet.py): submit
        the relayed batch to the named worker's host-local queue and
        answer when the worker resolves it. All-or-nothing per call — a
        worker error fails the whole relay request and the predictor's
        hedged failover (predictor/predictor.py) takes it from there.
        ``binary`` requests (one wire frame, queries possibly a stacked
        ndarray) are answered with a wire frame; JSON stays JSON."""
        import time as _time

        import numpy as _np

        from rafiki_tpu import config as _config

        if self.engine.broker is None:
            return self._respond(handler, 503, {
                "error": "no serving data plane on this agent"})
        queries = body.get("queries")
        if isinstance(queries, _np.ndarray):
            if queries.ndim < 1:
                return self._respond(handler, 400, {
                    "error": "stacked queries need a leading batch axis"})
            queries = list(queries)  # zero-copy row views
        if not isinstance(queries, list) or not queries:
            return self._respond(handler, 400, {
                "error": "body must carry a non-empty 'queries' list"})
        queue = self.engine.broker.get_worker_queues(job_id).get(worker_id)
        if queue is None:
            return self._respond(handler, 404, {
                "error": f"no worker {worker_id} for job {job_id} "
                         f"on this host"})
        from rafiki_tpu.utils.reqfields import parse_timeout_s

        # cap=None: relay senders are key-authenticated infrastructure
        # (the admin predictor forwarding ITS resolved timeout) — capping
        # here would time remote replicas out earlier than local ones
        timeout_s, terr = parse_timeout_s(
            body.get("timeout_s"), default=_config.PREDICT_TIMEOUT_S,
            cap=None)
        if terr:
            return self._respond(handler, 400, {"error": terr})
        deadline = _time.monotonic() + timeout_s
        from rafiki_tpu.cache.queue import QueueFullError
        from rafiki_tpu.utils import trace as rtrace

        # cross-host trace hop: the admin-side relay forwards the sampled
        # request's context in the body; this agent collects its local
        # half of the span tree (queue wait + worker phases over ITS shm
        # hop) and ships the spans home in the response. Old relays send
        # no "trace" key; old agents ignored it — both directions serve.
        rt = None
        ctx = rtrace.TraceContext.from_wire(body.get("trace"))
        if ctx is not None and ctx.sampled:
            rt = rtrace.RequestTrace(ctx)
        try:
            # the relayed deadline rides into the host-local queue, so a
            # stalled remote worker drops expired relayed queries exactly
            # like local ones
            futures = queue.submit_many(queries, deadline=deadline,
                                        trace=rt)
        except QueueFullError as e:
            # bounded queue refused: shed with the standard retryable code
            # — the admin-side predictor treats the failed relay as a
            # replica failure and fails over / suppresses its hedge
            return self._respond(handler, 429, {"error": str(e)})
        try:
            preds = [
                f.result(max(deadline - _time.monotonic(), 0.0))
                for f in futures
            ]
        except TimeoutError:
            return self._respond(handler, 504, {
                "error": f"worker {worker_id} missed the "
                         f"{timeout_s:.0f}s relay deadline"})
        except Exception:
            # the admin's relay treats ANY 502 as a failed worker — the
            # detail (traceback included) belongs in the agent log, not
            # on the wire (FWK402)
            logger.exception("relay to worker %s failed", worker_id)
            return self._respond(handler, 502, {
                "error": f"worker {worker_id}: relay failed "
                         "(see agent log)"})
        payload: Dict[str, Any] = {"predictions": preds}
        if rt is not None:
            # offsets relative to this agent's submit time; the relay
            # re-anchors them at its own (cache/fleet.py _relay)
            anchor = rt.t_submit if rt.t_submit is not None else rt.t0
            payload["trace_spans"] = rt.wire_spans(anchor)
        if binary:
            return self._respond_frame(handler, payload)
        self._respond(handler, 200, payload)

    @staticmethod
    def _respond(handler, code: int, payload: Dict[str, Any]) -> None:
        # json_default: worker predictions may be ndarrays (binary-era
        # workers) even when the caller negotiated JSON
        data = json.dumps(payload, default=json_default).encode()
        handler.send_response(code)
        handler.send_header("Content-Type", "application/json")
        handler.send_header("Content-Length", str(len(data)))
        handler.end_headers()
        handler.wfile.write(data)

    @staticmethod
    def _respond_frame(handler, payload: Dict[str, Any]) -> None:
        """Success leg of a binary relay: one wire frame back (ndarray
        predictions as raw bytes). Errors always answer JSON — the
        client's error decode path is shared with the control plane."""
        data = wire.encode(payload)
        handler.send_response(200)
        handler.send_header("Content-Type", wire.CONTENT_TYPE)
        handler.send_header("Content-Length", str(len(data)))
        handler.end_headers()
        handler.wfile.write(data)


def _admin_status_forwarder(db, admin_addr: Optional[str]):
    """Terminal service statuses must reach the Admin (its orchestration
    side-effects — job refresh — live behind the status callback, see
    admin._on_service_status). Mark the shared store locally, then forward
    the event best-effort over the admin REST API."""
    client_box: Dict[str, Any] = {}

    def on_status(service_id: str, status: str) -> None:
        try:
            if status == "RUNNING":
                db.mark_service_as_running(service_id)
            elif status == "STOPPED":
                db.mark_service_as_stopped(service_id)
            elif status == "ERRORED":
                db.mark_service_as_errored(service_id)
        except Exception:
            logger.exception("status write failed for %s", service_id)
        if not admin_addr:
            return
        try:
            if "client" not in client_box:
                from rafiki_tpu import config
                from rafiki_tpu.client.client import Client

                host, port = admin_addr.rsplit(":", 1)
                c = Client(admin_host=host, admin_port=int(port))
                c.login(config.SUPERADMIN_EMAIL, config.SUPERADMIN_PASSWORD)
                client_box["client"] = c
            client_box["client"].send_event(
                "service_status", service_id=service_id, status=status)
        except Exception:
            client_box.pop("client", None)  # re-login next time
            logger.warning("could not forward status of %s to admin",
                           service_id)

    return on_status


def main() -> int:
    logging.basicConfig(
        level=os.environ.get("RAFIKI_LOG_LEVEL", "INFO"),
        format="%(levelname)s:%(asctime)s:agent:%(name)s: %(message)s",
    )
    from rafiki_tpu.db.database import Database

    if chaos.enabled():
        logger.warning("RAFIKI_CHAOS set — fault injection ACTIVE on this "
                       "agent (unset it outside failover drills)")
    key = os.environ.get("RAFIKI_AGENT_KEY")
    insecure = os.environ.get("RAFIKI_AGENT_INSECURE") == "1"
    if not key and not insecure:
        print("RAFIKI_AGENT_KEY required: the agent API places services "
              "and relays predictions, so it is auth-gated by default "
              "(scripts/start_agent.sh generates one). Set "
              "RAFIKI_AGENT_INSECURE=1 to run keyless on a trusted "
              "network.", file=sys.stderr)
        return 2
    db_path = os.environ.get("RAFIKI_DB_PATH")
    if not db_path:
        print("RAFIKI_DB_PATH required (the shared metadata store)",
              file=sys.stderr)
        return 2
    chips_env = os.environ.get("RAFIKI_AGENT_CHIPS", "")
    chips = [int(c) for c in chips_env.split(",") if c.strip()] or None
    if chips is None:
        # Discover through the BOUNDED probe: an in-process jax.devices()
        # hangs forever when the TPU tunnel is wedged (r3 postmortem),
        # and the agent must come up — or fail fast with advice — either
        # way. ChipAllocator(None) is only for in-process callers that
        # already own a live backend.
        from rafiki_tpu.utils.backend_probe import probe_device_count

        n, err = probe_device_count()
        if not n:
            print(f"could not discover this host's chips ({err}); set "
                  "RAFIKI_AGENT_CHIPS to the device indices this host "
                  "should contribute", file=sys.stderr)
            return 2
        chips = list(range(n))
    db = Database(db_path)
    admin_addr = os.environ.get("RAFIKI_ADMIN_ADDR")
    addr_tuple = None
    if admin_addr:
        host, _, port = admin_addr.rpartition(":")
        addr_tuple = (host, int(port))
    # host-local serving data plane: this agent process owns the shm
    # segments; its inference worker processes attach; remote predictors
    # reach them via /predict_relay. Best-effort — a host without the
    # native library still trains, it just can't serve.
    broker = None
    try:
        from rafiki_tpu.cache.shm_broker import ShmBroker

        broker = ShmBroker()
    except Exception as e:
        logger.warning("no serving data plane on this host (%s); "
                       "agent will place TRAIN services only", e)
    engine = ProcessPlacementManager(
        db=db,
        admin_addr=addr_tuple,
        allocator=ChipAllocator(chips),
        broker=broker,
        on_status=_admin_status_forwarder(db, admin_addr),
    )
    server = AgentServer(
        engine,
        host=os.environ.get("RAFIKI_AGENT_HOST", "127.0.0.1"),
        port=int(os.environ.get("RAFIKI_AGENT_PORT", "0")),
        key=key, allow_insecure=insecure,
    ).start()
    print(f"rafiki_tpu agent on http://{server.host}:{server.port} "
          f"(chips={engine.allocator.total_chips}, db={db_path})", flush=True)

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    try:
        stop.wait()
    finally:
        server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
