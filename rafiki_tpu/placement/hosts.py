"""Multi-host placement: the admin-side manager driving per-host agents.

The reference placed dynamic workers across a multi-node Docker Swarm with
per-node GPU bookkeeping and a least-loaded node choice (reference
rafiki/container/docker_swarm.py:53-90, 99-172). `HostAgentPlacementManager`
is the TPU-VM analogue behind the same `PlacementManager` seam
(placement/manager.py:122): every host runs a placement agent
(placement/agent.py) owning that host's chips; train executors are placed
on the agent with the lightest load that can satisfy the chip grant.

Division of labor:

- TRAIN services  -> remote agents (pure processes; coordination runs over
  the shared store + admin REST, so host boundaries don't matter);
- INFERENCE -> remote agents too (reference: inference workers on any
  swarm node, services_manager.py:204-239). Each agent owns its host's
  shm data plane; the admin-side predictor reaches remote workers through
  the agent's ``/predict_relay`` via ``FleetBroker.register_remote_worker``
  (cache/fleet.py) — wire the broker in with :meth:`set_broker`. Falls
  back to the ``local`` engine when no agent can serve (no chips free
  fleet-wide, or no FleetBroker wired);
- PREDICT -> always the admin process (the predictor object lives there).

Status flow: worker processes write their own service rows to the shared
store (worker/bootstrap.py); each agent backstops crashes and forwards
terminal statuses to the admin's ``service_status`` event so job-level
refresh still fires (admin._on_service_status).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from rafiki_tpu import config
from rafiki_tpu.constants import AgentHealth, ServiceType
from rafiki_tpu.utils.agent_http import (
    STALE_EPOCH_STATUS,
    AgentCircuitOpenError,
    AgentHTTPError,
    AgentTransportError,
    breaker_states,
    call_agent,
    reset_breaker,
)
from rafiki_tpu.placement.manager import (
    InsufficientChipsError,
    PlacementManager,
    ServiceContext,
    StatusFn,
)

logger = logging.getLogger(__name__)


class AgentUnreachableError(Exception):
    pass


class AgentCircuitOpenUnreachable(AgentUnreachableError):
    """Refused by an open circuit breaker: the request NEVER reached the
    wire, so — unlike a generic transport failure — nothing can have been
    committed on the agent. Placement treats this as provably unplaced."""


class StaleAdminEpochError(Exception):
    """The agent refused this control call because a newer admin epoch
    holds the leadership lease (STALE_EPOCH_STATUS — the agent-side half
    of epoch fencing, docs/failure-model.md "Control-plane HA"). Terminal
    and NOT an unreachability: the agent is alive and the refusal is
    final — this admin must stop mutating, not fail over to a sibling."""


class _AgentHandle:
    """Client for one host agent (wire protocol: utils/agent_http.py)."""

    def __init__(self, addr: str, key: Optional[str] = None,
                 timeout_s: float = 10.0):
        self.addr = addr  # "host:port"
        self.key = key
        self.timeout_s = timeout_s
        # control-plane HA: when the owning manager is epoch-fenced, every
        # call stamps the leader's epoch (set_epoch_provider)
        self.epoch_provider: Optional[Callable[[], Optional[int]]] = None

    def epoch(self) -> Optional[int]:
        return self.epoch_provider() if self.epoch_provider else None

    def _call(self, method: str, path: str,
              body: Optional[Dict[str, Any]] = None,
              idempotent: Optional[bool] = None) -> Dict[str, Any]:
        try:
            return call_agent(self.addr, method, path, body=body,
                              key=self.key, timeout_s=self.timeout_s,
                              idempotent=idempotent, epoch=self.epoch())
        except AgentHTTPError as e:
            if e.code == STALE_EPOCH_STATUS:
                raise StaleAdminEpochError(f"{self.addr}: {e.message}")
            if e.code == 503:
                raise InsufficientChipsError(e.message)
            raise AgentUnreachableError(f"{self.addr}: {e.message}")
        except AgentCircuitOpenError as e:
            raise AgentCircuitOpenUnreachable(str(e))
        except AgentTransportError as e:
            raise AgentUnreachableError(str(e))

    def inventory(self) -> Dict[str, Any]:
        return self._call("GET", "/inventory")

    def create_service(self, service_id: str, service_type: str,
                       n_chips: int, best_effort_chips: bool,
                       extra: Dict[str, Any]) -> List[int]:
        out = self._call("POST", "/services", {
            "service_id": service_id,
            "service_type": service_type,
            "n_chips": n_chips,
            "best_effort_chips": best_effort_chips,
            "extra": extra,
        })
        return list(out.get("chips", []))

    def stop_service(self, service_id: str, wait: bool) -> None:
        # stopping an already-stopped service is a no-op on the agent, so
        # this POST is safe to retry on transport failures
        self._call("POST", f"/services/{service_id}/stop", {"wait": wait},
                   idempotent=True)


class _FleetInventory:
    """The budget-clamping shape admin/services.py expects from
    `placement.allocator`: `total_chips` across all reachable agents, and
    `max_chips_per_service` — the largest single-host inventory, since one
    executor's grant can never span hosts."""

    def __init__(self, manager: "HostAgentPlacementManager"):
        self._manager = manager

    @property
    def total_chips(self) -> int:
        return sum(
            inv.get("total_chips", 0)
            for _, inv in self._manager._inventories()
        )

    @property
    def free_chips(self) -> int:
        """Fleet-wide free chips — the chip-budget arbiter's borrow
        headroom signal (same shape ChipAllocator exposes locally)."""
        return sum(
            inv.get("free_chips", 0)
            for _, inv in self._manager._inventories()
        )

    @property
    def max_chips_per_service(self) -> int:
        return max(
            (inv.get("total_chips", 0)
             for _, inv in self._manager._inventories()),
            default=0,
        )


class ChipBudgetArbiter:
    """Arbitrates the chip budget between the serving and training planes
    (docs/failure-model.md "Overload adaptation").

    A serving surge may BORROW chips that trials aren't using: the
    autoscaler places extra replicas with exclusive grants as long as at
    least ``RAFIKI_AUTOSCALE_TRAIN_FLOOR`` chips remain un-borrowed —
    the hard floor that guarantees training can never be starved out
    entirely. When the training plane wants chips back (the next trial's
    executor can't allocate), :meth:`reclaim_for_training` drains borrowed
    serving replicas — training has priority over borrowed capacity, so
    every borrow is a loan, never a transfer.

    Works against any allocator exposing ``total_chips``/``free_chips``
    (the local :class:`ChipAllocator` or this module's fleet inventory),
    so single-host and hosts-mode deployments arbitrate identically.

    The loan book is in-memory, with a durable twin: every committed
    borrow writes ``borrowed_chips`` onto the replica's worker row
    (admin/services.py), and ControlPlaneRecovery re-enters the loan
    here when a successor admin adopts the replica — so targeted
    reclaim and the fleet-health loan picture survive an admin restart
    instead of silently leaking until the replica stops. The marker is
    cleared when the loan comes home (:meth:`note_return`)."""

    def __init__(self, allocator=None):
        self._alloc = allocator
        self._lock = threading.Lock()
        # service_id -> (inference_job_id, n_chips) currently on loan
        self._borrowed: Dict[str, Tuple[str, int]] = {}
        # loans held by warm STANDBY replicas — reclaim's first victims
        # (admin/warm_pool.py tags them; note_return untags)
        self._standby: set = set()
        # token -> n_chips of borrows DECIDED but not yet granted by the
        # allocator: counted against the floor so concurrent scale-ups
        # can't both pass the check before either takes its chips
        self._pending: Dict[object, int] = {}
        # installed by the autoscaler: callable(n_chips) -> chips actually
        # returned (drains borrowed replicas via graceful scale-down)
        self._reclaim_cb = None
        from rafiki_tpu.utils.metrics import REGISTRY

        self._g_borrowed = REGISTRY.gauge(
            "rafiki_autoscale_borrowed_chips",
            "chips the serving plane currently borrows from idle "
            "training capacity")

    def set_reclaim_callback(self, cb) -> None:
        self._reclaim_cb = cb

    def floor(self) -> int:
        return max(int(config.AUTOSCALE_TRAIN_FLOOR), 0)

    def capacity(self) -> Tuple[int, int]:
        """(total_chips, free_chips) of the arbitrated inventory, (0, 0)
        when no allocator is wired (chip-less deployments)."""
        if self._alloc is None:
            return 0, 0
        try:
            return int(self._alloc.total_chips), int(self._alloc.free_chips)
        except Exception:
            logger.exception("chip arbiter capacity probe failed")
            return 0, 0

    def may_borrow(self, n_chips: int) -> bool:
        """True when lending ``n_chips`` to serving leaves at least the
        training floor's worth of chips free (pending reservations
        included). Chips already held by running trials are not counted
        against the floor — they ARE training's. Advisory view; the
        atomic check-and-reserve is :meth:`begin_borrow`."""
        if n_chips <= 0 or self._alloc is None:
            return False
        total, free = self.capacity()
        if total <= 0:
            return False
        with self._lock:
            pending = sum(self._pending.values())
        return free - pending - n_chips >= self.floor()

    def begin_borrow(self, n_chips: int) -> Optional[object]:
        """Atomically check the floor AND reserve the intent to borrow
        ``n_chips`` — two concurrent scale-ups can't both pass the check
        before either takes its chips from the allocator. Returns an
        opaque reservation token (pass to :meth:`commit_borrow` /
        :meth:`cancel_borrow`), or None when the floor refuses."""
        if n_chips <= 0 or self._alloc is None:
            return None
        total, free = self.capacity()
        if total <= 0:
            return None
        with self._lock:
            if free - sum(self._pending.values()) - n_chips < self.floor():
                return None
            token = object()
            self._pending[token] = n_chips
            return token

    def commit_borrow(self, token: object, service_id: str,
                      inference_job_id: str, chips) -> None:
        """The reserved borrow was granted: move it onto the loan book."""
        with self._lock:
            self._pending.pop(token, None)
        self.note_borrow(service_id, inference_job_id, chips)

    def cancel_borrow(self, token: object) -> None:
        """The reserved borrow never happened (placement failed or fell
        back to shared devices): free the reservation."""
        with self._lock:
            self._pending.pop(token, None)

    def note_borrow(self, service_id: str, inference_job_id: str,
                    chips) -> None:
        n = len(chips) if hasattr(chips, "__len__") else int(chips)
        if n <= 0:
            return
        with self._lock:
            self._borrowed[service_id] = (inference_job_id, n)
            self._g_borrowed.set(
                sum(c for _, c in self._borrowed.values()))
        logger.info("serving borrowed %d chip(s) for replica %s (job %s)",
                    n, service_id[:8], inference_job_id[:8])

    def note_return(self, service_id: str) -> int:
        with self._lock:
            job_id, n = self._borrowed.pop(service_id, (None, 0))
            self._standby.discard(service_id)
            self._g_borrowed.set(
                sum(c for _, c in self._borrowed.values()))
        if n:
            logger.info("serving returned %d borrowed chip(s) with "
                        "replica %s", n, service_id[:8])
        return n

    def mark_standby(self, service_id: str, standby: bool = True) -> None:
        """Tag a loan as held by a warm STANDBY replica (or clear the
        tag on promotion). Standby loans are reclaim's first victims —
        they serve no traffic, so training wins them back with an
        outright destroy instead of a drain (admin/warm_pool.py;
        docs/failure-model.md "Cold-start faults")."""
        with self._lock:
            if standby and service_id in self._borrowed:
                self._standby.add(service_id)
            else:
                self._standby.discard(service_id)

    def borrowed(self) -> Dict[str, Tuple[str, int]]:
        with self._lock:
            return dict(self._borrowed)

    def standby_loans(self) -> Dict[str, Tuple[str, int]]:
        """The subset of the loan book held by warm standbys."""
        with self._lock:
            return {sid: v for sid, v in self._borrowed.items()
                    if sid in self._standby}

    def borrowed_chips(self) -> int:
        with self._lock:
            return sum(n for _, n in self._borrowed.values())

    def loan_split(self) -> Dict[str, int]:
        """{"serving": n, "standby": n} chips on loan — the fleet-health
        view of who holds what training could reclaim."""
        with self._lock:
            standby = sum(n for sid, (_, n) in self._borrowed.items()
                          if sid in self._standby)
            total = sum(n for _, n in self._borrowed.values())
        return {"serving": total - standby, "standby": standby}

    def reclaim_for_training(self, n_chips: int) -> int:
        """The training plane demands ``n_chips`` it cannot allocate:
        drain borrowed serving replicas until that many chips came home
        (or the loan book is empty). Returns the chips actually freed.
        Synchronous — the caller retries its allocation right after."""
        if n_chips <= 0 or self._reclaim_cb is None:
            return 0
        if not self.borrowed():
            return 0
        try:
            freed = int(self._reclaim_cb(n_chips) or 0)
        except Exception:
            logger.exception("chip reclaim callback failed")
            return 0
        if freed:
            logger.warning(
                "training reclaimed %d chip(s) from the serving plane "
                "(%d requested)", freed, n_chips)
        return freed


class HostAgentPlacementManager(PlacementManager):
    """Places train executors across per-host agents; serving stays on the
    admin host's local engine."""

    def __init__(
        self,
        agents: List[str],
        local: Optional[PlacementManager] = None,
        key: Optional[str] = None,
        on_status: Optional[StatusFn] = None,
        db=None,
        inventory_ttl_s: float = 1.0,
        monitor_interval_s: float = 0.5,
        heartbeat_interval_s: Optional[float] = None,
        down_threshold: Optional[int] = None,
    ):
        if not agents:
            raise ValueError("at least one agent address required")
        self.agents: Dict[str, _AgentHandle] = {
            a: _AgentHandle(a, key=key) for a in agents
        }
        self.local = local
        self.on_status = on_status
        # The shared metadata store. When provided, a monitor thread polls
        # the rows of remotely-placed services and fires `on_status` on
        # terminal transitions — the admin's job-refresh side effects then
        # never depend on agents being able to log in and forward events
        # (that path, placement/agent.py _admin_status_forwarder, remains as
        # a faster best-effort signal).
        self.db = db
        self.broker = None  # FleetBroker; see set_broker
        self.allocator = _FleetInventory(self)
        self._inventory_ttl_s = inventory_ttl_s
        self._monitor_interval_s = monitor_interval_s
        self._inventory_cache: List[Tuple[str, Dict[str, Any]]] = []
        self._inventory_at = 0.0
        self._lock = threading.Lock()
        self._placed: Dict[str, str] = {}  # service_id -> agent addr
        # service_id -> inference_job_id, for relay-queue teardown
        self._placed_jobs: Dict[str, str] = {}
        # service_id -> original create args, so a dead host's train
        # executors can be replayed onto survivors (failover)
        self._placed_specs: Dict[str, Dict[str, Any]] = {}
        # addr -> service ids stripped from it while it was DOWN; fenced
        # (stopped) on that agent if it ever rejoins, so a false-positive
        # DOWN (partition, not crash) cannot leave two live executors for
        # one service id
        self._stripped: Dict[str, List[str]] = {}
        self._reported: set = set()
        self._monitor: Optional[threading.Thread] = None
        self._closed = threading.Event()
        # -- fleet health: heartbeat/lease state per agent ----------------
        self._heartbeat_interval_s = (
            heartbeat_interval_s if heartbeat_interval_s is not None
            else config.AGENT_HEARTBEAT_INTERVAL_S)
        self._down_threshold = max(
            down_threshold if down_threshold is not None
            else config.AGENT_DOWN_THRESHOLD, 1)
        self._health: Dict[str, Dict[str, Any]] = {
            a: {"state": AgentHealth.UNKNOWN, "misses": 0,
                "last_ok": None, "last_error": None}
            for a in agents
        }
        self._heartbeat: Optional[threading.Thread] = None
        # control-plane HA: the leader's epoch provider (admin/lease.py);
        # every agent call is stamped with it once set, so agents learn
        # new epochs from ordinary authenticated traffic (a promoting
        # admin's recovery inventory probes, first of all) and can fence
        # a stale ex-leader's mutations
        self.epoch_provider: Optional[Callable[[], Optional[int]]] = None
        if self._heartbeat_interval_s > 0:
            self._heartbeat = threading.Thread(
                target=self._heartbeat_loop, name="hosts-heartbeat",
                daemon=True)
            self._heartbeat.start()

    def set_epoch_provider(
            self, fn: Optional[Callable[[], Optional[int]]]) -> None:
        """Wire the admin's leadership-epoch source into every agent
        handle (and the probe/heartbeat paths) — the client-side half of
        epoch fencing."""
        self.epoch_provider = fn
        for handle in self.agents.values():
            handle.epoch_provider = fn

    # -- inventories -------------------------------------------------------

    def _inventories(self) -> List[Tuple[str, Dict[str, Any]]]:
        with self._lock:
            if time.monotonic() - self._inventory_at < self._inventory_ttl_s:
                return list(self._inventory_cache)
        with self._lock:
            down = {a for a, h in self._health.items()
                    if h["state"] == AgentHealth.DOWN}
        out: List[Tuple[str, Dict[str, Any]]] = []
        for addr, handle in self.agents.items():
            if addr in down:
                continue  # heartbeat says dead; don't spend a timeout on it
            try:
                out.append((addr, handle.inventory()))
            except AgentUnreachableError:
                logger.warning("agent %s unreachable; skipping", addr)
        with self._lock:
            self._inventory_cache = out
            self._inventory_at = time.monotonic()
        return list(out)

    def _choose_agent(self, n_chips: int,
                      exclude: frozenset = frozenset()) -> Optional[str]:
        """Least-loaded host with enough free chips (the reference's node
        choice: filter by free GPUs, then fewest services, reference
        docker_swarm.py:53-70). ``exclude`` skips agents that already
        refused this service."""
        candidates = [
            (inv.get("n_services", 0), -inv.get("free_chips", 0), addr)
            for addr, inv in self._inventories()
            if inv.get("free_chips", 0) >= n_chips and addr not in exclude
        ]
        if not candidates:
            return None
        candidates.sort()
        return candidates[0][2]

    # -- PlacementManager --------------------------------------------------

    def set_broker(self, broker) -> None:
        """Wire in the admin's FleetBroker so remotely-placed inference
        workers get an admin-side relay queue (cache/fleet.py). Without
        it, inference falls back to the local engine."""
        self.broker = broker

    def create_service(
        self,
        service_id: str,
        service_type: str,
        run_fn=None,
        n_chips: int = 0,
        extra: Optional[Dict[str, Any]] = None,
        best_effort_chips: bool = False,
    ) -> ServiceContext:
        can_relay = (self.broker is not None
                     and hasattr(self.broker, "register_remote_worker"))
        if service_type == ServiceType.INFERENCE and can_relay:
            # Try EVERY agent (least-loaded first) before the local
            # fallback: one agent 503ing (no shm data plane, chip race)
            # must not pin serving to the admin host while siblings have
            # capacity. Only PROVABLY-unplaced failures continue the loop
            # or fall back: InsufficientChipsError is pre-commit, and
            # _create_on_agent returns None only when no candidate was
            # contacted or an ambiguous create was successfully undone.
            # An ambiguous create whose undo also failed PROPAGATES —
            # falling back would double-place the service (a remote copy
            # may be serving) and leak its chips forever.
            tried: set = set()
            while True:
                before = len(tried)
                try:
                    ctx = self._create_on_agent(
                        service_id, service_type, n_chips,
                        best_effort_chips, extra, tried=tried)
                except InsufficientChipsError as e:
                    if len(tried) == before:
                        # pre-choice fleet-wide verdict, not one agent
                        # refusing — nothing left to iterate
                        logger.info("fleet cannot serve %s (%s)",
                                    service_id[:8], e)
                        break
                    logger.info("agent refused %s (%s); trying the next",
                                service_id[:8], e)
                    continue  # that agent is in `tried` now
                if ctx is not None:
                    return ctx
                if len(tried) > before:
                    # an agent was contacted and its ambiguous create
                    # was confirmed undone — it is in `tried` now, so
                    # continuing is safe and tries the REMAINING agents
                    # (advisor r4: breaking here pinned serving to the
                    # local fallback while siblings had capacity)
                    continue
                break  # candidates exhausted: nothing was contacted
            logger.info("no agent can serve %s; trying the local engine",
                        service_id[:8])
            # fall through to the local engine
        if service_type != ServiceType.TRAIN:
            if self.local is None:
                raise RuntimeError(
                    "HostAgentPlacementManager has no engine for "
                    f"{service_type} executors: no agent can take it and "
                    "no `local` engine is configured")
            return self.local.create_service(
                service_id, service_type, run_fn, n_chips=n_chips,
                extra=extra, best_effort_chips=best_effort_chips)

        ctx = self._create_on_agent(
            service_id, service_type, n_chips, best_effort_chips, extra)
        if ctx is None:
            raise AgentUnreachableError("no reachable agents")
        return ctx

    def _create_on_agent(
        self,
        service_id: str,
        service_type: str,
        n_chips: int,
        best_effort_chips: bool,
        extra: Optional[Dict[str, Any]],
        tried: Optional[set] = None,
    ) -> Optional[ServiceContext]:
        """Least-loaded agent placement. Returns None when no agent can
        take the service (callers decide: TRAIN raises, INFERENCE falls
        back to the local engine). ``tried`` (mutated) records the chosen
        agent BEFORE the create attempt, so a caller retry loop always
        makes progress and never re-asks a refusing agent."""
        requested_chips = n_chips  # pre-downsize ask, for failover replay
        exclude = frozenset(tried or ())
        addr = self._choose_agent(n_chips, exclude=exclude)
        if addr is None:
            if not best_effort_chips and n_chips > 0:
                raise InsufficientChipsError(
                    f"No agent has {n_chips} free chips "
                    f"(fleet: {[i for _, i in self._inventories()]})")
            addr = self._choose_agent(0, exclude=exclude)
            if addr is None:
                return None  # nothing was contacted; caller decides
            n_chips = 0
        if tried is not None:
            tried.add(addr)
        try:
            chips = self.agents[addr].create_service(
                service_id, service_type, n_chips, best_effort_chips,
                dict(extra or {}))
        except AgentCircuitOpenUnreachable as e:
            # fail-fast refusal BEFORE any request was sent: provably
            # unplaced, no undo needed — skip this agent and let the
            # caller's loop try the remaining candidates
            logger.warning("agent %s circuit open; skipped (%s)", addr, e)
            return None
        except AgentUnreachableError:
            # AMBIGUOUS: the agent may have committed the worker before
            # the wire failed. Try to undo; only a confirmed undo makes a
            # retry/fallback safe (the remote copy would otherwise keep
            # serving and hold its chips with no admin-side record).
            try:
                self.agents[addr].stop_service(service_id, wait=False)
            except (AgentUnreachableError, InsufficientChipsError):
                raise AgentUnreachableError(
                    f"create on {addr} failed ambiguously and the undo "
                    f"stop also failed — service {service_id} may be "
                    f"running there; not falling back")
            logger.warning("create on %s failed; undo confirmed, agent "
                           "skipped", addr)
            return None
        job_id = (extra or {}).get("inference_job_id")
        if service_type == ServiceType.INFERENCE and job_id:
            # admin-side half of the data plane: a relay queue pointed at
            # this agent, merged into the predictor's fan-out set
            self.broker.register_remote_worker(
                job_id, service_id, addr, key=self.agents[addr].key)
        with self._lock:
            self._placed[service_id] = addr
            if service_type == ServiceType.INFERENCE and job_id:
                self._placed_jobs[service_id] = job_id
            self._placed_specs[service_id] = {
                "service_type": service_type,
                "n_chips": requested_chips,
                "best_effort_chips": best_effort_chips,
                "extra": dict(extra or {}),
            }
            self._inventory_at = 0.0  # free-chip counts changed
            self._maybe_start_monitor_locked()
        logger.info("placed %s on agent %s (chips=%s)",
                    service_id[:8], addr, chips)
        return ServiceContext(
            service_id=service_id,
            service_type=service_type,
            chips=chips,
            stop_event=threading.Event(),
            extra=dict(extra or {}),
        )

    def _maybe_start_monitor_locked(self) -> None:
        """Start the store-status monitor on first tracked service (must
        hold ``self._lock``)."""
        if (self.db is not None and self._monitor is None
                and not self._closed.is_set()):
            self._monitor = threading.Thread(
                target=self._monitor_loop, name="hosts-status-monitor",
                daemon=True)
            self._monitor.start()

    # -- control-plane crash recovery (admin/recovery.py) ------------------

    def probe_inventories(
        self, timeout_s: Optional[float] = None
    ) -> Dict[str, Optional[Dict[str, Any]]]:
        """One bounded /inventory probe per registered agent: the
        running-set the restart reconciliation diffs the store against.
        Unreachable agents map to None (their services are dead-host
        candidates: reschedule or error). Probes fan out concurrently —
        the boot reconcile (and the 503'd HTTP doors behind it) must pay
        ~one probe timeout for a partially-dead fleet, not one per dead
        agent."""
        if timeout_s is None:
            timeout_s = float(config.RECOVER_PROBE_TIMEOUT_S)

        def probe(item):
            addr, handle = item
            try:
                return addr, call_agent(addr, "GET", "/inventory",
                                        key=handle.key, timeout_s=timeout_s,
                                        epoch=handle.epoch())
            except Exception as e:
                logger.warning("recovery probe of agent %s failed: %s",
                               addr, e)
                return addr, None

        from concurrent.futures import ThreadPoolExecutor

        items = list(self.agents.items())
        with ThreadPoolExecutor(
                max_workers=max(1, min(len(items), 16)),
                thread_name_prefix="recover-probe") as pool:
            return dict(pool.map(probe, items))

    def adopt_service(
        self,
        service_id: str,
        addr: str,
        service_type: str,
        n_chips: int = 0,
        extra: Optional[Dict[str, Any]] = None,
        best_effort_chips: bool = False,
    ) -> bool:
        """Record a service ALREADY running on agent ``addr`` (admin
        restart reconciliation) as if this manager had placed it: the
        heartbeat failover, rejoin fencing, and store-status monitor then
        cover it like any placed service. Inference workers get their
        admin-side relay queue re-registered so the predictor fan-out
        reaches them again without a redeploy."""
        if addr not in self.agents:
            return False
        extra = dict(extra or {})
        job_id = extra.get("inference_job_id")
        if (service_type == ServiceType.INFERENCE and job_id
                and self.broker is not None
                and hasattr(self.broker, "register_remote_worker")):
            self.broker.register_remote_worker(
                job_id, service_id, addr, key=self.agents[addr].key)
        with self._lock:
            self._placed[service_id] = addr
            if service_type == ServiceType.INFERENCE and job_id:
                self._placed_jobs[service_id] = job_id
            self._placed_specs[service_id] = {
                "service_type": service_type,
                "n_chips": n_chips,
                "best_effort_chips": best_effort_chips,
                "extra": extra,
            }
            self._inventory_at = 0.0
            self._maybe_start_monitor_locked()
        logger.info("adopted service %s on agent %s (control-plane "
                    "restart)", service_id[:8], addr)
        return True

    def reschedule_service(
        self,
        service_id: str,
        service_type: str,
        n_chips: int = 0,
        extra: Optional[Dict[str, Any]] = None,
        best_effort_chips: bool = False,
        exclude=(),
    ) -> bool:
        """Replay a service whose host died while the control plane was
        down through the PR-1 failover path: least-loaded surviving
        agent, SAME service id (so the replacement train worker resumes
        its stale RUNNING trials). ``exclude`` lists agents that must not
        receive the replay — above all the probe-unreachable ones: an
        UNKNOWN-state agent that merely answered slowly may STILL be
        running the old executor, and re-placing the same id onto it
        would double-run the service (and the quarantine fence would
        later kill the legitimate replacement)."""
        return self._reschedule(
            service_id,
            {
                "service_type": service_type,
                "n_chips": n_chips,
                "best_effort_chips": best_effort_chips,
                "extra": dict(extra or {}),
            },
            dead="<admin-restart>",
            exclude=exclude,
        )

    def quarantine_on_rejoin(self, addrs, service_id: str) -> None:
        """Record that ``service_id`` was (or is about to be) re-placed
        while these agents were unreachable (boot-reconciliation probe
        failure): if one of them turns out to be alive — a slow probe,
        not a crash — and is still running the old executor, the rejoin
        fence stops it there, so one service id never keeps two live
        executors. An agent ALREADY back UP is fenced immediately: its
        UNKNOWN->UP fence sweep may have run before this record existed,
        and it will not run again while the agent stays UP."""
        fence_now = []
        with self._lock:
            for addr in addrs:
                if addr not in self.agents:
                    continue
                h = self._health.get(addr)
                if h is not None and h["state"] == AgentHealth.UP:
                    fence_now.append(addr)
                else:
                    self._stripped.setdefault(addr, []).append(service_id)
        for addr in fence_now:
            self.fence_service(service_id, addr)

    def fence_service(self, service_id: str, addr: str,
                      wait: bool = False) -> bool:
        """Stop an orphan on ``addr`` — a service still running whose job
        was stopped/errored while the admin was down (same split-brain
        rule as the rejoin fence: one service id, one live executor).
        ``wait=True`` blocks until the executor actually exited — required
        when the SAME service id is about to be re-placed (reschedule
        after a disabled adoption), or the old and new executor would
        briefly run concurrently."""
        if addr not in self.agents:
            return False
        try:
            self.agents[addr].stop_service(service_id, wait=wait)
        except (AgentUnreachableError, InsufficientChipsError) as e:
            logger.warning("could not fence orphan %s on %s (%s)",
                           service_id[:8], addr, e)
            return False
        logger.warning("fenced orphan service %s on agent %s "
                       "(control-plane restart)", service_id[:8], addr)
        return True

    def destroy_service(self, service_id: str, wait: bool = True) -> None:
        with self._lock:
            addr = self._placed.pop(service_id, None)
            job_id = self._placed_jobs.pop(service_id, None)
            self._placed_specs.pop(service_id, None)
        if addr is None:
            if self.local is not None:
                self.local.destroy_service(service_id, wait=wait)
            return
        if job_id is not None and self.broker is not None:
            # drop the admin-side relay queue first so no new predicts
            # race the worker teardown
            try:
                self.broker.unregister_worker(job_id, service_id)
            except Exception:
                logger.exception("relay unregister failed for %s", service_id)
        try:
            self.agents[addr].stop_service(service_id, wait)
        except AgentUnreachableError:
            logger.warning("agent %s unreachable destroying %s",
                           addr, service_id)
        with self._lock:
            self._inventory_at = 0.0

    def _monitor_loop(self) -> None:
        """Poll the shared store for terminal statuses of remotely-placed
        services and fire on_status once per service — the authoritative
        path for the admin's job-refresh side effects."""
        from rafiki_tpu.constants import ServiceStatus

        while not self._closed.wait(self._monitor_interval_s):
            with self._lock:
                pending = [sid for sid in self._placed
                           if sid not in self._reported]
            for sid in pending:
                try:
                    svc = self.db.get_service(sid)
                except Exception:
                    logger.exception("status poll failed for %s", sid)
                    continue
                if svc is None:
                    continue
                if svc["status"] in (ServiceStatus.STOPPED,
                                     ServiceStatus.ERRORED):
                    with self._lock:
                        self._reported.add(sid)
                    if self.on_status:
                        try:
                            self.on_status(sid, svc["status"])
                        except Exception:
                            logger.exception("status callback failed")

    # -- fleet health: heartbeats, DOWN handling, failover -----------------

    def _heartbeat_loop(self) -> None:
        """Probe every agent's /healthz each interval. N consecutive
        misses marks the agent DOWN (its lease lapses): serving queues are
        evicted, its services errored, train executors rescheduled. A
        successful probe after DOWN restores the agent and closes its
        circuit breaker. Probes bypass the breaker — they ARE the signal
        that decides recovery, so they must always reach the wire."""
        while not self._closed.wait(self._heartbeat_interval_s):
            for addr, handle in list(self.agents.items()):
                if self._closed.is_set():
                    return
                try:
                    call_agent(
                        addr, "GET", "/healthz", key=handle.key,
                        timeout_s=min(config.AGENT_HEARTBEAT_TIMEOUT_S,
                                      max(self._heartbeat_interval_s, 0.1)),
                        idempotent=False, use_breaker=False,
                        epoch=handle.epoch())
                    alive = True
                    err: Optional[str] = None
                except AgentHTTPError as e:
                    # the host answered; a non-200 /healthz is a config
                    # problem, not a dead machine
                    alive = True
                    err = f"healthz {e.code}: {e.message}"
                # lint: absorb(transport failure IS the down signal; recorded via _note_heartbeat)
                except Exception as e:
                    alive = False
                    err = str(e)
                try:
                    self._note_heartbeat(addr, alive, err)
                except Exception:
                    logger.exception("heartbeat bookkeeping failed for %s",
                                     addr)

    def _note_heartbeat(self, addr: str, alive: bool,
                        err: Optional[str]) -> None:
        went_down = came_up = False
        was_down = False
        with self._lock:
            h = self._health.get(addr)
            if h is None:
                return
            if alive:
                h["misses"] = 0
                h["last_ok"] = time.monotonic()
                h["last_error"] = err
                if h["state"] != AgentHealth.UP:
                    # ANY transition into UP runs the rejoin fence — a
                    # host that was unreachable only during this admin's
                    # boot reconciliation enters as UNKNOWN->UP, and its
                    # quarantined (re-placed) service ids must be fenced
                    # exactly like a DOWN->UP rejoin
                    was_down = h["state"] == AgentHealth.DOWN
                    came_up = True
                    h["state"] = AgentHealth.UP
                    self._inventory_at = 0.0  # re-include immediately
            else:
                h["misses"] += 1
                h["last_error"] = err
                if (h["state"] != AgentHealth.DOWN
                        and h["misses"] >= self._down_threshold):
                    h["state"] = AgentHealth.DOWN
                    went_down = True
        # reconciliation runs OFF the heartbeat thread: a slow failover
        # (inventory refreshes + create calls at transport timeouts) must
        # not stall failure detection for the other agents
        if came_up:
            reset_breaker(addr)
            if was_down:
                logger.warning("agent %s recovered; rejoining the fleet",
                               addr)
            threading.Thread(target=self._fence_rejoined, args=(addr,),
                             name=f"fence-{addr}", daemon=True).start()
        if went_down:
            logger.error("agent %s DOWN after %d missed heartbeats (%s)",
                         addr, self._down_threshold, err)
            threading.Thread(target=self._run_failover, args=(addr,),
                             name=f"failover-{addr}", daemon=True).start()

    def _run_failover(self, addr: str) -> None:
        try:
            self._handle_agent_down(addr)
        except Exception:
            logger.exception("failover for dead agent %s failed", addr)

    def _fence_rejoined(self, addr: str) -> None:
        """A host back from DOWN may still be running the services that
        were rescheduled or errored while it was away (false-positive DOWN:
        a partition, not a crash). Stop those orphans on it, so one service
        id never has two live executors."""
        with self._lock:
            orphans = self._stripped.pop(addr, [])
        for sid in orphans:
            try:
                self.agents[addr].stop_service(sid, wait=False)
                logger.warning("fenced orphan service %s on rejoined "
                               "agent %s", sid[:8], addr)
            except (AgentUnreachableError, InsufficientChipsError) as e:
                logger.warning("could not fence orphan %s on %s (%s)",
                               sid[:8], addr, e)

    def _handle_agent_down(self, addr: str) -> None:
        """Reconcile a dead host: (1) evict its relay queues so the
        predictor's hedged fan-out stops burning deadline slices on
        replicas that cannot answer; (2) reschedule its train executors
        onto surviving agents (same service id, so the new worker resumes
        the stale RUNNING trials from their checkpoints); (3) error
        everything that could not be moved, so the admin's job-level
        refresh and crash recovery fire without operator action."""
        if self.broker is not None and hasattr(self.broker, "evict_agent"):
            try:
                evicted = self.broker.evict_agent(addr)
                if evicted:
                    logger.warning("evicted %d relay queue(s) of dead agent "
                                   "%s: %s", len(evicted), addr, evicted)
            except Exception:
                logger.exception("relay eviction failed for %s", addr)
        with self._lock:
            doomed = [sid for sid, a in self._placed.items() if a == addr]
            specs = {}
            for sid in doomed:
                self._placed.pop(sid, None)
                self._placed_jobs.pop(sid, None)
                specs[sid] = self._placed_specs.pop(sid, None)
            self._stripped.setdefault(addr, []).extend(doomed)
            self._inventory_at = 0.0
        for sid in doomed:
            if self.db is not None:
                try:
                    row = self.db.get_service(sid)
                # lint: absorb(store hiccup reads as non-terminal; teardown stays conservative)
                except Exception:
                    row = None
                if row is not None and row["status"] in ("STOPPED",
                                                         "ERRORED"):
                    # already terminal in the store (e.g. a budget-drained
                    # worker that exited cleanly before its host died) —
                    # nothing to rehome, nothing to error
                    continue
            spec = specs.get(sid)
            if (spec is not None
                    and spec["service_type"] == ServiceType.TRAIN
                    and self._reschedule(sid, spec, dead=addr)):
                continue
            self._mark_errored(sid)

    def _reschedule(self, service_id: str, spec: Dict[str, Any],
                    dead: str, exclude=()) -> bool:
        """Replay a dead host's train executor through the least-loaded
        placement path, excluding every DOWN agent (plus ``exclude``).
        The service keeps its id, so the replacement worker's crash
        recovery resumes the trials the dead one left RUNNING
        (worker/train.py)."""
        with self._lock:
            tried = {a for a, h in self._health.items()
                     if h["state"] == AgentHealth.DOWN}
        tried.add(dead)
        tried.update(exclude)
        while True:
            before = len(tried)
            try:
                ctx = self._create_on_agent(
                    service_id, spec["service_type"], spec["n_chips"],
                    spec["best_effort_chips"], spec["extra"], tried=tried)
            except InsufficientChipsError as e:
                if len(tried) == before:
                    logger.warning("cannot reschedule %s: %s",
                                   service_id[:8], e)
                    return False
                continue
            except AgentUnreachableError:
                logger.exception("rescheduling %s failed", service_id[:8])
                return False
            if ctx is not None:
                logger.warning("service %s failed over %s -> %s",
                               service_id[:8], dead,
                               self._placed.get(service_id))
                return True
            if len(tried) > before:
                continue
            logger.warning("no surviving agent can take %s", service_id[:8])
            return False

    def _mark_errored(self, service_id: str) -> None:
        """Terminal-status backstop for a service whose host died with it:
        the agent-side monitor died too, so the admin side must write the
        store row (and fire the job-refresh side effects) itself."""
        if self.db is not None:
            try:
                self.db.mark_service_as_errored(service_id)
            except Exception:
                logger.exception("could not mark %s ERRORED", service_id)
        with self._lock:
            self._reported.add(service_id)  # status monitor: already final
        if self.on_status:
            try:
                self.on_status(service_id, "ERRORED")
            except Exception:
                logger.exception("status callback failed for %s", service_id)

    def agent_health(self) -> Dict[str, Dict[str, Any]]:
        """Operator view (admin API /fleet/health, doctor): heartbeat state
        + circuit breaker state + load per agent."""
        breakers = breaker_states()
        now = time.monotonic()
        with self._lock:
            placed_by_addr: Dict[str, int] = {}
            for a in self._placed.values():
                placed_by_addr[a] = placed_by_addr.get(a, 0) + 1
            return {
                addr: {
                    "state": h["state"],
                    "consecutive_misses": h["misses"],
                    "seconds_since_ok": (
                        round(now - h["last_ok"], 3)
                        if h["last_ok"] is not None else None),
                    "last_error": h["last_error"],
                    "breaker": breakers.get(addr, "CLOSED"),
                    "services_placed": placed_by_addr.get(addr, 0),
                }
                for addr, h in self._health.items()
            }

    def stop_all(self) -> None:
        self._closed.set()
        with self._lock:
            placed = dict(self._placed)
            self._placed.clear()
            self._placed_jobs.clear()
            self._placed_specs.clear()
        for sid, addr in placed.items():
            try:
                self.agents[addr].stop_service(sid, wait=False)
            except AgentUnreachableError:
                pass
        if self.local is not None and hasattr(self.local, "stop_all"):
            self.local.stop_all()

    # -- introspection (tests / ops) --------------------------------------

    def placements(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._placed)
