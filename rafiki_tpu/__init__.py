"""rafiki_tpu — a TPU-native AutoML Machine-Learning-as-a-Service framework.

A ground-up, JAX/XLA-first re-design of the capability surface of Rafiki
(reference: /root/reference, vivansxu/rafiki): users register *model templates*
(Python classes with tunable knobs), launch *train jobs* that run parallel
hyperparameter-search *trials* under a Bayesian-optimization advisor, and
deploy the best trials as ensembled, continuously-batched *inference jobs*.

Where the reference orchestrates per-GPU Docker containers over Docker Swarm
with Redis-polled serving (reference rafiki/admin/services_manager.py,
rafiki/container/docker_swarm.py, rafiki/predictor/predictor.py), this system
is designed for TPU VM slices:

- the model SDK (`rafiki_tpu.sdk`) has an explicit JAX backend — models are
  pytree params + jitted step functions, sharded over a `jax.sharding.Mesh`;
- trial executors are placed with *chip affinity* onto mesh sub-slices by an
  in-process placement layer (`rafiki_tpu.placement`) instead of containers;
- the advisor (`rafiki_tpu.advisor`) is a native Gaussian-process Bayesian
  optimizer shared across parallel workers of a sub-train-job (fixing the
  reference's uncoordinated per-worker advisors, reference worker/train.py:213);
- the predictor (`rafiki_tpu.predictor`) replaces the 0.25 s Redis poll
  pipeline with a deadline-based continuous batching queue feeding a jitted
  predict function.
"""

__version__ = "0.1.0"

from rafiki_tpu import constants  # noqa: F401
