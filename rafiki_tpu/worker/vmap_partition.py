"""Shape-bucketing partitioner for vectorized trial execution.

A batch of advisor proposals can only train as ONE vmapped XLA program
when every member compiles to the same computation: knobs that shape the
program (architecture width/depth, image size, batch size, epoch count)
must be identical across the stack, while pure dynamic hyperparameters
(lr/momentum/weight-decay riding the optimizer state through
``tunable_optimizer``) may differ per member — that is exactly the
params-stacking contract ``sdk/population.PopulationTrainer`` (and the
fused serving ensemble) already enforce.

This module is the pure, unit-testable half of that decision: given K
proposed knob dicts and the template's declared dynamic-knob names
(``PopulationSpec.dynamic_knobs``), split the batch into vmap-compatible
buckets. Members of one bucket agree on every NON-dynamic knob; buckets
are bounded by the spec's ``max_members`` (the per-chip memory
heuristic). Singleton buckets degrade to the scalar trial path in the
worker — a batch of architecturally-diverse proposals costs nothing, it
just doesn't vectorize.

Determinism contract: bucket order follows first appearance in
``knobs_list`` and member order within a bucket preserves proposal
order, so trial rows, advisor feedback, and ASHA rung reports line up
with what the advisor proposed.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Iterable, List, Sequence


def static_signature(knobs: Dict[str, Any],
                     dynamic_knobs: Iterable[str]) -> str:
    """Canonical signature of a proposal's program-shaping knobs: the
    sorted JSON of every knob NOT declared dynamic. Two proposals with
    the same signature can share one compiled (vmapped) program."""
    dyn = set(dynamic_knobs)
    static = {k: v for k, v in knobs.items() if k not in dyn}
    return json.dumps(static, sort_keys=True, default=str)


def partition_for_vmap(
    knobs_list: Sequence[Dict[str, Any]],
    dynamic_knobs: Iterable[str],
    max_members: int = 0,
) -> List[List[Dict[str, Any]]]:
    """Split proposed knob dicts into vmap-compatible buckets.

    Each returned bucket is a list of knob dicts that agree on every
    non-dynamic knob; ``max_members > 0`` splits oversized buckets into
    chunks of at most that many members. Empty input -> no buckets."""
    dyn = tuple(dynamic_knobs)
    groups: Dict[str, List[Dict[str, Any]]] = {}
    order: List[str] = []
    for knobs in knobs_list:
        sig = static_signature(knobs, dyn)
        if sig not in groups:
            groups[sig] = []
            order.append(sig)
        groups[sig].append(knobs)
    cap = max(int(max_members), 0)
    buckets: List[List[Dict[str, Any]]] = []
    for sig in order:
        members = groups[sig]
        if cap and len(members) > cap:
            buckets.extend(members[i:i + cap]
                           for i in range(0, len(members), cap))
        else:
            buckets.append(members)
    return buckets
