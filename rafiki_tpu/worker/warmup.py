"""Worker program pre-warming (docs/failure-model.md "Cold-start faults").

The single chokepoint every worker runs BEFORE registering as RUNNING:
enable the persistent compile cache (sdk/compile_cache.py), then compile
each enumerated program shape while the replica is still DEPLOYING. The
predictor's route-after-add_worker rule therefore never parks a request
behind a compiling replica — a still-warming worker is simply not
routable yet.

Each boot produces a warm-state report (stored in :data:`WARMUP_STATS`,
merged into the worker's stats row and `/healthz`):

- ``warm`` — True when the boot was served by the persistent cache
  (observed cache hits, or total compile time under
  ``RAFIKI_COMPILE_WARM_THRESHOLD_S`` when hit events are unavailable).
- ``compile_s`` / ``programs`` — total and per-program compile seconds.
- ``cache_hits`` / ``cache_misses`` — this boot's persistent-cache
  traffic (misses are counted here, where compile time is measured).

Chaos (RAFIKI_CHAOS site=compile, target
``"{scope}/{service_id}/{program}"``): ``delay`` stretches the warm-up
(the slow-compile drill), ``corrupt`` garbles the on-disk cache entries
before the program compiles (the bit-rot drill — JAX absorbs the damage
and recompiles, and warm-up EVICTS the unreadable entries so the boot
after next re-warms), ``error`` raises the typed :class:`WarmupError` that
fails the worker's startup (the bounded standby-retry drill). A
program's own exception is absorbed warn-only: a model whose optional
warm-up fails still serves, it just serves cold.
"""

from __future__ import annotations

import logging
import re
import threading
import time
import warnings as _pywarnings
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from rafiki_tpu import config
from rafiki_tpu.sdk import compile_cache
from rafiki_tpu.utils import chaos

logger = logging.getLogger(__name__)

#: per-service warm-up reports for this process, keyed by service_id
#: (guarded-by _warm_lock) — the /healthz and stats-row source
WARMUP_STATS: Dict[str, Dict[str, Any]] = {}
_warm_lock = threading.Lock()

#: a program is an (informative name, zero-arg callable that triggers
#: its compile) pair
Program = Tuple[str, Callable[[], Any]]


#: jax's warning when a persistent-cache entry exists but cannot be read
#: (bit rot / truncation): it recompiles fresh but never overwrites the
#: damaged entry, so warm-up evicts it or every later boot stays cold
_CACHE_READ_ERR = re.compile(
    r"Error reading persistent compilation cache entry for '([^']+)'")


class WarmupError(RuntimeError):
    """A warm-up program failed hard (today: only injected chaos — real
    program failures are absorbed warn-only). Propagates out of worker
    startup so the service lands ERRORED instead of half-warm RUNNING."""


def run_warmup(service_id: str, scope: str,
               programs: Sequence[Program]) -> Dict[str, Any]:
    """Compile ``programs`` under the persistent cache, timing each, and
    record + return the boot's warm-state report. Call BEFORE
    ``ctx.ready()``: the whole point is that warm-up time is spent while
    the replica is still DEPLOYING and unroutable."""
    compile_cache.enable()
    report: Dict[str, Any] = {
        "service_id": service_id,
        "scope": scope,
        "warm": False,
        "compile_s": 0.0,
        "programs": {},
        "cache_hits": 0,
        "cache_misses": 0,
        "evicted": 0,
        "warnings": [],
        "ts": time.time(),
    }
    hits_before = compile_cache.hit_count()
    started = time.monotonic()
    for name, fn in programs:
        rule = chaos.hit(chaos.SITE_COMPILE, f"{scope}/{service_id}/{name}")
        if rule is not None:
            if rule.action == chaos.ACTION_DELAY:
                chaos.sleep_for(rule)
            elif rule.action == chaos.ACTION_CORRUPT:
                damaged = compile_cache.corrupt_entries()
                logger.warning("chaos corrupted %d compile-cache entries "
                               "before %s", damaged, name)
            else:  # error / drop: fail the boot, typed
                raise WarmupError(
                    f"injected warm-up failure at {scope}/{service_id}/"
                    f"{name} (chaos site=compile)")
        hits_pre = compile_cache.hit_count()
        t0 = time.monotonic()
        # record python warnings across the compile: jax reports an
        # unreadable (bit-rotted) cache entry that way, and warm-up is
        # the boot-time chokepoint where self-healing can happen
        with _pywarnings.catch_warnings(record=True) as caught:
            _pywarnings.simplefilter("always")
            try:
                fn()
            # lint: absorb(an optional warm-up program failing must not block serving — the replica just boots cold; recorded in the report)
            except Exception as e:
                msg = f"{name}: {type(e).__name__}: {e}"
                report["warnings"].append(msg)
                logger.warning(
                    "warm-up program %s failed (serving anyway): %s",
                    name, msg, exc_info=True)
        dt = time.monotonic() - t0
        for rec in caught:
            m = _CACHE_READ_ERR.search(str(rec.message))
            if m is None:
                # not ours: hand it back to the normal warning machinery
                _pywarnings.warn_explicit(rec.message, rec.category,
                                          rec.filename, rec.lineno)
                continue
            evicted = compile_cache.evict_entries(m.group(1))
            report["evicted"] += evicted
            logger.warning(
                "evicted %d unreadable compile-cache entr(y/ies) for %s "
                "(bit-rot self-heal: the next boot recompiles and "
                "rewrites them)", evicted, m.group(1))
        report["programs"][name] = round(dt, 4)
        if compile_cache.hit_count() == hits_pre:
            # no persistent-cache hit observed for this program: it was
            # compiled fresh (or tracing-only) — account the miss where
            # the compile time is actually measured
            report["cache_misses"] += 1
            compile_cache.record_misses(1, dt)
    report["compile_s"] = round(time.monotonic() - started, 4)
    report["cache_hits"] = compile_cache.hit_count() - hits_before
    # warm <=> the cache demonstrably served this boot, or — when hit
    # events are unavailable / the cache is off — the boot compiled fast
    # enough that a request parked behind it would not have noticed
    report["warm"] = bool(
        report["cache_hits"] > 0
        or report["compile_s"] <= config.COMPILE_WARM_THRESHOLD_S)
    report["cache"] = compile_cache.stats()
    with _warm_lock:
        WARMUP_STATS[service_id] = report
    logger.info(
        "warm-up %s (%s): warm=%s compile_s=%.3f hits=%d misses=%d",
        service_id, scope, report["warm"], report["compile_s"],
        report["cache_hits"], report["cache_misses"])
    return report


def note_first_program(service_id: str, scope: str, name: str,
                       seconds: float, hits_delta: int) -> None:
    """One-shot warm verdict for workers whose compiled programs only
    materialize mid-run (the trial worker: jit programs depend on the
    advisor's knob draw, so there is nothing to enumerate at boot).
    Records the boot's first program, warm <=> the persistent cache
    demonstrably served it OR it finished under the warm threshold.
    Subsequent calls for the same service are no-ops — only the FIRST
    program of a boot carries the cold-start verdict."""
    with _warm_lock:
        if service_id in WARMUP_STATS:
            return
        WARMUP_STATS[service_id] = {
            "service_id": service_id,
            "scope": scope,
            "warm": bool(hits_delta > 0
                         or seconds <= config.COMPILE_WARM_THRESHOLD_S),
            "compile_s": round(seconds, 4),
            "programs": {name: round(seconds, 4)},
            "cache_hits": max(hits_delta, 0),
            "cache_misses": 0 if hits_delta > 0 else 1,
            "warnings": [],
            "ts": time.time(),
            "cache": compile_cache.stats(),
        }
    if hits_delta <= 0:
        compile_cache.record_misses(1, seconds)


def warmup_stats(service_id: Optional[str] = None) -> Dict[str, Any]:
    """This process's warm-up reports (one service's, or all of them) —
    consumed by worker stats rows and the predictor's /healthz."""
    with _warm_lock:
        if service_id is not None:
            return dict(WARMUP_STATS.get(service_id, {}))
        return {sid: dict(r) for sid, r in WARMUP_STATS.items()}


def stats_row_fields(service_id: str) -> Dict[str, Any]:
    """The compact warm-state fields a worker merges into its periodic
    stats row (relayed to admin -> GET /fleet/health workers)."""
    with _warm_lock:
        r = WARMUP_STATS.get(service_id)
    if not r:
        return {}
    return {"warm": 1 if r["warm"] else 0,
            "compile_ms": int(r["compile_s"] * 1000),
            "compile_cache_hits": r["cache_hits"],
            "compile_cache_misses": r["cache_misses"]}


def reset_for_tests() -> None:
    with _warm_lock:
        WARMUP_STATS.clear()
