"""Train worker: the AutoML trial loop.

Parity with the reference's TrainWorker (reference rafiki/worker/train.py:37-132):
read job info -> budget check -> propose knobs -> instantiate model -> train ->
evaluate -> persist params -> record trial -> feed back the score -> repeat,
with crash handling (trial marked ERRORED, loop continues — the reference
instead exited the container and let swarm restart it) and termination
handling (in-flight trial marked TERMINATED on stop, reference train.py:134-148).

TPU-native differences:
- the worker is an *executor thread* with a chip grant; the model's mesh is
  built from exactly the granted devices (set_device_grant), so parallel
  trials occupy disjoint chips of the host slice;
- the advisor is shared per sub-train-job through AdvisorStore (keyed by
  sub_train_job_id, not worker service id), so parallel workers coordinate —
  fixing reference train.py:213;
- no per-boot pip install: dependencies are validated at model registration,
  and with RAFIKI_INSTALL_DEPS=1 provisioned ONCE per dependency-set into a
  cached prefix (sdk/deps.py) instead of the reference's per-container-boot
  install (reference scripts/start_worker.py:6-9).
"""

from __future__ import annotations

import logging
import os
import random
import time
import traceback
from typing import Any, Callable, Dict, Optional

from rafiki_tpu import config
from rafiki_tpu.advisor.advisor import AdvisorStore
from rafiki_tpu.constants import BudgetType, TrialStatus
from rafiki_tpu.db.database import Database
from rafiki_tpu.parallel.mesh import set_device_grant
from rafiki_tpu.placement.manager import ServiceContext
from rafiki_tpu.sdk import compile_cache
from rafiki_tpu.sdk.jax_backend import enable_persistent_compile_cache
from rafiki_tpu.sdk.artifact import write_artifact
from rafiki_tpu.sdk.log import ModelLogger, StopTrialEarly
from rafiki_tpu.sdk.model import load_model_class, population_capability
from rafiki_tpu.sdk.params import dump_params
from rafiki_tpu.worker.vmap_partition import partition_for_vmap
from rafiki_tpu.utils import chaos
from rafiki_tpu.utils.trace import Tracer, jax_profile
from rafiki_tpu.worker import faults, warmup
from rafiki_tpu.worker.faults import FaultKind, TrialChaosError, validate_score

logger = logging.getLogger(__name__)

# Event name the worker sends when its sub-train-job exhausts its budget
# (reference train.py:198-205).
EVENT_BUDGET_REACHED = "sub_train_job_budget_reached"

# Sent when the job fail-fast tripped: RAFIKI_TRIAL_FAULT_LIMIT
# consecutive user-class trial faults — the template is broken, and
# grinding the remaining budget through it would only produce more
# ERRORED rows. Payload: train_job_id, sub_train_job_id, fault_kind,
# reason. The admin marks the job ERRORED (with the reason on the row)
# and tears down its services.
EVENT_TRIAL_FAULT_LIMIT = "sub_train_job_fault_limit"

EventFn = Callable[[str, Dict[str, Any]], None]


class TrainWorker:
    """One trial executor for a sub-train-job."""

    def __init__(
        self,
        sub_train_job_id: str,
        db: Database,
        advisor_store: AdvisorStore,
        send_event: Optional[EventFn] = None,
        params_dir: Optional[str] = None,
    ):
        self._sub_id = sub_train_job_id
        self._db = db
        self._advisors = advisor_store
        self._send_event = send_event or (lambda name, payload: None)
        self._params_dir = params_dir or config.PARAMS_DIR
        # observations whose advisor feedback failed, awaiting retry —
        # BOUNDED (RAFIKI_PENDING_FEEDBACK_MAX, drop-oldest): an advisor
        # unreachable for hours must not grow this without limit
        self._pending_feedback: list = []
        self._feedback_drop_warned = False
        # trial fault tolerance (worker/faults.py): poison-knob
        # quarantine and the consecutive user-fault streak. The
        # signature counts are rebuilt from trial rows at startup, so
        # quarantine survives worker restarts; the streak is in-memory
        # on purpose — a restart is fresh evidence-gathering.
        self._knob_config = None
        self._quarantine: set = set()
        self._user_fault_sigs: Dict[str, int] = {}
        self._fault_streak = 0
        # vectorized trial execution (set per job in _loop): the
        # template's PopulationSpec when every gate passed, else None
        self._pop_spec = None
        self._vmap_k = 1

    def start(self, ctx: ServiceContext) -> None:
        """The trial loop; returns when budget is reached or stop is set."""
        set_device_grant(ctx.chips)
        # on-disk XLA executable reuse across trials AND worker processes —
        # the TPU-native answer to the reference's per-trial container boot
        # cost (reference scripts/start_worker.py:6-9)
        enable_persistent_compile_cache()
        try:
            self._loop(ctx)
        finally:
            set_device_grant(None)

    # -- internals ---------------------------------------------------------

    def _loop(self, ctx: ServiceContext) -> None:
        sub = self._db.get_sub_train_job(self._sub_id)
        assert sub is not None, f"no sub_train_job {self._sub_id}"
        job = self._db.get_train_job(sub["train_job_id"])
        model = self._db.get_model(sub["model_id"])
        assert job is not None and model is not None

        budget = job["budget"]
        max_trials = int(
            budget.get(BudgetType.MODEL_TRIAL_COUNT, config.DEFAULT_TRIAL_COUNT)
        )
        # optional wall-clock budget, measured from job start (a capability
        # the reference lacked: its only budgets were trials and GPUs)
        time_budget_h = budget.get(BudgetType.TIME_HOURS)
        deadline = (
            job["datetime_started"] + float(time_budget_h) * 3600
            if time_budget_h is not None
            else None
        )
        # ASHA early stopping (budget-opt-in): rung-check each trial's
        # per-epoch "loss" report against the sub-job's shared scheduler
        self._early_stop = bool(budget.get(BudgetType.EARLY_STOP, False))
        self._asha_min = int(budget.get(BudgetType.ASHA_MIN_EPOCHS, 1))
        self._asha_eta = int(budget.get(BudgetType.ASHA_ETA, 3))
        # deadlines enforced MID-trial through the same stop-check channel:
        # the job's TIME_HOURS deadline, and an optional per-trial wall cap
        # (TRIAL_TIMEOUT_S). Without these a runaway trial (bad knob draw
        # compiling into an enormous model) holds its executor forever —
        # the between-trials deadline check alone cannot interrupt it.
        self._job_deadline = deadline
        tt = budget.get(BudgetType.TRIAL_TIMEOUT_S)
        self._trial_timeout_s = float(tt) if tt is not None else None
        # provision declared dependencies before touching the template
        # (RAFIKI_INSTALL_DEPS=1 installs per dependency-set; default
        # validates and fails the executor fast — sdk/deps.py)
        from rafiki_tpu.sdk.deps import activate_prefix, ensure_dependencies

        self._deps_prefix = ensure_dependencies(model.get("dependencies"))
        activate_prefix(self._deps_prefix)
        clazz = load_model_class(model["model_file_bytes"], model["model_class"])
        # kept for the sandbox path: the child re-imports from bytes in its
        # own restricted process (sdk/sandbox.py)
        self._model_bytes = model["model_file_bytes"]
        self._model_class = model["model_class"]
        knob_config = clazz.get_knob_config()
        # Vectorized trial execution (vmap-over-knobs): when the template
        # advertises a PopulationSpec, drain K proposals per round and
        # train each shape-compatible bucket as ONE PopulationTrainer
        # program on this executor's chip grant — K trials for roughly
        # one trial's dispatch/overhead cost on underutilized chips.
        # Every gate below degrades to the unchanged scalar path.
        self._pop_spec = population_capability(clazz)
        vk = budget.get(BudgetType.TRIAL_VMAP_K)
        self._vmap_k = int(vk) if vk is not None else int(config.TRIAL_VMAP_K)
        if self._pop_spec is not None:
            from rafiki_tpu.sdk.sandbox import sandbox_enabled

            if not config.TRIAL_VMAP:
                self._pop_spec = None  # operator kill switch
            elif self._vmap_k < 2:
                self._pop_spec = None  # a population of one is a trial
            elif sandbox_enabled():
                # the sandbox runs one restricted child per trial; a
                # population shares one process by construction — scalar
                # until a population-aware sandbox child exists
                logger.info("RAFIKI_SANDBOX=1: vectorized trial execution "
                            "disabled; trials run scalar in children")
                self._pop_spec = None
            elif not set(self._pop_spec.dynamic_knobs) <= set(knob_config):
                logger.warning(
                    "population_spec dynamic knobs %s are not all in the "
                    "knob config %s; trials run scalar",
                    self._pop_spec.dynamic_knobs, sorted(knob_config))
                self._pop_spec = None
        advisor_id = self._advisors.create_advisor(
            knob_config, advisor_id=self._sub_id
        )
        self._db.update_sub_train_job_advisor(self._sub_id, advisor_id)
        ctx.ready()  # job info read + model class loaded: startup succeeded

        all_trials = self._db.get_trials_of_sub_train_job(self._sub_id)

        # Fault-tolerance state rebuild: poison-knob signatures with
        # enough recorded user-class faults are quarantined from the
        # first proposal of this incarnation — a restart must not spend
        # fresh budget re-learning which region crashes.
        self._knob_config = knob_config
        self._user_fault_sigs = faults.poison_signature_counts(
            all_trials, knob_config)
        k = max(int(config.TRIAL_QUARANTINE_K), 1)
        self._quarantine = {s for s, n in self._user_fault_sigs.items()
                            if n >= k}
        if self._quarantine:
            faults.record_quarantine(self._sub_id, self._quarantine)
            logger.warning("%d poison-knob signature(s) quarantined from "
                           "recorded trial faults", len(self._quarantine))

        # Crash recovery, part 1: if the advisor session is fresh (its
        # process died too — in-process store, or an admin restart), rebuild
        # the GP from the completed trials already in the store; otherwise
        # the remaining budget would be proposed from the prior as if no
        # trial had ever run. Atomic + empty-only on the store side, so
        # concurrently restarted siblings can't double-feed. Infeasible
        # observations (USER/TIMEOUT/INVALID_SCORE-errored trials) ride
        # the same replay so the GP also relearns which regions crash.
        scored = [(t["knobs"], t["score"]) for t in all_trials
                  if t["status"] == TrialStatus.COMPLETED
                  and t["score"] is not None]
        infeasible = [(t["knobs"], t["fault_kind"]) for t in all_trials
                      if faults.is_infeasible_row(t)]
        if scored or infeasible:
            try:
                if self._advisors.replay_feedback(advisor_id, scored,
                                                  infeasible=infeasible):
                    logger.info("replayed %d completed + %d infeasible "
                                "trials into advisor %s", len(scored),
                                len(infeasible), advisor_id)
            except TypeError:
                # an advisor store predating the infeasible signal
                try:
                    self._advisors.replay_feedback(advisor_id, scored)
                except Exception:
                    logger.warning("advisor replay failed; proposals start "
                                   "from the prior", exc_info=True)
            except Exception:
                logger.warning("advisor replay failed; proposals start from "
                               "the prior", exc_info=True)

        # Crash recovery, part 2: trials left RUNNING by a killed
        # predecessor of this service (a restarted worker keeps its service
        # id) are re-run under the SAME trial id and knobs — a template that
        # feeds ``checkpoint_path`` to fit() resumes from its last epoch
        # rather than from scratch (the reference discarded all progress,
        # reference worker/train.py:122-132).
        for stale in all_trials:
            if ctx.stopping:
                return
            if (stale["status"] != TrialStatus.RUNNING
                    or stale["worker_id"] != ctx.service_id):
                continue
            if deadline is not None and time.time() >= deadline:
                # the time budget expired while this trial was down: it
                # will never run — release its budget slot (the main loop
                # reports budget-reached right after)
                logger.info("time budget spent; terminating stale trial %s",
                            stale["id"])
                self._db.mark_trial_as_terminated(stale["id"])
                self._cleanup_ckpt(stale["id"])
                continue
            logger.info("resuming stale trial %s after worker restart",
                        stale["id"])
            if not self._execute_trial(ctx, clazz, job, advisor_id,
                                       stale["id"], stale["knobs"],
                                       start_attempt=int(
                                           stale.get("attempt") or 0)):
                return

        while not ctx.stopping:
            # shared budget accounting through the DB (reference
            # train.py:227-232) — but the reserve is ATOMIC (count + insert
            # in one transaction, db.reserve_trial): the reference's
            # check-then-create let N parallel workers overshoot the trial
            # budget by up to N-1
            over_time = deadline is not None and time.time() >= deadline
            if self._pop_spec is not None and not over_time:
                verdict = self._population_round(
                    ctx, clazz, job, model, advisor_id, max_trials)
                if verdict == "stop":
                    return
                if verdict == "budget":
                    self._send_event(EVENT_BUDGET_REACHED, {
                        "sub_train_job_id": self._sub_id,
                        "train_job_id": job["id"],
                    })
                    return
                continue
            trial = None
            tracer = Tracer("pending")
            if not over_time:
                with tracer.span("propose"):
                    try:
                        self._retry_pending_feedback(advisor_id)
                    except Exception:
                        logger.warning("pending feedback retry failed; "
                                       "proposing without it", exc_info=True)
                    knobs = self._propose_clear_of_quarantine(advisor_id)
                trial = self._db.reserve_trial(
                    self._sub_id, model["id"], knobs,
                    worker_id=ctx.service_id, max_trials=max_trials,
                )
            if trial is None:
                self._send_event(
                    EVENT_BUDGET_REACHED,
                    {
                        "sub_train_job_id": self._sub_id,
                        "train_job_id": job["id"],
                    },
                )
                return
            tracer.trace_id = trial["id"]
            if not self._execute_trial(ctx, clazz, job, advisor_id,
                                       trial["id"], knobs, tracer=tracer):
                return

    def _execute_trial(self, ctx, clazz, job, advisor_id: str,
                       trial_id: str, knobs, tracer=None,
                       start_attempt: int = 0) -> bool:
        """Run one trial end to end: per-trial logger + stop-check wiring,
        train/evaluate/persist, and terminal bookkeeping. Shared by the
        stale-resume path and the main loop. Returns False when the worker
        is exiting its loop — stopping (trial TERMINATED) or job
        fail-fast (RAFIKI_TRIAL_FAULT_LIMIT tripped).

        Failures run through the fault taxonomy (worker/faults.py):
        infra-class kinds (INFRA/MEM/STALL) re-run under the SAME trial
        id with jittered backoff up to RAFIKI_TRIAL_RETRY_MAX — no extra
        budget slot is consumed (the row is reused), and a template that
        keeps a checkpoint resumes mid-trial. User-class kinds
        (USER/TIMEOUT/INVALID_SCORE) are terminal: the trial is ERRORED
        with its kind + truncated traceback on the row, the budget slot
        is consumed (as before), and the advisor receives an infeasible
        observation so the proposal distribution steers away (the
        reference instead exited the worker, reference train.py:122-132,
        and this repo previously told the advisor nothing)."""
        trial_logger = ModelLogger()
        trial_logger.set_sink(
            lambda line, _tid=trial_id: self._db.add_trial_log(_tid, line))
        tracer = tracer or Tracer(trial_id)
        retry_max = max(int(config.TRIAL_RETRY_MAX), 0)
        attempt = max(int(start_attempt), 0)
        while True:
            # fresh stop-check per attempt: the TRIAL_TIMEOUT_S clock
            # measures THIS run of the template, not the sum of retries
            self._install_stop_check(trial_logger, advisor_id, trial_id)
            try:
                self._chaos_trial(trial_id)
                t_trial = time.monotonic()
                hits_before = compile_cache.hit_count()
                score, params_path = self._run_trial(
                    clazz, knobs, job, trial_id, trial_logger, tracer)
                # the boot's FIRST completed trial carries the cold-start
                # verdict: cache hits mean its jit programs loaded from
                # the persistent cache instead of compiling (the r5
                # cold-compile collapse, measured per boot)
                warmup.note_first_program(
                    ctx.service_id, self._sub_id, "first_trial",
                    time.monotonic() - t_trial,
                    compile_cache.hit_count() - hits_before)
                # feedback BEFORE mark-complete: a sibling restarting in
                # between sees COMPLETED only once the observation is in
                # the GP, so its empty-only replay can't double-feed (the
                # reverse window re-runs the trial at worst — a duplicate
                # noisy observation, which the GP tolerates). A feedback
                # failure must not cost the finished trial its result —
                # _feedback_best_effort queues it. A stop signal that
                # lands after the work finished does NOT discard the
                # result: the score and params exist, persisting them is
                # free, and only the loop exits early.
                self._feedback_best_effort(advisor_id, knobs, score)
                self._db.mark_trial_as_complete(trial_id, score, params_path)
                self._fault_streak = 0
                faults.record_counter(self._sub_id,
                                      "consecutive_user_faults", 0,
                                      absolute=True)
                return not ctx.stopping
            except Exception as e:
                if ctx.stopping:
                    self._db.mark_trial_as_terminated(trial_id)
                    self._cleanup_ckpt(trial_id)
                    return False
                kind, detail = faults.classify_failure(e)
                logger.error("trial %s fault %s (attempt %d):\n%s",
                             trial_id, kind, attempt, detail)
                if kind in faults.RETRYABLE_KINDS and attempt < retry_max:
                    # same trial id, same knobs, same budget slot; the
                    # attempt counter lives on the ROW, so the bound
                    # holds across worker restarts too
                    attempt = self._db.record_trial_fault(
                        trial_id, kind, detail)
                    faults.record_fault(self._sub_id, kind, retried=True)
                    trial_logger.set_stop_check(None)
                    self._retry_backoff(ctx, attempt)
                    if ctx.stopping:
                        self._db.mark_trial_as_terminated(trial_id)
                        self._cleanup_ckpt(trial_id)
                        return False
                    logger.info("retrying trial %s (attempt %d/%d) after "
                                "%s fault", trial_id, attempt, retry_max,
                                kind)
                    continue
                self._db.mark_trial_as_errored(trial_id, kind, detail)
                self._cleanup_ckpt(trial_id)
                faults.record_fault(self._sub_id, kind)
                if kind in faults.INFEASIBLE_KINDS or \
                        kind == FaultKind.MEM:
                    # terminal MEM (retries exhausted) is knob-driven
                    # too — steer the advisor away and count toward
                    # quarantine; only user-class kinds march the job
                    # fail-fast streak (repeated MEM on distinct knobs
                    # reads as host pressure, not a broken template)
                    self._feedback_infeasible_best_effort(
                        advisor_id, knobs, kind, trial_id=trial_id)
                    if not self._note_user_fault(
                            job, trial_id, knobs, kind,
                            streak=kind in faults.INFEASIBLE_KINDS):
                        return False  # job fail-fast: exit the loop
                return True

    def _chaos_trial(self, trial_id: str) -> None:
        """RAFIKI_CHAOS site=trial: the drillable fault chokepoint —
        every retry/classification path is exercisable in CPU tier-1
        tests without a real flaky host (docs/failure-model.md)."""
        rule = chaos.hit(chaos.SITE_TRIAL, f"{self._sub_id} {trial_id}")
        if rule is None:
            return
        if rule.action == chaos.ACTION_DELAY:
            chaos.sleep_for(rule)
            return
        if rule.action == chaos.ACTION_OOM:
            raise MemoryError("chaos-injected trial OOM (site=trial)")
        raise TrialChaosError(
            "chaos-injected transient trial fault (site=trial)")

    def _retry_backoff(self, ctx, attempt: int) -> None:
        """Exponential backoff with full jitter before an infra-retry
        (uniform in [0, min(base * 2^(n-1), 30 s)] — the cap bounds the
        realized sleep, not just the pre-jitter value), responsive to
        the stop signal (waits on the stop event, never a blind
        sleep)."""
        base = max(float(config.TRIAL_RETRY_BACKOFF_S), 0.0)
        ceiling = min(base * (2 ** max(attempt - 1, 0)), 30.0)
        if ceiling > 0:
            ctx.stop_event.wait(random.uniform(0, ceiling))

    def _note_user_fault(self, job, trial_id: str, knobs,
                         kind: str, streak: bool = True) -> bool:
        """Poison-knob quarantine + job fail-fast bookkeeping after a
        terminal poison fault. ``streak=False`` (terminal MEM) counts
        toward quarantine only, never the fail-fast streak. Returns
        False when the consecutive-fault limit tripped and the job was
        errored (the caller exits)."""
        sig = faults.knob_signature(self._knob_config, knobs)
        self._user_fault_sigs[sig] = self._user_fault_sigs.get(sig, 0) + 1
        k = max(int(config.TRIAL_QUARANTINE_K), 1)
        if (self._user_fault_sigs[sig] >= k
                and sig not in self._quarantine):
            self._quarantine.add(sig)
            faults.record_quarantine(self._sub_id, [sig])
            logger.warning(
                "knob signature %s quarantined after %d poison faults "
                "(RAFIKI_TRIAL_QUARANTINE_K=%d); matching proposals "
                "will be re-proposed", sig,
                self._user_fault_sigs[sig], k)
        if not streak:
            return True
        self._fault_streak += 1
        faults.record_counter(self._sub_id, "consecutive_user_faults",
                              self._fault_streak, absolute=True)
        limit = int(config.TRIAL_FAULT_LIMIT)
        if limit <= 0 or self._fault_streak < limit:
            return True
        reason = (
            f"{self._fault_streak} consecutive user-class trial faults "
            f"(RAFIKI_TRIAL_FAULT_LIMIT={limit}); last: {kind} on trial "
            f"{trial_id} — template broken at every proposed knob "
            f"combination, failing the job early instead of burning the "
            f"remaining budget")
        logger.error("train job %s fail-fast: %s", job["id"], reason)
        # record the typed reason directly (works headless), then tell
        # the admin so it tears down sibling workers; the guarded
        # transition makes the double-mark harmless
        self._db.mark_train_job_as_errored(job["id"], FaultKind.USER,
                                           reason)
        self._send_event(EVENT_TRIAL_FAULT_LIMIT, {
            "train_job_id": job["id"],
            "sub_train_job_id": self._sub_id,
            "fault_kind": FaultKind.USER,
            "reason": reason,
        })
        return False

    def _propose_clear_of_quarantine(self, advisor_id: str, knobs=None):
        """Propose knobs, re-proposing (bounded) while the draw matches
        a quarantined poison signature. Each rejection ALSO feeds the
        advisor an infeasible observation at the rejected point, so the
        GP's penalty mass grows until the region stops being proposed —
        the loop converges instead of fighting the optimizer forever.
        After RAFIKI_TRIAL_REPROPOSE_MAX rejections the last draw is
        accepted (with a warning): a mostly-quarantined search space
        must degrade to slow progress, never to a spinning worker.
        ``knobs`` seeds the loop with an already-made draw (the batch
        path filters each of its K draws through the same rule)."""
        if knobs is None:
            knobs = self._advisors.propose(advisor_id)
        if not self._quarantine:
            return knobs
        limit = max(int(config.TRIAL_REPROPOSE_MAX), 0)
        for rejections in range(limit + 1):
            sig = faults.knob_signature(self._knob_config, knobs)
            if sig not in self._quarantine:
                return knobs
            if rejections == limit:
                break  # this draw IS quarantined and the budget is out
            faults.record_counter(self._sub_id, "reproposals")
            logger.info("proposal matches quarantined signature %s; "
                        "re-proposing", sig)
            self._feedback_infeasible_best_effort(advisor_id, knobs,
                                                  FaultKind.USER)
            knobs = self._advisors.propose(advisor_id)
        logger.warning(
            "proposal still quarantined after %d re-proposals "
            "(RAFIKI_TRIAL_REPROPOSE_MAX); accepting it — most of the "
            "search space may be poisoned", limit)
        return knobs

    # -- vectorized trial execution (vmap-over-knobs) ----------------------

    def _propose_batch_clear_of_quarantine(self, advisor_id: str, k: int):
        """Drain K proposals in one advisor call (the GP spreads them via
        constant-liar fantasies), then run each draw through the same
        quarantine filter the scalar path uses. Advisor stores predating
        propose_batch fall back to K single proposals."""
        draws = None
        fn = getattr(self._advisors, "propose_batch", None)
        if fn is not None:
            try:
                draws = fn(advisor_id, k)
            except Exception:
                logger.warning("propose_batch failed; falling back to "
                               "single proposals", exc_info=True)
        if draws is None:
            draws = [self._advisors.propose(advisor_id) for _ in range(k)]
        if not self._quarantine:
            return draws
        return [self._propose_clear_of_quarantine(advisor_id, knobs=d)
                for d in draws]

    def _population_round(self, ctx, clazz, job, model,
                          advisor_id: str, max_trials: int) -> str:
        """One vectorized round: drain up to K proposals, bucket them by
        program shape (worker/vmap_partition.py), atomically reserve a
        trial ROW per member (the PR-5 budget contract is untouched —
        reserve_trial's count+insert transaction is still the only
        authority, so MODEL_TRIAL_COUNT=N yields exactly N rows no
        matter how K divides N), and train each bucket as one
        PopulationTrainer program. Singleton buckets run the scalar
        path. Returns "stop" (worker exiting), "budget" (caller sends
        the budget-reached event), or "ok" (next round)."""
        try:
            self._retry_pending_feedback(advisor_id)
        except Exception:
            logger.warning("pending feedback retry failed; proposing "
                           "without it", exc_info=True)
        # clamp the drain by the remaining budget (best-effort count; the
        # per-member reserve below stays authoritative) so a nearly-spent
        # job doesn't strand K-1 never-scored constant-liar fantasies in
        # the shared GP
        live = sum(1 for t in self._db.get_trials_of_sub_train_job(
            self._sub_id) if t["status"] != TrialStatus.TERMINATED)
        remaining = max_trials - live
        if remaining <= 0:
            return "budget"
        k = min(self._vmap_k, remaining,
                max(int(self._pop_spec.max_members), 1))
        draws = self._propose_batch_clear_of_quarantine(
            advisor_id, max(k, 1))
        buckets = partition_for_vmap(draws, self._pop_spec.dynamic_knobs,
                                     self._pop_spec.max_members)
        budget_out = False
        for bucket in buckets:
            if ctx.stopping:
                return "stop"
            members = []
            for knobs in bucket:
                trial = self._db.reserve_trial(
                    self._sub_id, model["id"], knobs,
                    worker_id=ctx.service_id, max_trials=max_trials)
                if trial is None:
                    budget_out = True
                    break
                members.append((trial["id"], knobs))
            if members:
                if len(members) == 1:
                    ok = self._execute_trial(ctx, clazz, job, advisor_id,
                                             members[0][0], members[0][1])
                else:
                    ok = self._execute_population_trial(
                        ctx, clazz, job, advisor_id, members)
                if not ok:
                    return "stop"
            if budget_out:
                return "budget"
        return "ok"

    def _execute_population_trial(self, ctx, clazz, job, advisor_id: str,
                                  members) -> bool:
        """Run one vmapped batch end to end: train all members as one
        program, evaluate all members, then settle each member's trial
        row INDIVIDUALLY — per-member scores feed the advisor one by
        one, a member whose score fails validation becomes a typed
        INVALID_SCORE fault + infeasible observation for that member
        only (never a batch abort), and ASHA rungs are reported per
        member. A batch-LEVEL failure (template crash, OOM, chaos)
        falls back to scalar execution of every member, so the full
        fault taxonomy — same-id infra retries included — applies
        exactly as if the batch had never been tried. Returns False
        when the worker is exiting its loop."""
        lead_id = members[0][0]
        trial_logger = ModelLogger()
        # the shared training log lands on the LEAD member's row; sibling
        # rows still carry their own knobs/score/params/fault columns
        trial_logger.set_sink(
            lambda line, _tid=lead_id: self._db.add_trial_log(_tid, line))
        tracer = Tracer(lead_id)
        self._install_population_stop_check(trial_logger, advisor_id,
                                            [tid for tid, _ in members])
        try:
            self._chaos_trial(lead_id)
            results = self._run_population_trial(
                clazz, members, job, trial_logger, tracer)
        except Exception:
            if ctx.stopping:
                for tid, _ in members:
                    self._db.mark_trial_as_terminated(tid)
                    self._cleanup_ckpt(tid)
                return False
            logger.warning(
                "population batch %s failed; re-running its %d members "
                "as scalar trials (same ids, full fault taxonomy):\n%s",
                lead_id, len(members), traceback.format_exc())
            self._cleanup_ckpt(lead_id)
            for idx, (tid, knobs) in enumerate(members):
                if ctx.stopping:
                    # never-started siblings must not stay RUNNING
                    self._terminate_members(members[idx:])
                    return False
                if not self._execute_trial(ctx, clazz, job, advisor_id,
                                           tid, knobs):
                    self._terminate_members(members[idx + 1:])
                    return False
            return not ctx.stopping
        # settle COMPLETED members first (pure DB writes): a blocking
        # scalar re-run or a fail-fast verdict below must never discard a
        # sibling's already-finished, already-persisted work
        for tid, knobs, score, params_path, err in results:
            if err is None:
                # same ordering contract as the scalar path: feedback
                # BEFORE mark-complete, so a restarting sibling's
                # empty-only replay can't double-feed
                self._feedback_best_effort(advisor_id, knobs, score)
                self._db.mark_trial_as_complete(tid, score, params_path)
                self._fault_streak = 0
                faults.record_counter(self._sub_id,
                                      "consecutive_user_faults", 0,
                                      absolute=True)
        faulted = [r for r in results if r[4] is not None]
        for idx, (tid, knobs, _, _, err) in enumerate(faulted):
            kind, detail = faults.classify_failure(err)
            if kind in faults.RETRYABLE_KINDS:
                # a platform fault on one member (params persist I/O)
                # is not a verdict on its knobs OR its siblings:
                # re-run just this member scalar under the same trial
                # id — the full taxonomy applies (same-id infra
                # retries, no budget burn)
                logger.warning(
                    "population member %s hit retryable %s fault; "
                    "re-running it as a scalar trial:\n%s",
                    tid, kind, detail)
                if not self._execute_trial(ctx, clazz, job,
                                           advisor_id, tid, knobs):
                    self._terminate_members(
                        [(t, k) for t, k, _, _, _ in faulted[idx + 1:]])
                    return False
                continue
            logger.error("population member %s fault %s:\n%s",
                         tid, kind, detail)
            self._db.mark_trial_as_errored(tid, kind, detail)
            faults.record_fault(self._sub_id, kind)
            self._feedback_infeasible_best_effort(
                advisor_id, knobs, kind, trial_id=tid)
            if not self._note_user_fault(job, tid, knobs, kind):
                self._terminate_members(
                    [(t, k) for t, k, _, _, _ in faulted[idx + 1:]])
                return False  # job fail-fast tripped
        return not ctx.stopping

    def _terminate_members(self, members) -> None:
        """Mark a batch's not-yet-settled members TERMINATED when the
        worker exits mid-settle (stop signal or job fail-fast): a
        reserved row must never outlive its batch as a forever-RUNNING
        orphan."""
        for tid, _ in members:
            try:
                self._db.mark_trial_as_terminated(tid)
                self._cleanup_ckpt(tid)
            except Exception:
                logger.warning("failed to terminate batch member %s",
                               tid, exc_info=True)

    def _run_population_trial(self, clazz, members, job,
                              trial_logger: ModelLogger,
                              tracer: Optional[Tracer] = None) -> list:
        """The vmapped analogue of _run_trial: one model instance
        (constructed with the lead member's knobs — all members share
        the program-shaping knobs by bucketing), one train_population
        call, one evaluate_population call, then per-member score
        validation and params persistence. Returns
        ``[(trial_id, knobs, score, params_path, error)]`` with exactly
        one entry per member; ``error`` is the member's typed fault (an
        InvalidScoreError) and the other fields None when set. The
        stacked checkpoint rides the lead member's .ckpt slot through
        the PR-4 artifact frame, so a restarted batch resumes mid-trial
        like a scalar trial would (a resume with a different K is typed
        artifact corruption -> fresh start)."""
        lead_id = members[0][0]
        tracer = tracer or Tracer(lead_id)
        member_knobs = [dict(knobs) for _, knobs in members]
        model = clazz(**member_knobs[0])
        model.logger = trial_logger
        os.makedirs(self._params_dir, exist_ok=True)
        model.checkpoint_path = os.path.join(
            self._params_dir, f"{lead_id}.ckpt")
        try:
            try:
                with jax_profile(), tracer.span("train"):
                    model.train_population(job["train_dataset_uri"],
                                           member_knobs)
            except StopTrialEarly:
                trial_logger.log(
                    "population batch stopped early by scheduler")
            trial_logger.set_stop_check(None)
            with tracer.span("evaluate"):
                raw_scores = model.evaluate_population(
                    job["test_dataset_uri"])
            if raw_scores is None or len(raw_scores) != len(members):
                # a template answering the wrong number of scores broke the
                # population contract: fail the BATCH (caller falls back
                # to scalar, where the taxonomy judges each member alone)
                raise faults.TrialFault(
                    f"evaluate_population returned "
                    f"{0 if raw_scores is None else len(raw_scores)} "
                    f"score(s) for {len(members)} members",
                    kind=FaultKind.USER)
            results = []
            with tracer.span("persist_params"):
                for i, (tid, knobs) in enumerate(members):
                    try:
                        score = validate_score(raw_scores[i])
                    except faults.TrialFault as e:
                        # per-member fault isolation: THIS member is
                        # infeasible; its siblings' scores stand
                        results.append((tid, knobs, None, None, e))
                        continue
                    params_path = os.path.join(
                        self._params_dir, f"{tid}.params")
                    try:
                        # dump + write both per-member: a template whose
                        # dump_member_parameters raises for ONE member
                        # (user code), or a disk blip on one artifact
                        # (platform), fails that member alone — siblings
                        # keep their completed, persisted work. The
                        # caller classifies: retryable kinds re-run the
                        # member scalar (same id, no budget burn),
                        # user-class kinds error it with infeasible
                        # feedback.
                        params_bytes = dump_params(
                            model.dump_member_parameters(i))
                        write_artifact(params_path, params_bytes)
                    except OSError as e:
                        results.append((tid, knobs, None, None,
                                        faults.TrialFault(
                                            f"params persist failed: {e}",
                                            kind=FaultKind.INFRA)))
                        continue
                    # lint: absorb(the exception is carried in results for per-member fault classification)
                    except Exception as e:
                        results.append((tid, knobs, None, None, e))
                        continue
                    results.append((tid, knobs, score, params_path, None))
            self._cleanup_ckpt(lead_id)
            return results
        finally:
            try:
                model.destroy()
            finally:
                try:
                    tracer.save()
                    trial_logger.log(
                        "population batch phase breakdown",
                        members=float(len(members)), **{
                            f"trace_{k}_s": round(v, 4)
                            for k, v in tracer.summary().items()
                        })
                except Exception:
                    logger.exception("failed to persist batch trace")

    def _install_population_stop_check(self, trial_logger: ModelLogger,
                                       advisor_id: str,
                                       member_ids: list) -> None:
        """The batch variant of _install_stop_check. Wall-clock caps
        (TRIAL_TIMEOUT_S, the job TIME_HOURS deadline) act on the whole
        batch — one program, one clock. ASHA rung accounting stays PER
        MEMBER: each member's ``member{k}_loss`` (PopulationTrainer.fit
        logs one per epoch) is reported under that member's own trial
        id, and the batch stops early only when EVERY member's verdict
        says stop — a population is competitive while any member is.
        Templates that log only the population-mean ``loss`` degrade to
        reporting that mean under each member's id (rung rows stay per
        trial, the signal is just shared)."""
        early_stop = getattr(self, "_early_stop", False)
        report = getattr(self._advisors, "report_rung", None)
        if early_stop and report is None:
            logger.warning("EARLY_STOP budget set but the advisor store "
                           "has no report_rung; rung checks disabled")
        job_deadline = getattr(self, "_job_deadline", None)
        trial_timeout = getattr(self, "_trial_timeout_s", None)
        if not ((early_stop and report is not None)
                or job_deadline is not None or trial_timeout is not None):
            return
        batch_start = time.time()

        def check(metrics: Dict[str, Any]) -> bool:
            now = time.time()
            if trial_timeout is not None \
                    and now - batch_start > trial_timeout:
                logger.info("population batch %s hit TRIAL_TIMEOUT_S=%.0f; "
                            "stopping", member_ids[0], trial_timeout)
                return True
            if job_deadline is not None and now >= job_deadline:
                logger.info("population batch %s crossed the job "
                            "TIME_HOURS deadline; stopping", member_ids[0])
                return True
            if not (early_stop and report is not None
                    and "epoch" in metrics):
                return False
            rung = int(metrics["epoch"]) + 1
            keep_any, reported = False, False
            for i, tid in enumerate(member_ids):
                value = metrics.get(f"member{i}_loss",
                                    metrics.get("loss"))
                if value is None:
                    continue
                reported = True
                try:
                    if report(advisor_id, tid, rung, value,
                              min_resource=self._asha_min,
                              eta=self._asha_eta):
                        keep_any = True
                except Exception:
                    logger.warning("ASHA rung report failed for member "
                                   "%s; keeping it", tid, exc_info=True)
                    keep_any = True
            return reported and not keep_any

        trial_logger.set_stop_check(check)

    def _feedback_best_effort(self, advisor_id: str, knobs, score) -> None:
        """Feed a trial score to the advisor, never letting an advisor
        failure destroy the trial result: the caller marks the trial
        COMPLETED right after. A failed observation is queued and retried
        before each later proposal (_retry_pending_feedback) — it cannot be
        recovered by replay_feedback, which only seeds *empty* sessions.
        The queue is bounded (RAFIKI_PENDING_FEEDBACK_MAX, drop-oldest):
        an advisor unreachable for a whole shift must cost observations,
        not memory."""
        try:
            self._retry_pending_feedback(advisor_id)
            self._advisors.get(advisor_id).feedback(knobs, score)
        except Exception:
            self._pending_feedback.append((knobs, score))
            logger.warning(
                "advisor feedback failed for %s (queued for retry):\n%s",
                advisor_id, traceback.format_exc())
            cap = max(int(config.PENDING_FEEDBACK_MAX), 1)
            if len(self._pending_feedback) > cap:
                dropped = len(self._pending_feedback) - cap
                del self._pending_feedback[:dropped]
                faults.record_counter(self._sub_id, "feedback_dropped",
                                      dropped)
                if not self._feedback_drop_warned:
                    self._feedback_drop_warned = True
                    logger.warning(
                        "pending advisor feedback exceeded "
                        "RAFIKI_PENDING_FEEDBACK_MAX=%d; dropping oldest "
                        "observations (warning once; drops counted in "
                        "training stats)", cap)

    def _feedback_infeasible_best_effort(self, advisor_id: str, knobs,
                                         kind: str,
                                         trial_id: Optional[str] = None
                                         ) -> None:
        """Best-effort infeasible signal: penalty points are advisory —
        a failure to deliver one is logged and DROPPED (never queued:
        unlike scores, losing one costs a little steering, not an
        observation). Tolerates advisor stores predating the signal."""
        fi = getattr(self._advisors, "feedback_infeasible", None)
        if fi is None:
            return
        try:
            fi(advisor_id, knobs, kind=kind, trial_id=trial_id)
        except Exception:
            logger.warning("infeasible feedback for %s dropped",
                           advisor_id, exc_info=True)

    def _install_stop_check(self, trial_logger: ModelLogger,
                            advisor_id: str, trial_id: str) -> None:
        """Wire a trial's logger to its in-flight stop conditions. Every
        METRICS report is a decision point; a verdict makes the next log()
        raise StopTrialEarly, which fit()/the trial runner treat as a
        normal (truncated) completion. Conditions, cheapest first:

        - per-trial wall cap (budget TRIAL_TIMEOUT_S),
        - the job's TIME_HOURS deadline (otherwise only enforced between
          trials — an in-flight runaway would sail past it),
        - ASHA rung checks on per-epoch "loss" (budget EARLY_STOP; advisor
          stores without report_rung silently disable this — never fail a
          trial over it)."""
        early_stop = getattr(self, "_early_stop", False)
        report = getattr(self._advisors, "report_rung", None)
        if early_stop and report is None:
            logger.warning("EARLY_STOP budget set but the advisor store "
                           "has no report_rung; rung checks disabled")
        job_deadline = getattr(self, "_job_deadline", None)
        trial_timeout = getattr(self, "_trial_timeout_s", None)
        if not ((early_stop and report is not None)
                or job_deadline is not None or trial_timeout is not None):
            return
        trial_start = time.time()

        def check(metrics: Dict[str, Any]) -> bool:
            now = time.time()
            if trial_timeout is not None and now - trial_start > trial_timeout:
                logger.info("trial %s hit TRIAL_TIMEOUT_S=%.0f; stopping",
                            trial_id, trial_timeout)
                return True
            if job_deadline is not None and now >= job_deadline:
                logger.info("trial %s crossed the job TIME_HOURS deadline; "
                            "stopping", trial_id)
                return True
            if (early_stop and report is not None
                    and "loss" in metrics and "epoch" in metrics):
                try:
                    return not report(
                        advisor_id, trial_id, int(metrics["epoch"]) + 1,
                        metrics["loss"], min_resource=self._asha_min,
                        eta=self._asha_eta)
                except Exception:
                    logger.warning("ASHA rung report failed; continuing "
                                   "trial", exc_info=True)
            return False

        trial_logger.set_stop_check(check)

    def _retry_pending_feedback(self, advisor_id: str) -> None:
        """Flush observations whose original feedback failed (advisor
        briefly unreachable). Called before proposing and before new
        feedback so the GP sees every completed trial, in order."""
        while self._pending_feedback:
            knobs, score = self._pending_feedback[0]
            self._advisors.get(advisor_id).feedback(knobs, score)
            self._pending_feedback.pop(0)

    def _run_trial_sandboxed(
        self,
        knobs: Dict[str, Any],
        job: Dict[str, Any],
        trial_id: str,
        trial_logger: ModelLogger,
        tracer: Optional[Tracer] = None,
    ) -> tuple:
        """Sandbox path (RAFIKI_SANDBOX=1): the untrusted slice — model
        import, train, evaluate, dump — runs in a restricted child
        (sdk/sandbox.py: env scrub, cwd jail, rlimits, uid drop under
        root); this trusted side forwards its log stream to the trial
        sink, applies the same mid-trial stop checks on METRICS records,
        and persists the returned params bytes itself. The child never
        sees the store, other trials' params, or admin credentials."""
        from rafiki_tpu import config as rconfig
        from rafiki_tpu.sdk.sandbox import make_jail, run_trial_sandboxed

        tracer = tracer or Tracer(trial_id)
        os.makedirs(self._params_dir, exist_ok=True)
        os.chmod(self._params_dir, 0o700)  # owner-only: jailed uids locked out
        jail = make_jail(rconfig.WORKDIR, trial_id)
        # the logger sink writes lines to the store; stop checks ride the
        # same METRICS records as the in-process path
        stop_check = getattr(trial_logger, "_stop_check", None)
        sink = (lambda line: trial_logger._sink(line)) if \
            trial_logger._sink else (lambda line: None)
        try:
            with tracer.span("train"):
                score, params_bytes = run_trial_sandboxed(
                    self._model_bytes, self._model_class, knobs,
                    job["train_dataset_uri"], job["test_dataset_uri"],
                    jail, on_log_line=sink, stop_check=stop_check,
                    timeout_s=getattr(self, "_trial_timeout_s", None),
                    extra_pythonpath=getattr(self, "_deps_prefix", None),
                )
            # NaN/inf survives the child's float() cast and the JSON
            # pipe — gate it here so it becomes a typed INVALID_SCORE
            # fault, never a poisoned GP observation
            score = validate_score(score)
            with tracer.span("persist_params"):
                params_path = os.path.join(
                    self._params_dir, f"{trial_id}.params")
                # atomic + checksummed (sdk/artifact.py): a crash mid-write
                # or later bit rot surfaces as a typed ArtifactCorruptError
                # at download/deploy, never a deserialize traceback
                try:
                    write_artifact(params_path, params_bytes, mode=0o600)
                except OSError as e:
                    # trusted-side I/O (full disk, yanked volume) — the
                    # platform's fault, never the template's knobs
                    raise faults.TrialFault(
                        f"params persist failed: {e}",
                        kind=FaultKind.INFRA) from e
            import shutil

            shutil.rmtree(jail, ignore_errors=True)
            return score, params_path
        finally:
            try:
                tracer.save()
                trial_logger.set_stop_check(None)
                trial_logger.log("trial phase breakdown", **{
                    f"trace_{k}_s": round(v, 4)
                    for k, v in tracer.summary().items()
                })
            except Exception:
                logger.exception("failed to persist trial trace")

    def _cleanup_ckpt(self, trial_id: str) -> None:
        """Drop a trial's mid-trial checkpoint once the trial reached a
        terminal state it will never resume from (ERRORED/TERMINATED —
        only RUNNING trials are ever re-run). Success-path cleanup lives in
        _run_trial."""
        for suffix in (".ckpt", ".ckpt.tmp"):
            try:
                os.remove(os.path.join(self._params_dir,
                                       f"{trial_id}{suffix}"))
            except OSError:
                pass
        # sandbox-mode trials keep their checkpoint inside the jail
        from rafiki_tpu import config as rconfig
        from rafiki_tpu.sdk.sandbox import jail_path

        jail = jail_path(rconfig.WORKDIR, trial_id)
        if os.path.isdir(jail):
            import shutil

            shutil.rmtree(jail, ignore_errors=True)

    def _run_trial(
        self,
        clazz: type,
        knobs: Dict[str, Any],
        job: Dict[str, Any],
        trial_id: str,
        trial_logger: ModelLogger,
        tracer: Optional[Tracer] = None,
    ) -> tuple:
        from rafiki_tpu.sdk.sandbox import sandbox_enabled

        if sandbox_enabled():
            return self._run_trial_sandboxed(knobs, job, trial_id,
                                             trial_logger, tracer)
        tracer = tracer or Tracer(trial_id)
        model = clazz(**knobs)
        model.logger = trial_logger
        # per-trial checkpoint slot: templates that pass it to fit() get
        # resume-from-last-epoch when a crashed worker re-runs this trial
        os.makedirs(self._params_dir, exist_ok=True)
        model.checkpoint_path = os.path.join(
            self._params_dir, f"{trial_id}.ckpt")
        try:
            try:
                with jax_profile(), tracer.span("train"):
                    model.train(job["train_dataset_uri"])
            except StopTrialEarly:
                # templates with hand-rolled train loops surface the ASHA
                # verdict here (SDK-trainer templates never do — fit()
                # absorbs it); the truncated model still gets evaluated
                trial_logger.log("trial stopped early by scheduler")
            # the verdict is delivered; trace/trace-metric logs after this
            # must not re-raise
            trial_logger.set_stop_check(None)
            with tracer.span("evaluate"):
                # typed INVALID_SCORE fault for NaN/inf/non-numeric —
                # previously only ASHA's rung check looked at finiteness
                score = validate_score(model.evaluate(job["test_dataset_uri"]))
            with tracer.span("persist_params"):
                params_path = os.path.join(
                    self._params_dir, f"{trial_id}.params")
                # atomic + checksummed (sdk/artifact.py) — see the
                # sandboxed persist path for the rationale; trusted-side
                # I/O failures (full disk) are typed INFRA, not USER
                params_bytes = dump_params(model.dump_parameters())
                try:
                    write_artifact(params_path, params_bytes)
                except OSError as e:
                    raise faults.TrialFault(
                        f"params persist failed: {e}",
                        kind=FaultKind.INFRA) from e
            # the trial is complete: its mid-trial checkpoint is dead weight
            self._cleanup_ckpt(trial_id)
            return score, params_path
        finally:
            try:
                model.destroy()
            finally:
                # diagnostics only: a trace-persistence failure must never
                # turn a successful trial into ERRORED (or mask the real
                # exception of a failed one)
                try:
                    tracer.save()
                    # the phase breakdown also lands in the trial's metric
                    # stream so the existing log/plot plumbing surfaces it
                    trial_logger.log("trial phase breakdown", **{
                        f"trace_{k}_s": round(v, 4)
                        for k, v in tracer.summary().items()
                    })
                except Exception:
                    logger.exception("failed to persist trial trace")
