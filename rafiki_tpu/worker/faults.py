"""Trial fault taxonomy for the training plane.

Before this module, every trial failure looked the same: the worker
caught ``Exception``, logged a traceback nobody could query, marked the
trial ERRORED (terminal, reasonless), burned the budget slot, and told
the advisor nothing — so the GP happily re-proposed the same crashing
knob region, and one flaky host could grind a whole search budget into
ERRORED rows. Vizier (Golovin et al., KDD 2017) treats the
transient-vs-infeasible distinction as first-class advisor signal; this
module gives rafiki_tpu the same spine.

Fault kinds and their contracts (docs/failure-model.md,
"Training-plane faults"):

``INFRA``
    The platform failed the trial, not the template: sandbox spawn
    failure, child killed by a signal, chaos injection, transient
    store/advisor errors. Retried under the SAME trial id with jittered
    backoff (``RAFIKI_TRIAL_RETRY_MAX``), resuming from the trial's
    checkpoint when the template keeps one — the retry does NOT consume
    an extra budget slot (the trial row is reused).
``MEM``
    The trial exceeded its memory envelope: in-process ``MemoryError``,
    RLIMIT_AS ``MemoryError`` inside the sandbox child, or a
    SIGKILLed child under an active ``RAFIKI_SANDBOX_MEM_MB`` cap.
    Retried like INFRA (a sibling trial's transient pressure may have
    tipped it), but the kind is recorded so a template that *always*
    OOMs is visible as such.
``USER``
    The template's own code raised (an ``err`` frame from
    ``sandbox_child``, or any unclassified exception in-process).
    Terminal: consumes the budget slot, feeds the advisor an
    *infeasible* observation so proposals steer away, and counts toward
    poison-knob quarantine and job fail-fast.
``TIMEOUT``
    The trial blew through ``TRIAL_TIMEOUT_S`` and could not be
    truncated at a metrics decision point (a mute runaway); the sandbox
    watchdog terminated it. Terminal + infeasible, like USER — the knob
    draw is too expensive for this budget.
``STALL``
    The sandbox child went mute before producing its FIRST frame for
    ``RAFIKI_TRIAL_STALL_S`` (wedged import, deadlocked setup, a dead
    TPU tunnel) and was killed by the no-frame watchdog. Retried like
    INFRA — stalls are overwhelmingly environmental.
``INVALID_SCORE``
    ``evaluate()`` returned NaN/inf/non-float. Terminal + infeasible:
    the trial "finished" but its result is unusable as advisor signal
    (previously only ASHA's rung check looked at finiteness).
"""

from __future__ import annotations

import math
import threading
import traceback
from typing import Any, Dict, Iterable, List, Optional, Tuple


class FaultKind:
    INFRA = "INFRA"
    MEM = "MEM"
    USER = "USER"
    TIMEOUT = "TIMEOUT"
    STALL = "STALL"
    INVALID_SCORE = "INVALID_SCORE"

    ALL = (INFRA, MEM, USER, TIMEOUT, STALL, INVALID_SCORE)


# kinds the worker re-runs under the same trial id (no budget consumed);
# everything else is terminal and burns the slot
RETRYABLE_KINDS = (FaultKind.INFRA, FaultKind.MEM, FaultKind.STALL)

# kinds that are the *template's* doing at these knobs: terminal AND fed
# to the advisor as an infeasible observation so the proposal
# distribution steers away (Vizier-style)
INFEASIBLE_KINDS = (FaultKind.USER, FaultKind.TIMEOUT,
                    FaultKind.INVALID_SCORE)


def is_infeasible_row(trial: Dict[str, Any]) -> bool:
    """Should this trial ROW feed the advisor as infeasible (replay,
    quarantine rebuild)? ERRORED user-class kinds, plus ERRORED MEM — a
    knob region that kept OOMing through its whole retry budget is
    knob-driven (batch/model size), and the optimizer must steer away
    from it too. The status check matters: COMPLETED/RUNNING rows carry
    the kind of an ABSORBED transient fault, which is not a verdict on
    their knobs."""
    if trial.get("status") != "ERRORED":
        return False
    kind = trial.get("fault_kind")
    return kind in INFEASIBLE_KINDS or kind == FaultKind.MEM

# how much traceback survives onto the trial row (fault_detail) — enough
# to diagnose without scraping worker logs, bounded so a pathological
# repr can't bloat the store
FAULT_DETAIL_MAX = 2000


class TrialFault(Exception):
    """Base for typed trial failures; carries its taxonomy kind."""

    kind = FaultKind.INFRA

    def __init__(self, detail: str, kind: Optional[str] = None):
        super().__init__(detail)
        if kind is not None:
            self.kind = kind


class TrialChaosError(TrialFault):
    """RAFIKI_CHAOS site=trial action=error — the drillable stand-in for
    a transient platform fault at the trial-run chokepoint."""

    kind = FaultKind.INFRA


class InvalidScoreError(TrialFault):
    """evaluate() produced NaN/inf/non-castable — unusable as signal."""

    kind = FaultKind.INVALID_SCORE


def validate_score(raw: Any) -> float:
    """THE score gate: every path that turns an evaluate() result into a
    trial score goes through here, so NaN/inf/non-float is one typed
    fault instead of an arbitrary traceback (or, worse, a silently
    recorded NaN that poisons the GP's standardization)."""
    try:
        score = float(raw)
    except (TypeError, ValueError) as e:
        raise InvalidScoreError(
            f"evaluate() returned non-numeric {type(raw).__name__}: "
            f"{e}") from e
    if not math.isfinite(score):
        raise InvalidScoreError(f"evaluate() returned non-finite {score!r}")
    return score


def classify_failure(exc: BaseException) -> Tuple[str, str]:
    """Map a trial-execution exception to ``(fault_kind, detail)``.

    Typed faults (TrialFault and the sandbox's typed errors) carry their
    own kind; the remaining mapping is deliberately conservative —
    anything not provably the platform's fault is USER, because treating
    a template bug as INFRA would retry it forever at no budget cost."""
    detail = f"{type(exc).__name__}: {exc}"
    tb = traceback.format_exc()
    if tb and tb != "NoneType: None\n":
        detail = f"{detail}\n{tb}"
    detail = detail[-FAULT_DETAIL_MAX:]
    kind = getattr(exc, "kind", None)
    if kind in FaultKind.ALL:
        return kind, detail
    if isinstance(exc, MemoryError):
        return FaultKind.MEM, detail
    # transient control-plane trouble: store errors (chaos-injected OR
    # real — a locked sqlite file under concurrent workers, a brief
    # postgres outage surfacing through the trial-log sink), HTTP
    # transport failures to the admin (remote advisor), and the
    # recovering-503 — the trial itself may be fine, and classifying
    # these USER would feed bogus infeasible points and march the
    # fail-fast streak toward erroring a healthy job
    import sqlite3

    if isinstance(exc, sqlite3.OperationalError):
        return FaultKind.INFRA, detail
    try:
        import psycopg2

        if isinstance(exc, (psycopg2.OperationalError,
                            psycopg2.InterfaceError)):
            return FaultKind.INFRA, detail
    except ImportError:  # pragma: no cover - sqlite-only install
        pass
    try:
        from rafiki_tpu.db.database import MetadataStoreChaosError

        if isinstance(exc, MetadataStoreChaosError):
            return FaultKind.INFRA, detail
    except ImportError:  # pragma: no cover - partial install
        pass
    # NOT mapped: requests transport errors / the recovering-503. The
    # worker's own control-plane calls are already absorbed upstream
    # (advisor/remote.py _ride_out, _feedback_best_effort queueing), so
    # a RequestException reaching this classifier came from TEMPLATE
    # code running in-process (e.g. fetching a misconfigured dataset
    # URI) — classifying it INFRA would retry it for free, skip the
    # infeasible signal, and exempt a broken job from fail-fast.
    return FaultKind.USER, detail


# -- poison-knob signatures --------------------------------------------------

# quantization grid for "near-identical" knob vectors: each unit-cube
# coordinate rounds to 1/SIGNATURE_GRID — close draws (a GP circling a
# crashing basin) share a signature, distant ones never do
SIGNATURE_GRID = 8


def knob_signature(knob_config, knobs: Dict[str, Any]) -> str:
    """Stable signature of a knob assignment for quarantine matching.

    Encodes through the knobs' own unit-cube mapping (sdk/knob.py) and
    quantizes, so "near-identical" is measured in search space, not in
    raw values (1e-3 vs 1.1e-3 on an exp-scaled FloatKnob is the same
    cell; 1e-3 vs 1e-1 is not). Falls back to the sorted JSON of the
    raw knobs when no config is available (doctor-side grouping)."""
    if knob_config is not None:
        try:
            from rafiki_tpu.sdk.knob import knobs_to_unit

            u = knobs_to_unit(knob_config, knobs)
            cells = [int(round(float(x) * SIGNATURE_GRID)) for x in u]
            return "u:" + ",".join(str(c) for c in cells)
        # lint: absorb(unexpected knob shape falls through to the JSON signature)
        except Exception:  # unexpected knob shape: fall through to JSON
            pass
    import json

    return "j:" + json.dumps(knobs, sort_keys=True, default=str)


def poison_signature_counts(
    trials: Iterable[Dict[str, Any]],
    knob_config,
) -> Dict[str, int]:
    """Raw signature -> poison-fault count over ``trials`` (ERRORED
    rows with a user-class or MEM kind — is_infeasible_row). THE
    counting rule, shared by the worker's startup rebuild (which keeps
    the raw counts for incremental updates) and the doctor's store
    scan (which thresholds them via quarantined_signatures)."""
    counts: Dict[str, int] = {}
    for t in trials:
        if not is_infeasible_row(t):
            continue
        sig = knob_signature(knob_config, t.get("knobs") or {})
        counts[sig] = counts.get(sig, 0) + 1
    return counts


def quarantined_signatures(
    trials: Iterable[Dict[str, Any]],
    knob_config,
    threshold: int,
) -> Dict[str, int]:
    """Signatures with >= ``threshold`` poison faults among ``trials``."""
    counts = poison_signature_counts(trials, knob_config)
    return {s: n for s, n in counts.items() if n >= max(int(threshold), 1)}


# -- per-worker training-plane counters (fleet-health "training" section) ----

# sub_train_job_id -> counters; the training-plane twin of
# worker/inference.py's SERVING_STATS. In-process workers (thread
# placement / admin-embedded engines) update this dict directly and the
# admin's GET /fleet/health reads it; out-of-process workers' fault
# history is visible through the trial rows instead. BOUNDED: a
# long-lived admin runs jobs for weeks, and every sub-train-job ever
# seen must not leave a permanent entry — beyond the cap the
# least-recently-updated entries drop (their durable record stays in
# the trial rows).
TRAINING_STATS: Dict[str, Dict[str, Any]] = {}
_STATS_CAP = 256
_STATS_LOCK = threading.Lock()


def training_stats() -> Dict[str, Dict[str, Any]]:
    """Snapshot for the health endpoint (copy: callers may mutate)."""
    with _STATS_LOCK:
        return {
            k: {**v, "faults": dict(v.get("faults", {})),
                "quarantined": list(v.get("quarantined", []))}
            for k, v in TRAINING_STATS.items()
        }


def _stats_entry(sub_id: str) -> Dict[str, Any]:
    entry = TRAINING_STATS.pop(sub_id, None)
    if entry is None:
        entry = {
            "faults": {},            # fault kind -> count
            "retries": 0,            # infra-class re-runs (no budget burned)
            "quarantined": [],       # live poison-knob signatures
            "reproposals": 0,        # proposals rejected for quarantine
            "feedback_dropped": 0,   # pending-feedback overflow drops
            "consecutive_user_faults": 0,
        }
    # re-insert at the end: plain-dict insertion order IS the LRU order
    TRAINING_STATS[sub_id] = entry
    while len(TRAINING_STATS) > _STATS_CAP:
        TRAINING_STATS.pop(next(iter(TRAINING_STATS)))
    return entry


def record_fault(sub_id: str, kind: str, retried: bool = False) -> None:
    """Terminal faults land in the per-kind counters; absorbed
    (retried) transients count ONLY as retries — same split as the
    store-side fault summary, so the two /fleet/health views agree on
    what "faulted" means. The registry mirrors (utils/metrics.py) carry
    the same split process-wide, labeled by fault kind."""
    from rafiki_tpu.utils.metrics import REGISTRY

    with _STATS_LOCK:
        s = _stats_entry(sub_id)
        if retried:
            s["retries"] += 1
        else:
            s["faults"][kind] = s["faults"].get(kind, 0) + 1
    if retried:
        REGISTRY.counter(
            "rafiki_training_retries_total",
            "infra-class trial faults absorbed by same-id retry").inc()
    else:
        REGISTRY.counter(
            "rafiki_training_faults_total",
            "terminal trial faults by taxonomy kind", ("kind",)
        ).labels(kind).inc()


def record_quarantine(sub_id: str, signatures: Iterable[str]) -> None:
    from rafiki_tpu.utils.metrics import REGISTRY

    with _STATS_LOCK:
        s = _stats_entry(sub_id)
        merged = set(s["quarantined"]) | set(signatures)
        s["quarantined"] = sorted(merged)
        total = sum(len(v.get("quarantined", ()))
                    for v in TRAINING_STATS.values())
    REGISTRY.gauge(
        "rafiki_training_quarantined_signatures",
        "poison-knob signatures currently quarantined in this process"
    ).set(total)


def record_counter(sub_id: str, counter: str, value: int = 1,
                   absolute: bool = False) -> None:
    from rafiki_tpu.utils.metrics import REGISTRY

    with _STATS_LOCK:
        s = _stats_entry(sub_id)
        s[counter] = value if absolute else s.get(counter, 0) + value
    if not absolute:
        # process-wide counter twin (reproposals, feedback_dropped, ...)
        REGISTRY.counter(
            "rafiki_training_counter_total",
            "training-plane worker counters", ("counter",)
        ).labels(counter).inc(value)


def reset_stats(sub_id: Optional[str] = None) -> None:
    with _STATS_LOCK:
        if sub_id is None:
            TRAINING_STATS.clear()
        else:
            TRAINING_STATS.pop(sub_id, None)
