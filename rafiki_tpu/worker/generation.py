"""Generation worker: token-streaming decode with continuous batching.

The classification worker (worker/inference.py) is one-request/one-answer:
take a batch, run ``predict``, resolve futures. Generative serving cannot
work that way — a 512-token completion would hold its whole batch hostage
for 512 steps. This worker applies the Orca insight (iteration-level
scheduling: admit/evict at TOKEN granularity, not request granularity) on
top of the platform's existing data plane:

- a **fixed-width slot table** (``RAFIKI_GEN_MAX_SLOTS``): the model's KV
  cache is preallocated for that many co-resident sequences, so one jitted
  ``decode_step`` program serves the table for its whole lifetime;
- per decode round the scheduler **pulls newly queued requests** from the
  same bounded ``WorkerQueue`` every serving hop already uses (deadline /
  expiry / depth-cap semantics preserved), prefills them into free slots,
  runs ONE step for every active slot, and pushes each sequence's token
  delta onto its :class:`~rafiki_tpu.cache.queue.TokenStream`;
- sequences **leave mid-decode** — EOS, ``max_tokens``, context edge,
  deadline, client cancel, injected fault — freeing their slot to the next
  queued request without stalling co-resident sequences.

Observability: time-to-first-token and inter-token-latency histograms,
a slot-occupancy gauge + per-job ring (the autoscaler's generative
backlog signal), eviction counters by reason, and the shared
SERVING_STATS row every stats surface already reads.
"""

from __future__ import annotations

import logging
import threading
import time
import traceback
import uuid
from typing import Any, Dict, List, Optional

import numpy as np

from rafiki_tpu import config
from rafiki_tpu.cache.queue import TokenStream
from rafiki_tpu.sdk.model import GenerationSpec, generation_capability
from rafiki_tpu.utils import chaos
from rafiki_tpu.worker.inference import (
    InferenceWorker,
    SERVING_STATS,
    _record_queue,
    _stats_lock,
)

logger = logging.getLogger(__name__)


class GenerationUnsupportedError(RuntimeError):
    """The deployed template does not advertise a fully-wired generation
    capability — a typed deploy-time error (the serving analogue of
    InvalidModelClassError), never a mid-stream AttributeError."""


class GenerationRequestError(ValueError):
    """A malformed generation request (bad prompt/max_tokens shape) —
    resolved onto the request's future so the door answers 400."""


def _metrics():
    """Lazily-created registry handles for the generation plane (same
    pattern as worker/inference.py — import stays cheap, increments all
    happen at one site per signal)."""
    global _M
    if _M is None:
        from rafiki_tpu.utils.metrics import REGISTRY

        _M = {
            "ttft": REGISTRY.histogram(
                "rafiki_gen_ttft_seconds",
                "prefill-to-first-token latency of admitted generation "
                "requests (worker side; the door-side histogram adds "
                "queue wait)"),
            "intertoken": REGISTRY.histogram(
                "rafiki_gen_intertoken_seconds",
                "latency between consecutive decode rounds of a live "
                "slot table"),
            "tokens": REGISTRY.counter(
                "rafiki_gen_tokens_total",
                "tokens emitted by generation workers in this process"),
            "slots": REGISTRY.gauge(
                "rafiki_gen_slots_busy",
                "generation slots currently decoding", ("service",)),
            "evictions": REGISTRY.counter(
                "rafiki_gen_evictions_total",
                "sequences leaving the slot table, by finish reason",
                ("reason",)),
        }
    return _M


_M = None


class _Slot:
    """One resident sequence's scheduler state."""

    __slots__ = ("stream", "last_id", "position", "produced", "max_tokens",
                 "deadline", "muted", "last_step_t")

    def __init__(self, stream: TokenStream, first_id: int, position: int,
                 max_tokens: int, deadline: Optional[float]) -> None:
        self.stream = stream
        self.last_id = first_id
        self.position = position      # cache index the NEXT token lands at
        self.produced = 1             # prefill emitted the first token
        self.max_tokens = max_tokens
        self.deadline = deadline
        #: chaos action=drop: the stalled-decode drill — the slot keeps
        #: its place but its deltas stop arriving; the DOOR's inter-token
        #: timeout must convert the silence into a typed error frame
        self.muted = False
        self.last_step_t = time.monotonic()


class GenerationWorker(InferenceWorker):
    """Serves one trained trial's LM as a token stream. Reuses the
    classification worker's model loading / stats reporting / queue
    registration; only the serve loop differs."""

    def start(self, ctx) -> None:
        from rafiki_tpu.parallel.mesh import set_device_grant
        from rafiki_tpu.utils.metrics import REGISTRY

        set_device_grant(ctx.chips)
        model = None
        queue = self._broker.register_worker(self._job_id, ctx.service_id)
        try:
            model = self._load_model(ctx.service_id)
            spec = generation_capability(type(model))
            if spec is None:
                raise GenerationUnsupportedError(
                    f"trial {self._trial_id}'s template does not advertise "
                    "a fully-wired GenerationSpec (init_kv_cache/prefill/"
                    "decode_step) — it cannot serve TEXT_GENERATION")
            max_slots = max(int(config.GEN_MAX_SLOTS), 1)
            cache = model.init_kv_cache(max_slots)
            try:
                model.warm_up()
            except Exception:
                logger.warning(
                    "warm_up failed in generation worker %s (serving "
                    "anyway):\n%s", ctx.service_id, traceback.format_exc())
            ctx.ready()
            if self._report_stats is not None:
                threading.Thread(
                    target=self._stats_reporter, args=(ctx,),
                    name="stats-reporter", daemon=True).start()
            slots: List[Optional[_Slot]] = [None] * max_slots
            occupancy_ring = REGISTRY.ring(
                f"slot_occupancy:job:{self._job_id}")
            m = _metrics()
            # lint: thread-confined(only the serve thread writes and reads this; the reporter thread reads the _stats_lock'd module dict copy)
            self._tokens_emitted = 0
            while not ctx.stopping:
                n_active = sum(1 for s in slots if s is not None)
                free = [i for i, s in enumerate(slots) if s is None]
                # -- admit: pull queued requests into free slots ----------
                if free and (n_active == 0 or queue.depth() > 0):
                    batch = queue.take_batch(
                        max_size=len(free), deadline_s=0.0,
                        wait_timeout_s=(0.25 if n_active == 0 else 0.0))
                    if batch is None:
                        logger.info("query queue closed; generation "
                                    "worker %s exiting", ctx.service_id)
                        break
                    for fut, query in batch:
                        cache = self._admit(
                            model, spec, cache, slots, free, fut, query,
                            ctx.service_id)
                    _record_queue(ctx.service_id, queue)
                n_active = sum(1 for s in slots if s is not None)
                m["slots"].labels(ctx.service_id).set(n_active)
                occupancy_ring.record(n_active / max_slots)
                self._stats_row(ctx.service_id, n_active, max_slots)
                if n_active == 0:
                    continue
                # -- decode: one token for every resident sequence --------
                cache = self._decode_round(model, spec, cache, slots, ctx)
        finally:
            self._broker.unregister_worker(self._job_id, ctx.service_id)
            if model is not None:
                model.destroy()
            set_device_grant(None)

    # -- admission -----------------------------------------------------------

    def _admit(self, model, spec: GenerationSpec, cache,
               slots: List[Optional[_Slot]], free: List[int], fut, query,
               service_id: str):
        """Prefill one queued request into a free slot and hand its
        TokenStream back through the request's future. A malformed
        request fails ITS future (typed, -> 400 at the door) and costs no
        slot; a prefill crash likewise never kills co-resident slots."""
        try:
            prompt, max_tokens, max_duration_s = self._parse_query(query)
        except GenerationRequestError as e:
            fut.set_error(e)
            return cache
        if not free:
            # take_batch was sized to the free count, but a same-round
            # earlier admit may have failed and returned its slot unused;
            # being here with none left means a scheduler bug upstream —
            # fail the request rather than strand it silently
            fut.set_error(RuntimeError("no free generation slot"))
            return cache
        if len(prompt) + max_tokens > spec.max_context:
            fut.set_error(GenerationRequestError(
                f"prompt ({len(prompt)} tokens) + max_tokens "
                f"({max_tokens}) exceeds the template's max_context "
                f"({spec.max_context})"))
            return cache
        slot_ix = free.pop(0)
        t0 = time.monotonic()
        try:
            first_id, cache = model.prefill(cache, slot_ix, list(prompt))
        except Exception as e:
            free.insert(0, slot_ix)
            logger.error("prefill failed in generation worker %s:\n%s",
                         service_id, traceback.format_exc())
            fut.set_error(RuntimeError(f"prefill failed: {e}"))
            return cache
        first_id = int(first_id)
        stream = TokenStream(seq_id=uuid.uuid4().hex[:12])
        deadline = (time.monotonic() + max_duration_s
                    if max_duration_s else None)
        slot = _Slot(stream, first_id, len(prompt), max_tokens, deadline)
        slots[slot_ix] = slot
        fut.set_result(stream)
        from rafiki_tpu.worker.inference import _record_batch

        _record_batch(service_id, 1)  # one admitted request
        m = _metrics()
        m["ttft"].observe(time.monotonic() - t0)
        m["tokens"].inc()
        finished, reason = self._finish_reason(slot, spec, first_id)
        stream.push([first_id], finished=finished, reason=reason)
        if finished:
            self._evict(slots, slot_ix, reason)
        return cache

    @staticmethod
    def _parse_query(query):
        if not isinstance(query, dict):
            raise GenerationRequestError(
                "generation query must be an object with 'prompt_ids'")
        prompt = query.get("prompt_ids")
        if (not isinstance(prompt, (list, tuple)) or not prompt
                or not all(isinstance(t, int) and t >= 0 for t in prompt)):
            raise GenerationRequestError(
                "'prompt_ids' must be a non-empty list of non-negative "
                "token ids")
        cap = max(int(config.GEN_MAX_TOKENS), 1)
        raw = query.get("max_tokens", cap)
        try:
            max_tokens = int(raw)
        except (TypeError, ValueError):
            raise GenerationRequestError(
                f"max_tokens={raw!r} is not an integer") from None
        if max_tokens < 1:
            raise GenerationRequestError(
                f"max_tokens={max_tokens} must be >= 1")
        max_tokens = min(max_tokens, cap)
        max_duration_s = query.get("max_duration_s")
        if max_duration_s is not None:
            try:
                max_duration_s = float(max_duration_s)
            except (TypeError, ValueError):
                raise GenerationRequestError(
                    "max_duration_s must be a number") from None
        return list(prompt), max_tokens, max_duration_s

    # -- the decode round ----------------------------------------------------

    def _decode_round(self, model, spec: GenerationSpec, cache,
                      slots: List[Optional[_Slot]], ctx):
        """Advance every resident sequence one token. Slot-level chaos is
        consulted per sequence, so a drill injures exactly one stream
        while siblings keep decoding."""
        n = len(slots)
        ids = np.zeros(n, np.int32)
        positions = np.zeros(n, np.int32)
        for i, s in enumerate(slots):
            if s is not None:
                ids[i] = s.last_id
                positions[i] = s.position
        try:
            next_ids, cache = model.decode_step(cache, ids, positions)
            next_ids = np.asarray(next_ids)
        except Exception:
            # a decode_step crash poisons the whole table (the cache may
            # be half-written): fail every resident stream TYPED and
            # clear the table — the worker keeps serving new requests
            logger.error("decode_step failed in generation worker %s:\n%s",
                         ctx.service_id, traceback.format_exc())
            for i, s in enumerate(slots):
                if s is not None:
                    s.stream.fail("decode step failed on the serving "
                                  "worker")
                    self._evict(slots, i, "error")
            return cache
        now = time.monotonic()
        m = _metrics()
        for i, slot in enumerate(slots):
            if slot is None:
                continue
            rule = chaos.hit(
                chaos.SITE_GENERATE,
                f"{self._job_id}/{ctx.service_id}/slot{i}/"
                f"{slot.stream.seq_id}")
            if rule is not None:
                if rule.action == chaos.ACTION_DELAY:
                    chaos.sleep_for(rule)
                elif rule.action == chaos.ACTION_DROP:
                    # stalled decode: the slot stays resident but its
                    # deltas stop — the door's inter-token timeout owns
                    # recovery (typed error frame + cancel)
                    logger.warning(
                        "chaos: muting generation slot %d (%s)", i,
                        slot.stream.seq_id)
                    slot.muted = True
                else:  # ACTION_ERROR: mid-stream fault on THIS stream
                    slot.stream.fail(
                        "chaos-injected mid-stream generation fault")
                    self._evict(slots, i, "error")
                    continue
            if slot.stream.cancelled:
                self._evict(slots, i, "cancelled")
                continue
            token = int(next_ids[i])
            slot.position += 1
            slot.last_id = token
            slot.produced += 1
            m["intertoken"].observe(now - slot.last_step_t)
            slot.last_step_t = now
            m["tokens"].inc()
            self._tokens_emitted += 1
            finished, reason = self._finish_reason(slot, spec, token)
            if slot.deadline is not None and now >= slot.deadline:
                finished, reason = True, "deadline"
            if not slot.muted:
                slot.stream.push([token], finished=finished, reason=reason)
            if finished:
                self._evict(slots, i, reason)
        return cache

    @staticmethod
    def _finish_reason(slot: _Slot, spec: GenerationSpec, token: int):
        if spec.eos_token_id is not None and token == spec.eos_token_id:
            return True, "eos"
        if slot.produced >= slot.max_tokens:
            return True, "max_tokens"
        if slot.position + 1 >= spec.max_context:
            return True, "context"
        return False, None

    @staticmethod
    def _evict(slots: List[Optional[_Slot]], i: int, reason: str) -> None:
        slots[i] = None
        _metrics()["evictions"].labels(reason or "unknown").inc()

    def _stats_row(self, service_id: str, busy: int, max_slots: int) -> None:
        """Fold the slot picture into the shared SERVING_STATS row (the
        /healthz + fleet-health + stats-relay surface every PR already
        reads); the 'queries' counter stays the admitted-request count.
        ``gen_tokens`` advances every decode round, so the process-mode
        stats relay (report_stats dedupes on an unchanged row) keeps
        pushing — and the admin keeps re-recording the occupancy ring —
        for as long as the table is actually decoding, even when
        occupancy itself sits pinned at full."""
        with _stats_lock:
            s = SERVING_STATS.setdefault(
                service_id, {"batches": 0, "queries": 0})
            s["gen_slots_busy"] = busy
            s["gen_slots_max"] = max_slots
            s["gen_tokens"] = getattr(self, "_tokens_emitted", 0)
