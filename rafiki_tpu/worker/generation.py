"""Generation worker: token-streaming decode with continuous batching.

The classification worker (worker/inference.py) is one-request/one-answer:
take a batch, run ``predict``, resolve futures. Generative serving cannot
work that way — a 512-token completion would hold its whole batch hostage
for 512 steps. This worker applies the Orca insight (iteration-level
scheduling: admit/evict at TOKEN granularity, not request granularity) on
top of the platform's existing data plane:

- a **fixed-width slot table** (``RAFIKI_GEN_MAX_SLOTS``): the model's KV
  cache is preallocated for that many co-resident sequences, so one jitted
  ``decode_step`` program serves the table for its whole lifetime;
- per decode round the scheduler **pulls newly queued requests** from the
  same bounded ``WorkerQueue`` every serving hop already uses (deadline /
  expiry / depth-cap semantics preserved), prefills them into free slots,
  runs ONE step for every active slot, and pushes each sequence's token
  delta onto its :class:`~rafiki_tpu.cache.queue.TokenStream`;
- sequences **leave mid-decode** — EOS, ``max_tokens``, context edge,
  deadline, client cancel, injected fault — freeing their slot to the next
  queued request without stalling co-resident sequences.

Decode memory comes in two layouts. Templates that implement only the
base generation contract get the **contiguous ring**: one
``max_context``-long K/V ring per slot, simple but worst-case-sized.
Templates that also implement the paged methods (sdk/model.py
``GENERATION_PAGED_METHODS``) serve under the **paged KV allocator**
(worker/kv_paging.py, ``RAFIKI_GEN_KV_PAGED``): a fixed pool of
``RAFIKI_GEN_KV_BLOCK_TOKENS``-sized pages plus per-slot block tables, so
resident streams are bound by *used* tokens rather than
``slots x max_context``. The paged path adds three levers the ring cannot
offer:

- **shared prefix cache** (``RAFIKI_GEN_PREFIX_CACHE``): prompt-prefix
  blocks are content-hashed, refcounted, and mapped read-only into later
  streams — N streams sharing a system prompt pay prefill once, with
  copy-on-write protecting the partial tail block when streams diverge;
- **chunked prefill** (``RAFIKI_GEN_PREFILL_CHUNK``): a long-prompt join
  is ingested a chunk per scheduler round, interleaved with decode
  rounds, so resident streams' inter-token latency never stalls behind
  one giant prompt;
- **preempt-don't-crash**: pool exhaustion preempts the youngest stream
  (blocks freed, the stream transparently re-queued and later resumed
  from a fresh prefill of its tokens-so-far — greedy decode makes the
  continuation exact) instead of failing a round.

On top of the paged plane, **speculative decoding** (``RAFIKI_GEN_SPEC``;
a draft trial budgeted as ``GEN_DRAFT_TRIAL``) multiplies tokens per
round: a small draft LM proposes ``RAFIKI_GEN_SPEC_K`` tokens per
scheduler round and the target verifies all k+1 positions in ONE
fixed-shape ``paged_verify_step`` forward — per-slot accept lengths are
data, not shape, so mixed acceptance across resident streams never
retraces. **Real sampling** (temperature / top-k / top-p,
``RAFIKI_GEN_SAMPLING``) rides the same plane under a counter-based RNG
key — every draw is keyed by (stream seed, absolute token position, draw
role) — which keeps sampled streams exactly resumable through the
preemption path above and makes the speculative accept test
well-defined; temperature=0 reproduces the greedy path bit-identically.
A draft fault (crash, stall, vocab mismatch) degrades the worker to
plain decode TYPED: resident streams keep their tokens/s floor and
``gen_spec_degraded`` in the stats row names the reason.

Observability: time-to-first-token and inter-token-latency histograms,
a slot-occupancy gauge + per-job ring (the autoscaler's generative
backlog signal — BLOCK-pool occupancy under the paged layout, busy
slots under the ring), prefix hit/miss/evict + COW + preemption
counters, eviction counters by reason, and the shared SERVING_STATS row
every stats surface already reads.
"""

from __future__ import annotations

import hashlib
import logging
import threading
import time
import traceback
import uuid
from collections import OrderedDict
from typing import Dict, List, Optional

import numpy as np

from rafiki_tpu import config
from rafiki_tpu.cache.queue import TokenStream
from rafiki_tpu.constants import BudgetType
from rafiki_tpu.sdk.model import (
    GenerationSpec,
    ROLE_DRAFT,
    ROLE_TARGET,
    draft_capability,
    generation_capability,
    paged_generation_capability,
    sampling_capability,
    spec_verify_capability,
)
from rafiki_tpu.utils import chaos
from rafiki_tpu.worker.inference import (
    InferenceWorker,
    SERVING_STATS,
    _record_queue,
    _stats_lock,
)
from rafiki_tpu.worker.kv_paging import PagedKVAllocator

logger = logging.getLogger(__name__)


class GenerationUnsupportedError(RuntimeError):
    """The deployed template does not advertise a fully-wired generation
    capability — a typed deploy-time error (the serving analogue of
    InvalidModelClassError), never a mid-stream AttributeError."""


class GenerationRequestError(ValueError):
    """A malformed generation request (bad prompt/max_tokens shape) —
    resolved onto the request's future so the door answers 400."""


def _metrics():
    """Lazily-created registry handles for the generation plane (same
    pattern as worker/inference.py — import stays cheap, increments all
    happen at one site per signal)."""
    global _M
    if _M is None:
        from rafiki_tpu.utils.metrics import REGISTRY

        _M = {
            "ttft": REGISTRY.histogram(
                "rafiki_gen_ttft_seconds",
                "prefill-to-first-token latency of admitted generation "
                "requests (worker side; the door-side histogram adds "
                "queue wait)"),
            "intertoken": REGISTRY.histogram(
                "rafiki_gen_intertoken_seconds",
                "latency between consecutive decode rounds of a live "
                "slot table"),
            "tokens": REGISTRY.counter(
                "rafiki_gen_tokens_total",
                "tokens emitted by generation workers in this process"),
            "slots": REGISTRY.gauge(
                "rafiki_gen_slots_busy",
                "generation slots currently decoding", ("service",)),
            "evictions": REGISTRY.counter(
                "rafiki_gen_evictions_total",
                "sequences leaving the slot table, by finish reason",
                ("reason",)),
            "kv_used": REGISTRY.gauge(
                "rafiki_gen_kv_blocks_used",
                "paged-KV pool blocks currently allocated", ("service",)),
            "kv_pool": REGISTRY.gauge(
                "rafiki_gen_kv_pool_blocks",
                "paged-KV pool size in blocks", ("service",)),
            "prefix_hits": REGISTRY.counter(
                "rafiki_gen_prefix_hits_total",
                "admissions that reused cached prompt-prefix blocks"),
            "prefix_misses": REGISTRY.counter(
                "rafiki_gen_prefix_misses_total",
                "admissions that found no cached prefix"),
            "prefix_tokens": REGISTRY.counter(
                "rafiki_gen_prefix_tokens_total",
                "prompt tokens served from the prefix cache instead of "
                "prefill compute"),
            "prefix_evictions": REGISTRY.counter(
                "rafiki_gen_prefix_evictions_total",
                "prefix-cache entries evicted (LRU, refcount back to "
                "zero)"),
            "prefix_shareable": REGISTRY.counter(
                "rafiki_gen_prefix_shareable_total",
                "admitted prompts whose leading tokens matched a "
                "recently-seen prompt (shared-prefix traffic signal — "
                "counted even while the prefix cache is disabled, so the "
                "doctor can flag a disabled cache under shareable load)"),
            "cow": REGISTRY.counter(
                "rafiki_gen_kv_cow_copies_total",
                "copy-on-write page copies (tail-block divergence)"),
            "preempts": REGISTRY.counter(
                "rafiki_gen_preemptions_total",
                "streams preempted by pool exhaustion (blocks freed, "
                "request re-queued and later resumed)"),
            "spec_proposed": REGISTRY.counter(
                "rafiki_gen_spec_proposed_total",
                "draft tokens proposed to the speculative verify step"),
            "spec_accepted": REGISTRY.counter(
                "rafiki_gen_spec_accepted_total",
                "draft tokens accepted by the target's verify step "
                "(acceptance rate = accepted / proposed)"),
            "spec_rounds": REGISTRY.counter(
                "rafiki_gen_spec_rounds_total",
                "speculative draft-propose/verify rounds run"),
            "spec_degraded": REGISTRY.counter(
                "rafiki_gen_spec_degraded_total",
                "speculation degradations to plain decode (draft fault, "
                "verify fault, capability mismatch)"),
            "migrated": REGISTRY.counter(
                "rafiki_gen_streams_migrated_total",
                "unfinished streams handed back typed (MIGRATING) by a "
                "retiring generation replica for door-side resume on a "
                "sibling (docs/failure-model.md \"Stream continuity\")"),
        }
    return _M


_M = None

#: leading-token window hashed for the shared-prefix-traffic signal
_SHARE_PROBE_TOKENS = 16


class _Slot:
    """One resident sequence's scheduler state."""

    __slots__ = ("stream", "last_id", "position", "produced", "max_tokens",
                 "deadline", "muted", "last_step_t", "prompt", "tokens",
                 "pending_from", "seq", "t0", "temperature", "top_k",
                 "top_p", "rng_seed", "draft_ready")

    def __init__(self, stream: TokenStream, prompt: List[int],
                 max_tokens: int, deadline: Optional[float], seq: int,
                 produced: int = 0,
                 pending_from: Optional[int] = None,
                 sampling: Optional[tuple] = None) -> None:
        self.stream = stream
        self.prompt = prompt          # full token history being prefilled
        self.tokens: List[int] = []   # tokens produced SINCE (re)admission
        self.last_id = 0
        self.position = 0             # cache index the NEXT token lands at
        self.produced = produced      # client-visible tokens so far
        self.max_tokens = max_tokens
        self.deadline = deadline
        #: admission order — pool exhaustion preempts the YOUNGEST stream
        self.seq = seq
        #: next prompt index still to prefill (None = decoding)
        self.pending_from = pending_from
        #: admit time, for the TTFT observation (None after first token
        #: or for preemption resumes — a resume is not a first token)
        self.t0: Optional[float] = None
        #: chaos action=drop: the stalled-decode drill — the slot keeps
        #: its place but its deltas stop arriving; the DOOR's inter-token
        #: timeout must convert the silence into a typed error frame
        self.muted = False
        self.last_step_t = time.monotonic()
        #: sampling params (temperature=0 = greedy); rng_seed is the
        #: stream's counter-RNG seed, FIXED at first admission so a
        #: preemption resume replays the identical sampled sequence
        t, tk, tp, sd = sampling or (0.0, 0, 1.0, 0)
        self.temperature = float(t)
        self.top_k = int(tk)
        self.top_p = float(tp)
        self.rng_seed = int(sd)
        #: draft-model KV rows cover this slot's history (speculation).
        #: Any round a decoding slot sits out garbles its draft-ring row,
        #: so non-participants are invalidated and re-prefilled lazily.
        self.draft_ready = False


class _Pending:
    """A stream waiting for pool blocks: either a not-yet-admitted
    request (``fut``/``query`` set) or a preempted resident stream being
    resumed (``stream``/``prompt`` carry its full token history)."""

    __slots__ = ("fut", "query", "stream", "prompt", "produced",
                 "max_tokens", "deadline", "seq", "sampling")

    def __init__(self, seq: int, fut=None, query=None, stream=None,
                 prompt=None, produced=0, max_tokens=0, deadline=None,
                 sampling=None):
        self.seq = seq
        self.fut = fut
        self.query = query
        self.stream = stream
        self.prompt = prompt
        self.produced = produced
        self.max_tokens = max_tokens
        self.deadline = deadline
        self.sampling = sampling


class GenerationWorker(InferenceWorker):
    """Serves one trained trial's LM as a token stream. Reuses the
    classification worker's model loading / stats reporting / queue
    registration; only the serve loop differs."""

    def start(self, ctx) -> None:
        from rafiki_tpu.parallel.mesh import set_device_grant
        from rafiki_tpu.utils.metrics import REGISTRY

        set_device_grant(ctx.chips)
        model = None
        queue = self._broker.register_worker(self._job_id, ctx.service_id)
        try:
            model = self._load_model(ctx.service_id)
            spec = generation_capability(type(model))
            if spec is None:
                raise GenerationUnsupportedError(
                    f"trial {self._trial_id}'s template does not advertise "
                    "a fully-wired GenerationSpec (init_kv_cache/prefill/"
                    "decode_step) — it cannot serve TEXT_GENERATION")
            max_slots = max(int(config.GEN_MAX_SLOTS), 1)
            self._alloc: Optional[PagedKVAllocator] = None
            self._chunk = 0
            paged_spec = paged_generation_capability(type(model))
            if bool(config.GEN_KV_PAGED) and paged_spec is not None:
                block_tokens = max(int(config.GEN_KV_BLOCK_TOKENS), 1)
                table_blocks = -(-int(spec.max_context) // block_tokens)
                pool_blocks = (int(config.GEN_KV_POOL_BLOCKS)
                               or max_slots * table_blocks)
                self._alloc = PagedKVAllocator(
                    pool_blocks, block_tokens, table_blocks,
                    prefix_cache=bool(config.GEN_PREFIX_CACHE))
                self._chunk = max(int(config.GEN_PREFILL_CHUNK), 0)
                cache = model.init_paged_kv_cache(pool_blocks, block_tokens)
                logger.info(
                    "generation worker %s: paged KV (%d blocks x %d "
                    "tokens, prefix cache %s, prefill chunk %d)",
                    ctx.service_id, pool_blocks, block_tokens,
                    "on" if self._alloc.prefix_cache else "off",
                    self._chunk)
            else:
                cache = model.init_kv_cache(max_slots)
            self._init_spec(model, spec, max_slots, ctx)
            # pre-warm per-bucket prefill + decode programs under the
            # persistent compile cache, before ctx.ready(): a still-
            # compiling generation replica stays DEPLOYING/unroutable
            from rafiki_tpu.worker.warmup import run_warmup

            run_warmup(ctx.service_id, self._job_id,
                       [("warm_up", model.warm_up)])
            ctx.ready()
            if self._report_stats is not None:
                threading.Thread(
                    target=self._stats_reporter, args=(ctx,),
                    name="stats-reporter", daemon=True).start()
            slots: List[Optional[_Slot]] = [None] * max_slots
            occupancy_ring = REGISTRY.ring(
                f"slot_occupancy:job:{self._job_id}")
            m = _metrics()
            # lint: thread-confined(only the serve thread writes and reads this; the reporter thread reads the _stats_lock'd module dict copy)
            self._tokens_emitted = 0
            # lint: thread-confined(admission order counter — the serve thread is the only scheduler)
            self._seq = 0
            # lint: thread-confined(preempted/stashed continuations — only the serve thread admits, preempts, and resumes)
            self._pending = []
            self._recent_prefixes: "OrderedDict[str, bool]" = OrderedDict()
            self._last_alloc_stats: Dict[str, int] = {}
            # lint: thread-confined(set by the serve thread's chaos kill only)
            killed = False
            while not ctx.stopping:
                # replica-level chaos (RAFIKI_CHAOS site=worker, the same
                # target shape as the classification serve loop): the
                # deterministic SIGKILL-mid-stream drill. drop = abrupt
                # death — resident streams are ABANDONED without terminal
                # deltas (exactly what a real SIGKILL leaves behind; the
                # door detects the dead replica on its stall timeout and
                # resumes from the journal); error = clean kill — every
                # resident stream is handed back typed MIGRATING before
                # the replica exits; delay = slow replica.
                rule = chaos.hit(chaos.SITE_WORKER,
                                 f"{self._job_id}/{ctx.service_id}")
                if rule is not None:
                    if rule.action == chaos.ACTION_DELAY:
                        chaos.sleep_for(rule)
                    elif rule.action == chaos.ACTION_DROP:
                        logger.warning(
                            "chaos: killing generation replica %s "
                            "(streams abandoned, SIGKILL drill)",
                            ctx.service_id)
                        killed = True
                        break
                    else:  # ACTION_ERROR: clean kill with handoff
                        logger.warning(
                            "chaos: retiring generation replica %s "
                            "(streams handed back MIGRATING)",
                            ctx.service_id)
                        break
                n_active = sum(1 for s in slots if s is not None)
                free = [i for i, s in enumerate(slots) if s is None]
                # -- admit: resumes first, then queued requests -----------
                if free and self._pending:
                    cache = self._readmit(model, spec, cache, slots, free,
                                          ctx.service_id)
                if free and (n_active == 0 or queue.depth() > 0) \
                        and self._room_for_new():
                    batch = queue.take_batch(
                        max_size=len(free), deadline_s=0.0,
                        wait_timeout_s=(0.25 if n_active == 0
                                        and not self._pending else 0.0))
                    if batch is None:
                        logger.info("query queue closed; generation "
                                    "worker %s exiting", ctx.service_id)
                        break
                    for fut, query in batch:
                        cache = self._admit(
                            model, spec, cache, slots, free, fut, query,
                            ctx.service_id)
                    _record_queue(ctx.service_id, queue)
                # -- chunked prefill: one chunk per prefilling slot -------
                if self._alloc is not None:
                    cache = self._prefill_round(model, spec, cache, slots,
                                                ctx)
                n_active = sum(1 for s in slots if s is not None)
                m["slots"].labels(ctx.service_id).set(
                    sum(1 for s in slots
                        if s is not None and s.pending_from is None))
                self._mirror_alloc(ctx.service_id, m)
                occupancy_ring.record(self._occupancy(slots, max_slots))
                self._stats_row(ctx.service_id, slots, max_slots)
                if n_active == 0 and not self._pending:
                    continue
                # -- decode: one token for every resident sequence (or a
                # draft-propose/verify burst when speculation is live) ----
                if any(s is not None and s.pending_from is None
                       for s in slots):
                    if self._spec_on:
                        cache = self._spec_round(model, spec, cache,
                                                 slots, ctx)
                    else:
                        cache = self._decode_round(model, spec, cache,
                                                   slots, ctx)
                elif n_active == 0:
                    # only stashed streams remain and nothing can run —
                    # don't spin while the pool refills
                    time.sleep(0.005)
            # -- drain handoff (docs/failure-model.md "Stream
            # continuity"): a retiring replica (scale-down drain, rollout
            # retirement, queue closed, clean chaos kill) must never
            # abandon a resident stream silently — each one is handed
            # back typed MIGRATING so the door resumes it on a sibling.
            # A chaos SIGKILL (killed=True) skips this on purpose: the
            # whole point of that drill is recovering WITHOUT a handoff.
            if not killed:
                self._hand_back_all(slots, ctx.service_id)
        finally:
            self._broker.unregister_worker(self._job_id, ctx.service_id)
            if getattr(self, "_draft", None) is not None:
                self._draft.destroy()
            if model is not None:
                model.destroy()
            set_device_grant(None)

    # -- sampling + speculation setup ----------------------------------------

    def _init_spec(self, model, spec: GenerationSpec, max_slots: int,
                   ctx) -> None:
        """Wire sampling + speculative decoding for this worker. Sampling
        needs only a capable template; speculation additionally needs the
        paged plane, the verify capability, and a draft trial budgeted
        as ``BudgetType.GEN_DRAFT_TRIAL`` on the inference job. Anything
        missing degrades TYPED — the worker serves plain decode and the
        reason lands in the stats row for the doctor to surface."""
        self._sampling_cap = sampling_capability(type(model))
        # lint: thread-confined(speculation state — only the serve thread schedules; the reporter thread reads the _stats_lock'd row copy)
        self._spec_on = False
        self._spec_degraded: Optional[str] = None
        self._spec_k = min(max(int(config.GEN_SPEC_K), 1), 16)
        self._spec_proposed = 0
        self._spec_accepted = 0
        self._spec_rounds = 0
        self._draft = None
        self._draft_spec: Optional[GenerationSpec] = None
        self._draft_cache = None
        if not bool(config.GEN_SPEC) or self._alloc is None:
            return  # speculation is opt-in and lives on the paged plane
        if spec_verify_capability(type(model)) is None:
            self._spec_degraded = (
                "template lacks the speculative verify capability "
                "(paged_verify_step + sampled decode)")
            return
        try:
            draft = self._load_draft_model(ctx.service_id)
        except Exception:
            logger.error("draft model failed to load in generation "
                         "worker %s:\n%s", ctx.service_id,
                         traceback.format_exc())
            self._spec_degraded = "draft model failed to load"
            return
        if draft is None:
            return  # job budgets no draft: plain decode, not a fault
        dspec = draft_capability(type(draft))
        if dspec is None:
            draft.destroy()
            self._spec_degraded = (
                "draft trial's template is not draft-capable (generation "
                "contract + decode_step_sampled)")
            return
        self._draft = draft
        self._draft_spec = dspec
        self._draft_cache = draft.init_kv_cache(max_slots)
        self._spec_on = True
        logger.info(
            "generation worker %s: speculative decoding on (k=%d, draft "
            "max_context=%d)", ctx.service_id, self._spec_k,
            dspec.max_context)

    def _load_draft_model(self, service_id: str):
        """The job's draft LM: ``BudgetType.GEN_DRAFT_TRIAL`` in the
        inference job's budget names a (small) generation-capable trial,
        loaded through the normal trial-artifact path. None = the job
        budgets no draft, so speculation simply stays off."""
        if getattr(self, "_db", None) is None:
            return None
        inf = self._db.get_inference_job(self._job_id)
        draft_tid = ((inf or {}).get("budget") or {}).get(
            BudgetType.GEN_DRAFT_TRIAL)
        if not draft_tid:
            return None
        return self._load_one(str(draft_tid), f"{service_id}-draft")

    def _degrade_spec(self, reason: str) -> None:
        """Speculation faulted (draft crash/stall, verify mismatch): fall
        back to plain paged decode TYPED. Resident streams keep decoding
        — losing the multiplier must never lose tokens."""
        if not self._spec_on:
            return
        self._spec_on = False
        self._spec_degraded = reason
        _metrics()["spec_degraded"].inc()
        logger.error("generation worker: speculative decoding degraded "
                     "to plain decode — %s", reason)

    def _sampling_arrays(self, slots, role, only=None) -> Dict[str, object]:
        """Per-slot sampling params as the fixed-shape arrays the sampled
        model methods take. Idle (and filtered) rows get temperature 0,
        whose modified distribution is the argmax one-hot — shape-stable
        and harmless for rows whose writes are dropped anyway."""
        n = len(slots)
        seed = np.zeros(n, np.uint32)
        temp = np.zeros(n, np.float32)
        tk = np.zeros(n, np.int32)
        tp = np.ones(n, np.float32)
        for i, s in enumerate(slots):
            if s is None or (only is not None and i not in only):
                continue
            seed[i] = np.uint32(s.rng_seed & 0xFFFFFFFF)
            temp[i] = np.float32(s.temperature)
            tk[i] = np.int32(s.top_k)
            tp[i] = np.float32(s.top_p)
        return {"seed": seed, "temperature": temp, "top_k": tk,
                "top_p": tp, "role": int(role)}

    # -- admission -----------------------------------------------------------

    def _room_for_new(self) -> bool:
        """Gate NEW queue pulls under the paged allocator: stashed
        streams resume first, and an effectively-dry pool admits no one
        (churning admissions straight into preemption helps nobody)."""
        if self._alloc is None:
            return True
        if self._pending:
            return False
        return (self._alloc.free_blocks()
                + self._alloc.evictable_blocks()) >= 2

    def _admit(self, model, spec: GenerationSpec, cache,
               slots: List[Optional[_Slot]], free: List[int], fut, query,
               service_id: str, seq: Optional[int] = None):
        """Prefill one queued request into a free slot and hand its
        TokenStream back through the request's future. A malformed
        request fails ITS future (typed, -> 400 at the door) and costs no
        slot; a prefill crash likewise never kills co-resident slots.
        ``seq`` re-admits a stashed request under its ORIGINAL admission
        order — minting a fresh one would make the oldest waiter the
        youngest resident and the first preemption victim (starvation).

        A RESUME request (``resume_tokens`` carries a dead/retired
        sibling's committed history) admits through this same path: the
        full history is prefilled under the stream's pinned seed, the
        position-keyed RNG continues the sampled sequence
        token-identically, and the slot starts with ``produced`` already
        at the committed count so ``max_tokens`` stays the ORIGINAL
        budget — the KV charge is exactly history + remaining budget,
        and a resume never lands a TTFT observation."""
        try:
            prompt, max_tokens, max_duration_s, sampling = \
                self._parse_query(query)
            resume = self._parse_resume(query)
        except GenerationRequestError as e:
            fut.set_error(e)
            return cache
        if resume and len(resume) >= max_tokens:
            fut.set_error(GenerationRequestError(
                f"resume_tokens ({len(resume)}) already meets max_tokens "
                f"({max_tokens}) — nothing left to resume"))
            return cache
        if sampling[0] > 0.0 \
                and getattr(self, "_sampling_cap", None) is None:
            fut.set_error(GenerationRequestError(
                "sampled generation (temperature > 0) needs a "
                "sampling-capable template (decode_step_sampled; plus "
                "paged_decode_step_sampled under the paged layout)"))
            return cache
        if not free:
            # take_batch was sized to the free count, but a same-round
            # earlier admit may have failed and returned its slot unused;
            # being here with none left means a scheduler bug upstream —
            # fail the request rather than strand it silently
            fut.set_error(RuntimeError("no free generation slot"))
            return cache
        if len(prompt) + max_tokens > spec.max_context:
            fut.set_error(GenerationRequestError(
                f"prompt ({len(prompt)} tokens) + max_tokens "
                f"({max_tokens}) exceeds the template's max_context "
                f"({spec.max_context})"))
            return cache
        self._note_shareable(prompt)
        #: the prefill history — prompt + committed tokens for a resume
        history = prompt + resume
        produced = len(resume)
        deadline = (time.monotonic() + max_duration_s
                    if max_duration_s else None)
        if self._alloc is not None:
            if self._alloc.blocks_for(len(history) + 1) \
                    > self._alloc.pool_blocks:
                fut.set_error(GenerationRequestError(
                    f"prompt+history ({len(history)} tokens) cannot fit "
                    f"the KV pool ({self._alloc.pool_blocks} blocks x "
                    f"{self._alloc.block_tokens} tokens) — raise "
                    "RAFIKI_GEN_KV_POOL_BLOCKS"))
                return cache
            return self._admit_paged(model, spec, cache, slots, free, fut,
                                     history, max_tokens, deadline,
                                     service_id, seq=seq,
                                     sampling=sampling, produced=produced)
        # -- contiguous-ring path -------------------------------------------
        slot_ix = free.pop(0)
        t0 = time.monotonic()
        try:
            first_id, cache = model.prefill(cache, slot_ix, list(history))
        except Exception as e:
            free.insert(0, slot_ix)
            logger.error("prefill failed in generation worker %s:\n%s",
                         service_id, traceback.format_exc())
            fut.set_error(RuntimeError(f"prefill failed: {e}"))
            return cache
        stream = TokenStream(seq_id=uuid.uuid4().hex[:12])
        slot = _Slot(stream, list(history), max_tokens, deadline,
                     self._next_seq() if seq is None else seq,
                     produced=produced, sampling=sampling)
        slots[slot_ix] = slot
        fut.set_result(stream)
        from rafiki_tpu.worker.inference import _record_batch

        _record_batch(service_id, 1)  # one admitted request
        m = _metrics()
        if slot.temperature > 0.0:
            # sampled stream: prefill's token is the GREEDY pick — do not
            # commit it. Rewind one row so the next decode round rewrites
            # the last prompt position (identical K/V) and SAMPLES the
            # first token under its position-keyed counter RNG; TTFT
            # lands on that first sampled commit. A resume rewinds the
            # same way — onto its last COMMITTED token — and suppresses
            # TTFT (a resumed token is never a first token).
            slot.last_id = history[-1]
            slot.position = len(history) - 1
            slot.t0 = None if produced else t0
            return cache
        first_id = int(first_id)
        slot.last_id = first_id
        slot.position = len(history)
        slot.produced += 1
        slot.tokens.append(first_id)
        if not produced:
            m["ttft"].observe(time.monotonic() - t0)
        m["tokens"].inc()
        finished, reason = self._finish_reason(slot, spec, first_id)
        stream.push([first_id], finished=finished, reason=reason)
        if finished:
            self._evict(slots, slot_ix, reason)
        return cache

    def _next_seq(self) -> int:
        self._seq += 1
        return self._seq

    def _note_shareable(self, prompt: List[int]) -> None:
        """Record shared-prefix traffic whether or not the cache is on —
        the doctor's disabled-cache-under-shareable-load signal."""
        probe = tuple(prompt[:_SHARE_PROBE_TOKENS])
        if len(probe) < 2:
            return
        d = hashlib.sha1(np.asarray(probe, np.int64).tobytes()).hexdigest()
        lru = self._recent_prefixes
        if d in lru:
            lru.move_to_end(d)
            _metrics()["prefix_shareable"].inc()
            return
        lru[d] = True
        while len(lru) > 512:
            lru.popitem(last=False)

    # -- paged admission / prefill -------------------------------------------

    def _admit_paged(self, model, spec, cache, slots, free, fut, prompt,
                     max_tokens, deadline, service_id, seq=None,
                     sampling=None, produced=0):
        """Open a block table for the prompt (mapping any cached prefix),
        run the FIRST prefill chunk synchronously, and resolve the
        request's future. Remaining chunks (long prompts) advance one per
        scheduler round so resident streams keep decoding in between. A
        pool too full for even the first chunk stashes the request — it
        is the youngest stream, so IT waits, not the residents.

        For a door-side RESUME, ``prompt`` is the full prompt+committed
        history and ``produced`` the committed count — the slot keeps the
        original ``max_tokens`` budget and never lands a TTFT sample."""
        slot_ix = free.pop(0)
        slot = _Slot(TokenStream(seq_id=uuid.uuid4().hex[:12]),
                     list(prompt), max_tokens, deadline,
                     self._next_seq() if seq is None else seq,
                     produced=produced, sampling=sampling)
        plan = self._alloc.open_slot(slot_ix, prompt)
        slot.pending_from = plan.cached_tokens
        slot.position = plan.cached_tokens
        slot.t0 = None if produced else time.monotonic()
        slots[slot_ix] = slot  # before _try_chunk: a same-call finish
        # (tiny prompt hitting EOS on its first token) evicts through the
        # normal path
        try:
            if plan.copies:
                cache = self._apply_copies(model, cache, plan.copies)
            n = len(prompt)
            end = n if self._chunk <= 0 else min(n, plan.cached_tokens
                                                 + self._chunk)
            ok, cache = self._try_chunk(model, spec, cache, slots, slot_ix,
                                        slot, end)
            if not ok:
                # pool dry: stash the request un-admitted (future intact)
                slots[slot_ix] = None
                self._alloc.close_slot(slot_ix)
                free.insert(0, slot_ix)
                cut = len(prompt) - produced
                query = {"prompt_ids": list(prompt[:cut]),
                         "max_tokens": max_tokens,
                         "max_duration_s": None,
                         # carry the DERIVED seed: the resumed parse
                         # must replay the identical sampled stream
                         "temperature": slot.temperature,
                         "top_k": slot.top_k, "top_p": slot.top_p,
                         "seed": slot.rng_seed}
                if produced:
                    query["resume_tokens"] = list(prompt[cut:])
                self._stash(_Pending(slot.seq, fut=fut, query=query,
                                     deadline=deadline))
                return cache
        except Exception as e:
            slots[slot_ix] = None
            self._alloc.close_slot(slot_ix)
            free.insert(0, slot_ix)
            logger.error("prefill failed in generation worker %s:\n%s",
                         service_id, traceback.format_exc())
            fut.set_error(RuntimeError(f"prefill failed: {e}"))
            return cache
        fut.set_result(slot.stream)
        from rafiki_tpu.worker.inference import _record_batch

        _record_batch(service_id, 1)
        return cache

    def _readmit(self, model, spec, cache, slots, free, service_id):
        """Resume stashed streams (oldest first): preempted residents
        re-prefill their full token history — greedy decode makes the
        continuation exact — and not-yet-admitted requests go through
        the normal paged admission."""
        while free and self._pending:
            entry = self._pending[0]
            if not self._room_for_resume(entry):
                break
            self._pending.pop(0)
            now = time.monotonic()
            if entry.deadline is not None and now >= entry.deadline:
                if entry.stream is not None:
                    entry.stream.push([], finished=True, reason="deadline")
                elif entry.fut is not None:
                    entry.fut.set_error(TimeoutError(
                        "generation request expired waiting for KV pool "
                        "blocks"))
                continue
            if entry.fut is not None:
                if entry.deadline is not None:
                    # re-derive the request's remaining duration so the
                    # resumed admission keeps the original absolute bound
                    entry.query["max_duration_s"] = max(
                        entry.deadline - now, 0.001)
                cache = self._admit(model, spec, cache, slots, free,
                                    entry.fut, entry.query, service_id,
                                    seq=entry.seq)
                continue
            if entry.stream.cancelled:
                continue
            slot_ix = free.pop(0)
            slot = _Slot(entry.stream, list(entry.prompt),
                         entry.max_tokens, entry.deadline, entry.seq,
                         produced=entry.produced,
                         sampling=entry.sampling)
            plan = self._alloc.open_slot(slot_ix, slot.prompt)
            slot.pending_from = plan.cached_tokens
            slot.position = plan.cached_tokens
            try:
                if plan.copies:
                    cache = self._apply_copies(model, cache, plan.copies)
            except Exception:
                logger.error("resume copy failed in generation worker "
                             "%s:\n%s", service_id,
                             traceback.format_exc())
                self._alloc.close_slot(slot_ix)
                free.insert(0, slot_ix)
                slot.stream.fail("preempted stream could not be resumed")
                continue
            slots[slot_ix] = slot  # chunks advance in _prefill_round
        return cache

    def _room_for_resume(self, entry: _Pending) -> bool:
        need = self._alloc.blocks_for(
            self._chunk if self._chunk > 0
            else len(entry.prompt or (entry.query or {}).get(
                "prompt_ids", [])) + 1)
        return (self._alloc.free_blocks()
                + self._alloc.evictable_blocks()) >= max(need, 1)

    def _stash(self, entry: _Pending) -> None:
        self._pending.append(entry)
        self._pending.sort(key=lambda e: e.seq)

    def _apply_copies(self, model, cache, copies):
        src = np.asarray([s for s, _ in copies], np.int32)
        dst = np.asarray([d for _, d in copies], np.int32)
        return model.kv_copy_blocks(cache, src, dst)

    def _try_chunk(self, model, spec, cache, slots, slot_ix, slot, end):
        """Prefill prompt positions [pending_from, end) for one slot.
        Returns (ok, cache); ok=False means the pool could not supply
        blocks even after preempting every younger stream — the CALLER
        stashes/fails this slot. Exceptions propagate (model crash)."""
        start = slot.pending_from
        n = len(slot.prompt)
        if not self._make_capacity(slots, slot_ix, end - 1):
            return False, cache
        for ix in range(start // self._alloc.block_tokens,
                        (end - 1) // self._alloc.block_tokens + 1):
            copies = self._alloc.ensure_writable(
                slot_ix, ix * self._alloc.block_tokens)
            if copies is None:
                if not self._preempt_youngest(slots, exclude=slot_ix):
                    return False, cache
                copies = self._alloc.ensure_writable(
                    slot_ix, ix * self._alloc.block_tokens)
                if copies is None:
                    return False, cache
            if copies:
                cache = self._apply_copies(model, cache, copies)
        chunk_tokens = slot.prompt[start:end]
        tok, cache = model.paged_prefill(
            cache, self._alloc.table_row(slot_ix), list(chunk_tokens),
            int(start))
        slot.pending_from = end
        slot.position = end
        if end < n:
            return True, cache
        if slot.temperature > 0.0:
            # sampled stream: prefill's token is the greedy pick — do not
            # commit it. Rewind one row so the next decode rewrites the
            # last prompt position (identical K/V) and SAMPLES the first
            # token under its position-keyed counter RNG — which is also
            # exactly how a preempted sampled stream resumes mid-sequence.
            slot.pending_from = None
            slot.last_id = slot.prompt[-1]
            slot.position = n - 1
            self._alloc.publish(slot_ix, slot.prompt)
            return True, cache
        # final chunk: first generated token
        tok = int(tok)
        slot.pending_from = None
        slot.last_id = tok
        slot.produced += 1
        slot.tokens.append(tok)
        m = _metrics()
        now = time.monotonic()
        if slot.t0 is not None:
            m["ttft"].observe(now - slot.t0)
            slot.t0 = None
        m["tokens"].inc()
        slot.last_step_t = now
        self._tokens_emitted += 1
        finished, reason = self._finish_reason(slot, spec, tok)
        if slot.deadline is not None and now >= slot.deadline:
            finished, reason = True, "deadline"
        slot.stream.push([tok], finished=finished, reason=reason)
        if finished:
            self._evict_slot(slots, slot_ix, reason)
        else:
            self._alloc.publish(slot_ix, slot.prompt)
        return True, cache

    def _prefill_round(self, model, spec, cache, slots, ctx):
        """Advance every PREFILLING slot by one chunk — interleaved with
        decode rounds so a max-context prompt joining never stalls
        resident streams' inter-token latency."""
        for i, slot in enumerate(slots):
            if slot is None or slot.pending_from is None:
                continue
            if slot.stream.cancelled:
                self._evict_slot(slots, i, "cancelled")
                continue
            n = len(slot.prompt)
            end = n if self._chunk <= 0 else min(n, slot.pending_from
                                                 + self._chunk)
            try:
                ok, cache = self._try_chunk(model, spec, cache, slots, i,
                                            slot, end)
            except Exception:
                logger.error(
                    "chunked prefill failed in generation worker %s:\n%s",
                    ctx.service_id, traceback.format_exc())
                slot.stream.fail("prefill failed on the serving worker")
                self._evict_slot(slots, i, "error")
                continue
            if not ok and slots[i] is slot:
                # pool dry even after preempting younger streams: this
                # slot yields its blocks and waits its turn
                self._preempt(slots, i)
        return cache

    # -- preemption ----------------------------------------------------------

    def _make_capacity(self, slots, slot_ix, position) -> bool:
        """ensure_capacity with the pool-exhaustion policy: preempt the
        youngest resident stream YOUNGER than the requester (typed:
        blocks freed, request re-queued) until the allocation lands or no
        such victim remains — an older stream is never displaced by a
        newer one, so the oldest stream always makes progress and the
        preemption chain terminates."""
        while not self._alloc.ensure_capacity(slot_ix, position):
            if not self._preempt_youngest(slots, exclude=slot_ix):
                return False
        return True

    def _preempt_youngest(self, slots, exclude: int) -> bool:
        """Preempt the youngest resident stream younger than ``exclude``
        (by admission order); False when there is nobody eligible."""
        mine = slots[exclude].seq if slots[exclude] is not None else -1
        cand = [(s.seq, i) for i, s in enumerate(slots)
                if s is not None and i != exclude and s.seq > mine]
        if not cand:
            return False
        _, victim = max(cand)
        self._preempt(slots, victim)
        return True

    def _preempt(self, slots, i) -> None:
        """Evict slot ``i`` for pool exhaustion: its blocks return to the
        pool and the stream is re-queued as a continuation (full token
        history re-prefilled on resume — the client just sees a pause,
        never an error or duplicate tokens). A stream whose grown history
        can NEVER fit the pool again is failed typed instead: re-queueing
        it would cycle preempt -> resume -> preempt forever while
        ``_room_for_new`` holds all new admissions behind it."""
        slot = slots[i]
        slots[i] = None
        self._alloc.close_slot(i)
        m = _metrics()
        if slot.stream.cancelled:
            m["evictions"].labels("cancelled").inc()
            return
        history = list(slot.prompt)
        if slot.pending_from is None:
            history += slot.tokens
        if self._alloc.blocks_for(len(history) + 1) \
                > self._alloc.pool_blocks:
            slot.stream.fail(
                f"stream outgrew the KV pool ({len(history)} tokens vs "
                f"{self._alloc.pool_blocks} blocks x "
                f"{self._alloc.block_tokens} tokens) — raise "
                "RAFIKI_GEN_KV_POOL_BLOCKS")
            m["evictions"].labels("kv_pool").inc()
            return
        m["evictions"].labels("preempted").inc()
        m["preempts"].inc()
        logger.warning(
            "generation worker: KV pool exhausted — preempting youngest "
            "stream %s (seq %d, %d tokens produced); re-queued",
            slot.stream.seq_id, slot.seq, slot.produced)
        self._stash(_Pending(
            slot.seq, stream=slot.stream, prompt=history,
            produced=slot.produced, max_tokens=slot.max_tokens,
            deadline=slot.deadline,
            sampling=(slot.temperature, slot.top_k, slot.top_p,
                      slot.rng_seed)))

    # -- drain handoff -------------------------------------------------------

    def _hand_back_all(self, slots: List[Optional[_Slot]],
                       service_id: str) -> None:
        """Typed MIGRATING handback of every unfinished resident (and
        preempted-stashed) stream — the retiring replica's half of the
        door-side resume contract. Streams that could finish inside the
        drain window already ran out through the normal serve loop; what
        is left here continues on a sibling from the door's journal.
        Pool-dry requests still waiting on their future get the same
        queue-closed error a close() would give them (the door's submit
        walk owns pre-stream retry)."""
        m = _metrics()
        handed = 0
        for i, s in enumerate(slots):
            if s is None:
                continue
            if s.stream.cancelled:
                self._evict_slot(slots, i, "cancelled")
                continue
            s.stream.hand_back(
                f"generation replica {service_id} is retiring; stream "
                "handed back for resume on a sibling")
            m["migrated"].inc()
            handed += 1
            self._evict_slot(slots, i, "migrating")
        for entry in self._pending:
            if entry.stream is not None:
                if not entry.stream.cancelled:
                    entry.stream.hand_back(
                        f"generation replica {service_id} is retiring; "
                        "stream handed back for resume on a sibling")
                    m["migrated"].inc()
                    handed += 1
            elif entry.fut is not None:
                entry.fut.set_error(RuntimeError("worker queue closed"))
        self._pending = []
        if handed:
            logger.info(
                "generation replica %s handed back %d unfinished "
                "stream(s) for door-side resume", service_id, handed)

    # -- the decode round ----------------------------------------------------

    def _decode_round(self, model, spec: GenerationSpec, cache,
                      slots: List[Optional[_Slot]], ctx, only=None):
        """Advance every resident DECODING sequence one token. Slot-level
        chaos is consulted per sequence, so a drill injures exactly one
        stream while siblings keep decoding. ``only`` restricts the round
        to a subset of slot indices — the speculative round uses it to
        advance the streams that sat out a verify burst (context edge,
        burst-capacity demotion) without re-stepping the participants."""
        n = len(slots)
        paged = self._alloc is not None
        if paged:
            # growth + COW barriers for this round's writes
            for i, s in enumerate(slots):
                if s is None or s.pending_from is not None:
                    continue
                if only is not None and i not in only:
                    continue
                if not self._make_capacity(slots, i, s.position):
                    if slots[i] is s:
                        self._preempt(slots, i)
                    continue
                copies = self._alloc.ensure_writable(i, s.position)
                if copies is None:
                    if not self._preempt_youngest(slots, exclude=i):
                        s.stream.fail(
                            "KV pool exhausted and no sibling stream "
                            "left to preempt — raise "
                            "RAFIKI_GEN_KV_POOL_BLOCKS")
                        self._evict_slot(slots, i, "kv_pool")
                        continue
                    copies = self._alloc.ensure_writable(i, s.position)
                    if copies is None:
                        s.stream.fail("KV pool exhausted")
                        self._evict_slot(slots, i, "kv_pool")
                        continue
                if copies:
                    cache = self._apply_copies(model, cache, copies)
        active = [(i, s) for i, s in enumerate(slots)
                  if s is not None and s.pending_from is None
                  and (only is None or i in only)]
        if not active:
            return cache
        ids = np.zeros(n, np.int32)
        positions = np.zeros(n, np.int32)
        for i, s in active:
            ids[i] = s.last_id
            positions[i] = s.position
        # one sampled slot puts the whole batch through the sampled step
        # (greedy rows are bit-identical there: their modified dist is
        # the argmax one-hot) — the program count stays at one per shape
        sampled = (getattr(self, "_sampling_cap", None) is not None
                   and any(s.temperature > 0.0 for _, s in active))
        live = set(i for i, _ in active)
        try:
            if paged:
                tables = np.stack([
                    self._alloc.table_row(i) if i in live
                    else self._alloc.idle_row()
                    for i in range(n)])
                if sampled:
                    next_ids, _probs, cache = \
                        model.paged_decode_step_sampled(
                            cache, ids, positions, tables,
                            self._sampling_arrays(slots, ROLE_TARGET,
                                                  only=live))
                else:
                    next_ids, cache = model.paged_decode_step(
                        cache, ids, positions, tables)
            elif sampled:
                next_ids, _probs, cache = model.decode_step_sampled(
                    cache, ids, positions,
                    self._sampling_arrays(slots, ROLE_TARGET, only=live))
            else:
                next_ids, cache = model.decode_step(cache, ids, positions)
            next_ids = np.asarray(next_ids)
        except Exception:
            # a decode_step crash poisons the whole table (the cache may
            # be half-written): fail every resident stream TYPED and
            # clear the table — the worker keeps serving new requests
            logger.error("decode_step failed in generation worker %s:\n%s",
                         ctx.service_id, traceback.format_exc())
            for i, s in enumerate(slots):
                if s is not None:
                    s.stream.fail("decode step failed on the serving "
                                  "worker")
                    self._evict_slot(slots, i, "error")
            return cache
        now = time.monotonic()
        m = _metrics()
        for i, slot in enumerate(slots):
            if slot is None or slot.pending_from is not None:
                continue
            if i not in live:
                continue
            rule = chaos.hit(
                chaos.SITE_GENERATE,
                f"{self._job_id}/{ctx.service_id}/slot{i}/"
                f"{slot.stream.seq_id}")
            if rule is not None:
                if rule.action == chaos.ACTION_DELAY:
                    chaos.sleep_for(rule)
                elif rule.action == chaos.ACTION_DROP:
                    # stalled decode: the slot stays resident but its
                    # deltas stop — the door's inter-token timeout owns
                    # recovery (typed error frame + cancel)
                    logger.warning(
                        "chaos: muting generation slot %d (%s)", i,
                        slot.stream.seq_id)
                    slot.muted = True
                else:  # ACTION_ERROR: mid-stream fault on THIS stream
                    slot.stream.fail(
                        "chaos-injected mid-stream generation fault")
                    self._evict_slot(slots, i, "error")
                    continue
            if slot.stream.cancelled:
                self._evict_slot(slots, i, "cancelled")
                continue
            token = int(next_ids[i])
            slot.position += 1
            slot.last_id = token
            slot.produced += 1
            slot.tokens.append(token)
            m["intertoken"].observe(now - slot.last_step_t)
            slot.last_step_t = now
            m["tokens"].inc()
            self._tokens_emitted += 1
            if slot.t0 is not None:
                # a sampled stream's first token commits HERE (admission
                # rewound past prefill's greedy pick)
                m["ttft"].observe(now - slot.t0)
                slot.t0 = None
            finished, reason = self._finish_reason(slot, spec, token)
            if slot.deadline is not None and now >= slot.deadline:
                finished, reason = True, "deadline"
            if not slot.muted:
                slot.stream.push([token], finished=finished, reason=reason)
            if finished:
                self._evict_slot(slots, i, reason)
        return cache

    # -- the speculative round -----------------------------------------------

    def _spec_round(self, model, spec: GenerationSpec, cache,
                    slots: List[Optional[_Slot]], ctx):
        """One draft-propose/verify round: the draft LM proposes k tokens
        per eligible resident stream, the target verifies all k+1
        positions in ONE fixed-shape ``paged_verify_step`` forward, and
        every participant commits accept_len+1 tokens. Streams near a
        context edge (or demoted by a burst-capacity shortfall) take the
        plain one-token round instead THIS round; a draft or verify fault
        degrades speculation typed and the round finishes plain for
        everyone — the multiplier is lost, never the streams."""
        k = self._spec_k
        cand = []
        for i, s in enumerate(slots):
            if s is None or s.pending_from is not None:
                continue
            if (s.position + k >= spec.max_context
                    or s.position + k >= self._draft_spec.max_context):
                continue  # burst would cross a context edge
            cand.append(i)
        if not cand:
            return self._decode_round(model, spec, cache, slots, ctx)
        # draft-fault drill: a crashing/stalling DRAFT must cost the
        # multiplier, never the streams (docs/failure-model.md)
        rule = chaos.hit(chaos.SITE_GENERATE,
                         f"draft/{self._job_id}/{ctx.service_id}")
        if rule is not None:
            if rule.action == chaos.ACTION_DELAY:
                chaos.sleep_for(rule)  # slow draft: the round still lands
            elif rule.action == chaos.ACTION_DROP:
                # draft stalled THIS round: skip speculation, decode plain
                return self._decode_round(model, spec, cache, slots, ctx)
            else:
                self._degrade_spec("chaos-injected draft fault")
                return self._decode_round(model, spec, cache, slots, ctx)
        # growth + COW barriers for the whole k+1-row write burst
        bt = self._alloc.block_tokens
        part: List[int] = []
        for i in cand:
            s = slots[i]
            if s is None:
                continue  # preempted making room for an earlier burst
            ok = self._make_capacity(slots, i, s.position + k)
            if ok:
                for bx in range(s.position // bt,
                                (s.position + k) // bt + 1):
                    copies = self._alloc.ensure_writable(i, bx * bt)
                    if copies is None:
                        ok = False
                        break
                    if copies:
                        cache = self._apply_copies(model, cache, copies)
            if ok:
                part.append(i)
        part = [i for i in part if slots[i] is not None]
        rest = set(i for i, s in enumerate(slots)
                   if s is not None and s.pending_from is None
                   and i not in part)
        if not part:
            return self._decode_round(model, spec, cache, slots, ctx,
                                      only=rest)
        # the propose steps below write garbage into the draft-ring rows
        # of every slot sitting this round out — invalidate them so their
        # next participation re-prefills the draft cache
        for i in rest:
            slots[i].draft_ready = False
        n = len(slots)
        try:
            for i in part:
                s = slots[i]
                if s.draft_ready:
                    continue
                # lazy draft prefill of the slot's committed history
                # (positions 0..position; the first propose step rewrites
                # row `position` with identical K/V)
                _, self._draft_cache = self._draft.prefill(
                    self._draft_cache, i, list(s.prompt) + list(s.tokens))
                s.draft_ready = True
            cur = np.zeros(n, np.int32)
            cpos = np.zeros(n, np.int32)
            for i in part:
                cur[i] = slots[i].last_id
                cpos[i] = slots[i].position
            dsamp = self._sampling_arrays(slots, ROLE_DRAFT, only=part)
            fused = getattr(self._draft, "decode_steps_sampled", None)
            if callable(fused):
                # fused proposal: all k chained steps in ONE program —
                # the k-call loop below pays dispatch + a host sync per
                # step just to feed the sampled token back in
                d_j, q_j, self._draft_cache = fused(
                    self._draft_cache, cur, cpos, k, dsamp)
                d_ids = np.asarray(d_j, np.int32)        # (S, k)
                draft_probs = np.asarray(q_j, np.float32)
            else:
                d_ids = np.zeros((n, k), np.int32)
                q_list = []
                for j in range(k):
                    nxt, q, self._draft_cache = \
                        self._draft.decode_step_sampled(
                            self._draft_cache, cur.copy(), cpos.copy(),
                            dsamp)
                    nxt = np.asarray(nxt, np.int32)
                    d_ids[:, j] = nxt
                    q_list.append(np.asarray(q, np.float32))
                    cur = nxt
                    cpos = cpos + 1
                draft_probs = np.stack(q_list, axis=1)   # (S, k, V_draft)
        except Exception:
            logger.error("draft propose failed in generation worker "
                         "%s:\n%s", ctx.service_id, traceback.format_exc())
            self._degrade_spec("draft propose failed")
            return self._decode_round(model, spec, cache, slots, ctx)
        ids2 = np.zeros((n, k + 1), np.int32)
        pos2 = np.tile(np.arange(k + 1, dtype=np.int32), (n, 1))
        for i in part:
            s = slots[i]
            ids2[i, 0] = s.last_id
            ids2[i, 1:] = d_ids[i]
            pos2[i] = s.position + np.arange(k + 1, dtype=np.int32)
        tables = np.stack([
            self._alloc.table_row(i) if i in part
            else self._alloc.idle_row() for i in range(n)])
        vsamp = self._sampling_arrays(slots, ROLE_TARGET, only=part)
        try:
            acc, toks, cache = model.paged_verify_step(
                cache, ids2, pos2, tables, draft_probs, vsamp)
            acc = np.asarray(acc)
            toks = np.asarray(toks)
        except Exception:
            # the verify forward raised BEFORE returning a new cache, so
            # the resident table is intact — degrade typed (the classic
            # cause is a draft/target vocab mismatch) and finish the
            # round plain for everyone
            logger.error("speculative verify failed in generation worker "
                         "%s:\n%s", ctx.service_id, traceback.format_exc())
            self._degrade_spec(
                "verify step failed (draft/target mismatch?)")
            return self._decode_round(model, spec, cache, slots, ctx)
        now = time.monotonic()
        m = _metrics()
        # lint: unguarded(scheduler thread is the only writer; the stats snapshot reads cross-thread and tolerates a stale round count)
        self._spec_rounds += 1
        m["spec_rounds"].inc()
        for i in part:
            s = slots[i]
            if s is None:
                continue
            rule = chaos.hit(
                chaos.SITE_GENERATE,
                f"{self._job_id}/{ctx.service_id}/slot{i}/"
                f"{s.stream.seq_id}")
            if rule is not None:
                if rule.action == chaos.ACTION_DELAY:
                    chaos.sleep_for(rule)
                elif rule.action == chaos.ACTION_DROP:
                    logger.warning(
                        "chaos: muting generation slot %d (%s)", i,
                        s.stream.seq_id)
                    s.muted = True
                else:
                    s.stream.fail(
                        "chaos-injected mid-stream generation fault")
                    self._evict_slot(slots, i, "error")
                    continue
            if s.stream.cancelled:
                self._evict_slot(slots, i, "cancelled")
                continue
            a = int(acc[i])
            # lint: unguarded(scheduler-thread-only writer, stale reads ok)
            self._spec_proposed += k
            # lint: unguarded(scheduler-thread-only writer, stale reads ok)
            self._spec_accepted += a
            m["spec_proposed"].inc(k)
            m["spec_accepted"].inc(a)
            emit: List[int] = []
            finished, reason = False, None
            for t in toks[i, :a + 1]:
                token = int(t)
                s.position += 1
                s.last_id = token
                s.produced += 1
                s.tokens.append(token)
                emit.append(token)
                self._tokens_emitted += 1
                finished, reason = self._finish_reason(s, spec, token)
                if finished:
                    break
            if s.deadline is not None and now >= s.deadline:
                finished, reason = True, "deadline"
            m["intertoken"].observe(now - s.last_step_t)
            s.last_step_t = now
            m["tokens"].inc(len(emit))
            if s.t0 is not None:
                m["ttft"].observe(now - s.t0)
                s.t0 = None
            if not s.muted:
                s.stream.push(emit, finished=finished, reason=reason)
            if finished:
                self._evict_slot(slots, i, reason)
            else:
                # free any block now holding ONLY rejected-suffix rows;
                # stale rows inside the frontier block are overwritten
                # before attention by the next round's writes
                self._alloc.truncate_to(i, s.position)
        if rest:
            cache = self._decode_round(model, spec, cache, slots, ctx,
                                       only=rest)
        return cache

    @staticmethod
    def _finish_reason(slot: _Slot, spec: GenerationSpec, token: int):
        if spec.eos_token_id is not None and token == spec.eos_token_id:
            return True, "eos"
        if slot.produced >= slot.max_tokens:
            return True, "max_tokens"
        if slot.position + 1 >= spec.max_context:
            return True, "context"
        return False, None

    def _evict_slot(self, slots: List[Optional[_Slot]], i: int,
                    reason: str) -> None:
        slots[i] = None
        if self._alloc is not None:
            self._alloc.close_slot(i)
        _metrics()["evictions"].labels(reason or "unknown").inc()

    # kept for compatibility with the ring-path call sites/tests
    def _evict(self, slots: List[Optional[_Slot]], i: int,
               reason: str) -> None:
        self._evict_slot(slots, i, reason)

    @staticmethod
    def _parse_query(query):
        if not isinstance(query, dict):
            raise GenerationRequestError(
                "generation query must be an object with 'prompt_ids'")
        prompt = query.get("prompt_ids")
        if (not isinstance(prompt, (list, tuple)) or not prompt
                or not all(isinstance(t, int) and t >= 0 for t in prompt)):
            raise GenerationRequestError(
                "'prompt_ids' must be a non-empty list of non-negative "
                "token ids")
        cap = max(int(config.GEN_MAX_TOKENS), 1)
        raw = query.get("max_tokens", cap)
        try:
            max_tokens = int(raw)
        except (TypeError, ValueError):
            raise GenerationRequestError(
                f"max_tokens={raw!r} is not an integer") from None
        if max_tokens < 1:
            raise GenerationRequestError(
                f"max_tokens={max_tokens} must be >= 1")
        max_tokens = min(max_tokens, cap)
        max_duration_s = query.get("max_duration_s")
        if max_duration_s is not None:
            try:
                max_duration_s = float(max_duration_s)
            except (TypeError, ValueError):
                raise GenerationRequestError(
                    "max_duration_s must be a number") from None
        raw_t = query.get("temperature", 0.0)
        try:
            temperature = float(raw_t if raw_t is not None else 0.0)
        except (TypeError, ValueError):
            raise GenerationRequestError(
                f"temperature={raw_t!r} is not a number") from None
        if temperature < 0.0:
            raise GenerationRequestError(
                f"temperature={temperature} must be >= 0")
        raw_k = query.get("top_k", 0)
        try:
            top_k = int(raw_k if raw_k is not None else 0)
        except (TypeError, ValueError):
            raise GenerationRequestError(
                f"top_k={raw_k!r} is not an integer") from None
        if top_k < 0:
            raise GenerationRequestError(f"top_k={top_k} must be >= 0")
        raw_p = query.get("top_p", 1.0)
        try:
            top_p = float(raw_p if raw_p is not None else 1.0)
        except (TypeError, ValueError):
            raise GenerationRequestError(
                f"top_p={raw_p!r} is not a number") from None
        if not 0.0 < top_p <= 1.0:
            raise GenerationRequestError(
                f"top_p={top_p} must be in (0, 1]")
        raw_s = query.get("seed")
        if raw_s is not None:
            try:
                seed = int(raw_s)
            except (TypeError, ValueError):
                raise GenerationRequestError(
                    f"seed={raw_s!r} is not an integer") from None
            if seed < 0:
                raise GenerationRequestError(f"seed={seed} must be >= 0")
        elif temperature > 0.0:
            # derive one NOW and keep it for the stream's whole life —
            # a preemption resume must replay the identical sequence
            seed = uuid.uuid4().int & 0x7FFFFFFF
        else:
            seed = 0
        if temperature > 0.0 and not bool(config.GEN_SAMPLING):
            raise GenerationRequestError(
                "sampled generation is disabled on this deployment "
                "(RAFIKI_GEN_SAMPLING=0)")
        return (list(prompt), max_tokens, max_duration_s,
                (temperature, top_k, top_p, seed))

    @staticmethod
    def _parse_resume(query) -> List[int]:
        """The committed-token history of a door-side RESUME request
        ([] for a fresh stream). The worker prefills prompt+history
        under the stream's pinned seed; the position-keyed counter RNG
        (PR 18 invariant) then continues the sampled sequence
        token-identically from where the dead replica stopped."""
        raw = query.get("resume_tokens") if isinstance(query, dict) \
            else None
        if raw is None:
            return []
        if (not isinstance(raw, (list, tuple))
                or not all(isinstance(t, int) and t >= 0 for t in raw)):
            raise GenerationRequestError(
                "'resume_tokens' must be a list of non-negative token "
                "ids")
        return list(raw)

    # -- observability -------------------------------------------------------

    def _occupancy(self, slots, max_slots: int) -> float:
        """The autoscaler's saturation signal: under the paged layout the
        binding resource is POOL BLOCKS, not slots — a few long streams
        can exhaust the pool with the slot table half empty, and block
        occupancy is what predicts the next admission stalling."""
        if self._alloc is not None:
            return self._alloc.used_blocks() / self._alloc.pool_blocks
        busy = sum(1 for s in slots if s is not None)
        return busy / max_slots

    def _mirror_alloc(self, service_id: str, m) -> None:
        """Mirror the allocator's cumulative counters into the PR-6
        registry by delta (one site per loop — host-side bookkeeping has
        no natural increment hook) and refresh the pool gauges."""
        if self._alloc is None:
            return
        st = self._alloc.stats()
        last = self._last_alloc_stats
        for key, counter in (("prefix_hits", "prefix_hits"),
                             ("prefix_misses", "prefix_misses"),
                             ("prefix_hit_tokens", "prefix_tokens"),
                             ("cow_copies", "cow"),
                             ("cache_evictions", "prefix_evictions")):
            delta = st[key] - last.get(key, 0)
            if delta > 0:
                m[counter].inc(delta)
        self._last_alloc_stats = st
        m["kv_used"].labels(service_id).set(st["used_blocks"])
        m["kv_pool"].labels(service_id).set(st["pool_blocks"])

    def _stats_row(self, service_id: str, slots, max_slots: int) -> None:
        """Fold the slot picture into the shared SERVING_STATS row (the
        /healthz + fleet-health + stats-relay surface every PR already
        reads); the 'queries' counter stays the admitted-request count.
        ``gen_tokens`` advances every decode round, so the process-mode
        stats relay (report_stats dedupes on an unchanged row) keeps
        pushing — and the admin keeps re-recording the occupancy ring —
        for as long as the table is actually decoding, even when
        occupancy itself sits pinned at full. Under the paged layout the
        row also carries the block-pool picture (the admin relay then
        records BLOCK occupancy into the autoscaler ring) and the prefix
        hit counters fleet health aggregates per job."""
        busy = sum(1 for s in slots if s is not None)
        with _stats_lock:
            s = SERVING_STATS.setdefault(
                service_id, {"batches": 0, "queries": 0})
            s["gen_slots_busy"] = busy
            s["gen_slots_max"] = max_slots
            # resident + preempted-stashed: what a drain must wait out
            # (admin/services.py _drain_one) before destroying
            s["gen_resident_streams"] = busy + len(
                getattr(self, "_pending", ()))
            s["gen_tokens"] = getattr(self, "_tokens_emitted", 0)
            s["gen_job"] = self._job_id
            s["gen_spec_on"] = bool(getattr(self, "_spec_on", False))
            s["gen_spec_proposed"] = getattr(self, "_spec_proposed", 0)
            s["gen_spec_accepted"] = getattr(self, "_spec_accepted", 0)
            s["gen_spec_rounds"] = getattr(self, "_spec_rounds", 0)
            deg = getattr(self, "_spec_degraded", None)
            if deg:
                s["gen_spec_degraded"] = deg
            if self._alloc is not None:
                st = self._last_alloc_stats or self._alloc.stats()
                s["gen_kv_blocks_used"] = st["used_blocks"]
                s["gen_kv_pool_blocks"] = st["pool_blocks"]
                s["gen_kv_block_tokens"] = st["block_tokens"]
                s["gen_prefix_hits"] = st["prefix_hits"]
                s["gen_prefix_misses"] = st["prefix_misses"]
                s["gen_prefix_hit_tokens"] = st["prefix_hit_tokens"]
