"""Worker-process bootstrap: `python -m rafiki_tpu.worker.bootstrap`.

The analogue of the reference's in-container entrypoint (reference
scripts/start_worker.py:15-25 dispatching on RAFIKI_SERVICE_TYPE, and
rafiki/utils/service.py:10-46 installing signal handlers and marking the
service RUNNING/ERRORED in the store). Launched by ProcessPlacementManager
with everything it needs in env:

    RAFIKI_SERVICE_ID / RAFIKI_SERVICE_TYPE   identity + dispatch
    RAFIKI_CHIP_GRANT                         comma-sep jax.devices() indices
    RAFIKI_DB_PATH                            shared SQLite/WAL file
    RAFIKI_SUB_TRAIN_JOB_ID                   (TRAIN)
    RAFIKI_INFERENCE_JOB_ID, RAFIKI_TRIAL_ID  (INFERENCE)
    RAFIKI_ADMIN_ADDR                         host:port for advisor/events
    RAFIKI_BROKER_PREFIX                      shm data-plane namespace

Status protocol: RUNNING is written on ctx.ready() (startup really
succeeded), STOPPED on clean exit/SIGTERM, ERRORED on crash — rc mirrors it
so the parent's monitor can backstop a silent death.
"""

from __future__ import annotations

import logging
import os
import signal
import sys
import threading
import traceback

logger = logging.getLogger(__name__)


def _require(name: str) -> str:
    v = os.environ.get(name)
    if not v:
        raise RuntimeError(f"bootstrap: missing env {name}")
    return v


def main() -> int:
    # Honor JAX_PLATFORMS in the child explicitly: site hooks that register
    # a remote-TPU plugin can initialize it from backends() regardless of
    # the env var, and a worker meant for CPU (tests, CPU-fallback
    # services) must never block on a TPU tunnel. The config update wins
    # as long as no computation has run yet (same trick as
    # tests/conftest.py).
    platforms = os.environ.get("JAX_PLATFORMS")
    if platforms:
        import jax

        jax.config.update("jax_platforms", platforms)

    from rafiki_tpu import config
    from rafiki_tpu.constants import ServiceType
    from rafiki_tpu.db.database import Database
    from rafiki_tpu.placement.manager import ServiceContext

    service_id = _require("RAFIKI_SERVICE_ID")
    service_type = _require("RAFIKI_SERVICE_TYPE")

    logging.basicConfig(
        level=logging.INFO,
        format=f"%(levelname)s:%(asctime)s:{service_id[:8]}:%(name)s: "
               "%(message)s",
    )

    chips = [int(c) for c in os.environ.get("RAFIKI_CHIP_GRANT", "").split(",")
             if c.strip()]
    db = Database(_require("RAFIKI_DB_PATH"))

    stop_event = threading.Event()

    def on_signal(signum, frame):
        logger.info("signal %s: stopping", signum)
        stop_event.set()

    signal.signal(signal.SIGTERM, on_signal)
    signal.signal(signal.SIGINT, on_signal)

    # Orphan watchdog: if the parent placement manager dies without managing
    # to SIGTERM us (hard kill mid-teardown, agent crash), this process must
    # not linger — an orphaned serving worker with a torn-down data plane
    # spins forever and, on a small host, starves everything else. Detected
    # by reparenting (PPID becomes init).
    #
    # Control-plane crash recovery (RAFIKI_ORPHAN_SURVIVE=1, set by an
    # ADMIN-embedded engine for TRAIN children only): the parent dying is
    # an admin crash, and THIS worker is the thing recovery adopts by pid
    # — so instead of stopping on reparent, keep working and watch the
    # shared store: exit only when the service row goes terminal (a
    # restarted admin fenced or stopped us, or we finished on our own).
    # Agent-spawned children never get the flag: an agent's death is a
    # host failure and the PR-1 reschedule must never find the old
    # executor still running.
    parent0 = os.getppid()
    survivable = (os.environ.get("RAFIKI_ORPHAN_SURVIVE") == "1"
                  and service_type == ServiceType.TRAIN)

    def watch_parent():
        orphaned = False
        while not stop_event.wait(2.0):
            if not orphaned and os.getppid() != parent0:
                if not survivable:
                    logger.warning("parent %d died; stopping", parent0)
                    stop_event.set()
                    return
                orphaned = True
                logger.warning(
                    "parent %d died; surviving for control-plane recovery "
                    "(will stop when the store says so)", parent0)
            if orphaned:
                try:
                    svc = db.get_service(service_id)
                # lint: absorb(store hiccup while orphaned: keep serving, retry next beat)
                except Exception:
                    continue  # store hiccup: keep working
                if svc is None or svc["status"] in ("STOPPED", "ERRORED"):
                    logger.warning("service row is terminal while "
                                   "orphaned; stopping")
                    stop_event.set()
                    return

    threading.Thread(target=watch_parent, name="orphan-watchdog",
                     daemon=True).start()

    ctx = ServiceContext(
        service_id=service_id,
        service_type=service_type,
        chips=chips,
        stop_event=stop_event,
        on_ready=lambda: db.mark_service_as_running(service_id),
    )

    admin_client = None
    addr = os.environ.get("RAFIKI_ADMIN_ADDR")
    if addr:
        from rafiki_tpu.client.client import Client

        host, port = addr.rsplit(":", 1)
        admin_client = Client(admin_host=host, admin_port=int(port))
        admin_client.login(config.SUPERADMIN_EMAIL, config.SUPERADMIN_PASSWORD)

    try:
        if service_type == ServiceType.TRAIN:
            _run_train(ctx, db, admin_client)
        elif service_type == ServiceType.INFERENCE:
            _run_inference(ctx, db, admin_client)
        else:
            raise RuntimeError(f"bootstrap: unsupported type {service_type}")
    except Exception:
        logger.error("service crashed:\n%s", traceback.format_exc())
        try:
            db.mark_service_as_errored(service_id)
        except Exception:
            logger.exception("could not mark errored")
        return 1
    db.mark_service_as_stopped(service_id)
    return 0


def _run_train(ctx, db, admin_client) -> None:
    from rafiki_tpu.worker.train import TrainWorker

    if admin_client is not None:
        from rafiki_tpu.advisor.remote import RemoteAdvisorStore

        advisors = RemoteAdvisorStore(admin_client)

        def send_event(name, payload):
            # best-effort: events are advisory (job refresh also rides
            # the service-status rows) — an admin that happens to be
            # down/restarting at this moment must not error a worker
            # that just finished its work
            try:
                admin_client.send_event(name, **payload)
            except Exception as e:
                logger.warning("event %s could not reach the admin "
                               "(%s); continuing", name, e)
    else:
        # no admin API reachable: process-local advisor (the reference's
        # uncoordinated-parallel-HPO behavior, reference train.py:213)
        from rafiki_tpu.advisor.advisor import AdvisorStore

        logger.warning("no RAFIKI_ADMIN_ADDR; HPO is process-local")
        advisors = AdvisorStore()
        send_event = lambda name, payload: None  # noqa: E731

    worker = TrainWorker(
        _require("RAFIKI_SUB_TRAIN_JOB_ID"),
        db,
        advisors,
        send_event=send_event,
    )
    worker.start(ctx)


def _run_inference(ctx, db, admin_client) -> None:
    from rafiki_tpu.cache.shm_broker import ShmBrokerClient
    from rafiki_tpu.worker.inference import InferenceWorker

    broker = ShmBrokerClient(_require("RAFIKI_BROKER_PREFIX"))
    report = None
    if admin_client is not None:
        # relay serving counters to the admin (its in-process SERVING_STATS
        # cannot see this process) for /inference_jobs/<app>/<v>/stats
        report = lambda payload: admin_client.send_event(  # noqa: E731
            "inference_worker_stats", **payload)
    trial_ids = os.environ.get("RAFIKI_TRIAL_IDS")
    worker = InferenceWorker(
        _require("RAFIKI_INFERENCE_JOB_ID"),
        _require("RAFIKI_TRIAL_ID"),
        db,
        broker,
        report_stats=report,
        # fused ensemble group (budget ENSEMBLE_FUSED)
        trial_ids=trial_ids.split(",") if trial_ids else None,
    )
    worker.start(ctx)


if __name__ == "__main__":
    sys.exit(main())
