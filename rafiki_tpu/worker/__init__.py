"""Worker runtime (L3): trial execution and model serving loops
(reference rafiki/worker/)."""

from rafiki_tpu.worker.train import TrainWorker  # noqa: F401
from rafiki_tpu.worker.inference import InferenceWorker  # noqa: F401
