"""Inference worker: serves one trained trial's model with continuous
batching.

Parity with the reference's InferenceWorker (reference
rafiki/worker/inference.py:19-105): register in the job's worker set, load the
trial's model (class bytes from the store + persisted params), serve batches.

TPU-native difference: instead of popping <=32 queries from a Redis list every
0.25 s (reference inference.py:43-65, config.py:17-18), the worker blocks on a
condition-variable queue and wakes the instant a query lands, draining up to
``PREDICT_MAX_BATCH_SIZE`` of whatever has queued — batches fill under load
because queries accumulate during the previous dispatch, and a single query
at idle is served immediately (PREDICT_BATCH_DEADLINE_MS defaults to 0).
"""

from __future__ import annotations

import logging
import threading
import time
import traceback
from typing import Any, Dict, List, Optional

import numpy as np

from rafiki_tpu import config
from rafiki_tpu.cache.queue import Broker
from rafiki_tpu.db.database import Database
from rafiki_tpu.utils import chaos
from rafiki_tpu.parallel.mesh import set_device_grant
from rafiki_tpu.placement.manager import ServiceContext
from rafiki_tpu.sdk.model import load_model_class
from rafiki_tpu.sdk.params import load_params

logger = logging.getLogger(__name__)

# Per-service serving counters (batches served, queries served), updated by
# the worker loop so benchmarks and ops can compute *batch occupancy* —
# mean queries/batch, the signal that continuous batching actually
# coalesces under concurrent load instead of serving singletons. Overload
# control adds the queue picture: `queue_depth` (gauge), `expired`
# (queries dropped past their request deadline) and `shed` (queries the
# bounded queue refused) — surfaced through GET /fleet/health.
_stats_lock = threading.Lock()
SERVING_STATS: Dict[str, Dict[str, int]] = {}


def _metrics():
    """Registry mirrors of the serving counters (utils/metrics.py) —
    lazily created so import stays cheap; the JSON SERVING_STATS keeps
    its shape and the mirrors increment at the same sites."""
    global _M
    if _M is None:
        from rafiki_tpu.utils.metrics import REGISTRY

        _M = {
            "batches": REGISTRY.counter(
                "rafiki_serving_batches_total",
                "batches served by inference workers in this process"),
            "queries": REGISTRY.counter(
                "rafiki_serving_queries_total",
                "queries served by inference workers in this process"),
            "batch_size": REGISTRY.histogram(
                "rafiki_serving_batch_size",
                "queries per served batch (continuous-batching occupancy)",
                buckets=[1, 2, 4, 8, 16, 32, 64, 128, 256]),
            "depth": REGISTRY.gauge(
                "rafiki_queue_depth",
                "current worker-queue depth", ("service",)),
            "phase": REGISTRY.histogram(
                "rafiki_worker_phase_seconds",
                "worker-side phase latency per served batch", ("phase",)),
        }
    return _M


_M = None


def serving_stats() -> Dict[str, Dict[str, int]]:
    """Snapshot of {service_id: {batches, queries, ...}} for this process."""
    with _stats_lock:
        return {k: dict(v) for k, v in SERVING_STATS.items()}


def _record_batch(service_id: str, n_queries: int) -> None:
    with _stats_lock:
        s = SERVING_STATS.setdefault(service_id, {"batches": 0, "queries": 0})
        s["batches"] += 1
        s["queries"] += n_queries
    m = _metrics()
    m["batches"].inc()
    m["queries"].inc(n_queries)
    m["batch_size"].observe(n_queries)


def _record_queue(service_id: str, queue) -> None:
    """Fold the queue's overload counters into this service's stats row
    (queues without a stats() signal just contribute nothing). Only the
    keys a queue actually reports are written: condvar queues carry the
    depth/expired/rejected overload picture, shm queues carry the wire
    picture (undecodable frames, ring occupancy high-water)."""
    stats_fn = getattr(queue, "stats", None)
    if not callable(stats_fn):
        return
    try:
        q = stats_fn()
    # lint: absorb(queue stats are best-effort telemetry)
    except Exception:
        return
    with _stats_lock:
        s = SERVING_STATS.setdefault(service_id, {"batches": 0, "queries": 0})
        for src, dst in (("depth", "queue_depth"), ("expired", "expired"),
                         ("rejected", "shed"), ("wire_errors", "wire_errors"),
                         ("ring_used_bytes_hw", "ring_used_bytes_hw")):
            if src in q:
                s[dst] = int(q[src])
    if "depth" in q:
        m = _metrics()
        m["depth"].labels(service_id).set(int(q["depth"]))
        # autoscaler-grade ring series (~1 s resolution): the depth the
        # worker observed at this tick. One ring PER service — a shared
        # ring would interleave last-write-wins samples from every queue
        # in the process into one meaningless sawtooth.
        from rafiki_tpu.utils.metrics import REGISTRY

        REGISTRY.ring(f"queue_depth:{service_id}").record(int(q["depth"]))


def _resolve_batch(futures: List[Any], predictions: Any,
                   service_id: str) -> None:
    """Resolve one served batch, delivering every computed prediction
    and failing the rest with a TYPED error when a buggy model returns
    fewer predictions than queries. Every future MUST resolve here: the
    shm plane's per-frame response flushes only once a frame's futures
    have all resolved, so a silently-dropped future would strand its
    whole request — computed results included — until the SLO."""
    n = len(predictions)
    for fut, pred in zip(futures, predictions):
        fut.set_result(pred)
    if n < len(futures):
        logger.error(
            "model in worker %s returned %d predictions for %d queries",
            service_id, n, len(futures))
        err = RuntimeError(
            f"model returned {n} predictions for {len(futures)} queries")
        for fut in futures[n:]:
            fut.set_error(err)


class _BatchAssembler:
    """Single-copy batch assembly for ndarray queries.

    The old path handed the model a Python list, so every predict paid a
    per-query ``np.asarray`` shuffle over N separate objects. When a
    batch's queries are homogeneous ndarrays (the shape the binary wire
    delivers: zero-copy frombuffer rows), they are now copied ONCE into a
    contiguous batch — into a reused preallocated buffer when the queue
    declares ``reusable_batch_ok`` (shm queues: responses serialize
    inside the resolve loop, so the buffer is dead by the next take;
    in-process futures hand objects across threads, so those batches get
    a fresh ``np.stack`` instead of a buffer a pathological input-echoing
    model could alias). Heterogeneous/non-array batches pass through
    untouched."""

    def __init__(self) -> None:
        self._buf: Optional[np.ndarray] = None

    def assemble(self, queries: List[Any], reusable: bool):
        from rafiki_tpu.cache import wire

        if not wire.stackable(queries):  # the one shared predicate
            return queries
        first = queries[0]
        n = len(queries)
        if not reusable:
            return wire.stack_batch(queries)
        buf = self._buf
        if (buf is None or buf.shape[1:] != first.shape
                or buf.dtype != first.dtype or buf.shape[0] < n):
            cap = max(int(config.PREDICT_MAX_BATCH_SIZE), n)
            buf = self._buf = np.empty((cap,) + first.shape, first.dtype)
        for i, q in enumerate(queries):
            buf[i] = q
        return buf[:n]


class _FusedEnsembleModel:
    """The fused-ensemble serving unit (budget ``ENSEMBLE_FUSED``): every
    best trial's model co-resident in this worker, answering each batch as
    one unit. When the group shares a compiled predict
    (``BaseModel.ensemble_stack``), the whole ensemble is ONE vmapped
    device dispatch; otherwise the models answer sequentially in-process.
    Either way this worker resolves futures with the FINAL (cross-trial
    ensembled) predictions, so the predictor treats the group as a single
    replica set."""

    def __init__(self, models, task: str):
        from rafiki_tpu.predictor.ensemble import ensemble_predictions

        self._models = models
        self._task = task
        self._ensemble = ensemble_predictions
        # sandboxed serving children (sdk/sandbox.py SandboxedModelServer)
        # are separate processes — co-residency is impossible there, so the
        # hook may be absent entirely
        stack_fn = getattr(models[0], "ensemble_stack", None)
        self._stacked = None
        if callable(stack_fn):
            try:
                self._stacked = stack_fn(models)
            except Exception:
                # the hook is TEMPLATE code (ADVICE r5): a raising hook —
                # OOM stacking N param trees, a template bug — must
                # degrade to sequential serving, not fail worker startup
                # and roll back the whole inference job
                logger.exception(
                    "fused worker: ensemble_stack hook raised; falling "
                    "back to sequential in-process serving of %d models",
                    len(models))
        if self._stacked is None and len(models) > 1:
            logger.info(
                "fused worker: trials do not share a compiled predict; "
                "serving %d models sequentially in-process", len(models))

    @property
    def fused_dispatch(self) -> bool:
        return self._stacked is not None

    @property
    def dead(self) -> bool:
        # sandbox-mode members expose .dead when their child process died
        # and will never recover; the worker loop reads this to exit and
        # let placement's restart policy replace the whole replica
        return any(getattr(m, "dead", False) for m in self._models)

    def predict(self, queries):
        if self._stacked is not None:
            per_model = self._stacked.predict_all(queries)
        else:
            per_model = [m.predict(queries) for m in self._models]
        return [
            self._ensemble([pm[i] for pm in per_model], self._task)
            for i in range(len(queries))
        ]

    def warm_up(self):
        if self._stacked is not None and hasattr(self._stacked, "warm_up"):
            self._stacked.warm_up()
        else:
            for m in self._models:
                m.warm_up()

    def destroy(self):
        for m in self._models:
            try:
                m.destroy()
            except Exception:
                logger.exception("destroy failed for a fused-ensemble model")


class InferenceWorker:
    def __init__(
        self,
        inference_job_id: str,
        trial_id: str,
        db: Database,
        broker: Broker,
        report_stats=None,
        report_interval_s: float = 5.0,
        trial_ids: Optional[list] = None,
    ):
        """``report_stats({"service_id", "batches", "queries"})`` relays
        cumulative serving counters to a remote admin (process placement —
        the admin cannot see this process's SERVING_STATS). Pushed from a
        background thread every ``report_interval_s`` (and once at ready
        and at exit) so counters stay fresh even when traffic pauses;
        best-effort."""
        self._job_id = inference_job_id
        self._trial_id = trial_id
        #: fused-ensemble mode (budget ENSEMBLE_FUSED): ALL the job's best
        #: trials co-served by this one worker; ``trial_id`` is then the
        #: group's top trial (the bookkeeping row)
        self._trial_ids = list(trial_ids) if trial_ids else [trial_id]
        self._db = db
        self._broker = broker
        self._report_stats = report_stats
        self._report_interval_s = report_interval_s

    def _stats_reporter(self, ctx: ServiceContext) -> None:
        """Push cumulative counters on a fixed cadence, independent of
        traffic (a throttle piggybacked on the serve loop would leave the
        last batches before a pause unreported). First push immediately —
        benches/dashboards read stats right after the first predicts."""
        last = None

        def push():
            nonlocal last
            s = serving_stats().get(ctx.service_id,
                                    {"batches": 0, "queries": 0})
            # warm-state fields from this boot's warm-up report ride on
            # every row (static after boot — cheap) so fleet health can
            # show per-replica warm + last-boot compile seconds
            from rafiki_tpu.worker.warmup import stats_row_fields

            s = {**s, **stats_row_fields(ctx.service_id)}
            if s == last:
                return
            try:
                self._report_stats({"service_id": ctx.service_id, **s})
                # only remember a SUCCESSFUL push — a transient failure
                # must retry on the next tick even with unchanged counters
                last = s
            except Exception:
                logger.warning("stats report failed (continuing)",
                               exc_info=True)

        while True:
            push()
            if ctx.stop_event.wait(self._report_interval_s):
                push()  # final snapshot: batches since the last tick
                return

    def _load_model(self, service_id: str):
        if len(self._trial_ids) > 1:
            models = [
                self._load_one(tid, f"{service_id}-m{i}")
                for i, tid in enumerate(self._trial_ids)
            ]
            inf = self._db.get_inference_job(self._job_id)
            assert inf is not None
            train_job = self._db.get_train_job(inf["train_job_id"])
            assert train_job is not None
            return _FusedEnsembleModel(models, train_job["task"])
        return self._load_one(self._trial_id, service_id)

    def _load_one(self, trial_id: str, service_id: str):
        trial = self._db.get_trial(trial_id)
        assert trial is not None, f"no trial {trial_id}"
        model_row = self._db.get_model(trial["model_id"])
        assert model_row is not None
        from rafiki_tpu.sdk.deps import activate_prefix, ensure_dependencies
        from rafiki_tpu.sdk.sandbox import sandbox_enabled

        prefix = ensure_dependencies(model_row.get("dependencies"))
        from rafiki_tpu.sdk.artifact import read_artifact

        # verified read: a truncated/bit-rotten params file raises the
        # typed ArtifactCorruptError here — the deploy path surfaces it as
        # a clean ServiceDeploymentError instead of a msgpack traceback
        params_bytes = read_artifact(trial["params_file_path"])
        if sandbox_enabled():
            # serving isolation parity with the trial path: the uploaded
            # template answers batches from a locked-down child; this
            # trusted worker keeps the store, the params file, and the
            # data plane (sdk/sandbox.py SandboxedModelServer — warm-up
            # happens child-side before the ready frame)
            from rafiki_tpu.sdk.sandbox import (
                SandboxedModelServer,
                make_jail,
            )

            return SandboxedModelServer(
                model_row["model_file_bytes"], model_row["model_class"],
                trial["knobs"], params_bytes,
                make_jail(config.WORKDIR, f"serve-{service_id}"),
                extra_pythonpath=prefix,
            )
        activate_prefix(prefix)
        clazz = load_model_class(
            model_row["model_file_bytes"], model_row["model_class"]
        )
        model = clazz(**trial["knobs"])
        model.load_parameters(load_params(params_bytes))
        return model

    def start(self, ctx: ServiceContext) -> None:
        set_device_grant(ctx.chips)
        model = None
        assembler = _BatchAssembler()
        queue = self._broker.register_worker(self._job_id, ctx.service_id)
        try:
            model = self._load_model(ctx.service_id)
            # compile every serving batch bucket before accepting
            # traffic — a mid-traffic XLA compile is a multi-second
            # p99 spike (the reference never compiled anything, but
            # paid 0.25 s polls instead). run_warmup enables the
            # persistent compile cache, times the compiles, and records
            # this boot's cold/warm verdict; it runs BEFORE ctx.ready()
            # so a still-compiling replica stays DEPLOYING/unroutable.
            from rafiki_tpu.worker.warmup import run_warmup

            run_warmup(ctx.service_id, self._job_id,
                       [("warm_up", model.warm_up)])
            ctx.ready()  # model + params loaded: startup succeeded
            if self._report_stats is not None:
                threading.Thread(
                    target=self._stats_reporter, args=(ctx,),
                    name="stats-reporter", daemon=True).start()
            while not ctx.stopping:
                batch = queue.take_batch(
                    max_size=config.PREDICT_MAX_BATCH_SIZE,
                    deadline_s=config.PREDICT_BATCH_DEADLINE_MS / 1000.0,
                )
                if batch is None:
                    # the data plane was closed under us (broker teardown,
                    # owner gone): serving is over — exit instead of
                    # spinning on a queue that answers instantly
                    logger.info("query queue closed; worker %s exiting",
                                ctx.service_id)
                    break
                if not batch:
                    # still publish the queue gauge/counters on idle ticks
                    # and on takes that only dropped expired entries
                    _record_queue(ctx.service_id, queue)
                    continue
                _record_batch(ctx.service_id, len(batch))
                _record_queue(ctx.service_id, queue)
                futures = [f for f, _ in batch]
                # trace sinks for sampled requests in this batch — the
                # in-process future carries the door's RequestTrace, the
                # shm handle its frame responder; both accept
                # add_span(name, start, end). Deduplicated: a request's
                # entries share one sink.
                sinks = []
                for f in futures:
                    sink = getattr(f, "trace", None)
                    if sink is not None and all(s is not sink
                                                for s in sinks):
                        sinks.append(sink)
                t_asm = time.monotonic()
                queries = assembler.assemble(
                    [q for _, q in batch],
                    reusable=getattr(queue, "reusable_batch_ok", False))
                t_fwd = time.monotonic()
                for sink in sinks:
                    sink.add_span("batch_assembly", t_asm, t_fwd)
                rule = chaos.hit(chaos.SITE_WORKER,
                                 f"{self._job_id}/{ctx.service_id}")
                if rule is not None:
                    # deterministic overload drills (RAFIKI_CHAOS
                    # site=worker): slow replica / silent stall / failing
                    # replica, injected between take and predict so queue
                    # bounding and admission shed upstream are what a test
                    # observes
                    if rule.action == chaos.ACTION_DELAY:
                        chaos.sleep_for(rule)
                    elif rule.action == chaos.ACTION_DROP:
                        # swallow the batch: futures never resolve — the
                        # predictor's SLO/hedging machinery owns recovery
                        logger.warning(
                            "chaos: worker %s stalling a %d-query batch",
                            ctx.service_id, len(batch))
                        continue
                    else:  # ACTION_ERROR
                        err = RuntimeError("chaos-injected worker error")
                        for fut in futures:
                            fut.set_error(err)
                        continue
                try:
                    predictions = model.predict(queries)
                    t_done = time.monotonic()
                    m = _metrics()
                    m["phase"].labels("batch_assembly").observe(
                        t_fwd - t_asm)
                    m["phase"].labels("model_forward").observe(
                        t_done - t_fwd)
                    for sink in sinks:
                        sink.add_span("model_forward", t_fwd, t_done)
                    _resolve_batch(futures, predictions, ctx.service_id)
                except Exception as e:
                    logger.error(
                        "predict failed in worker %s:\n%s",
                        ctx.service_id,
                        traceback.format_exc(),
                    )
                    for fut in futures:
                        fut.set_error(e)
                    if getattr(model, "dead", False):
                        # a dead sandbox child never recovers — exit so
                        # placement's restart policy replaces this worker
                        # instead of serving errors forever
                        raise
        finally:
            self._broker.unregister_worker(self._job_id, ctx.service_id)
            if model is not None:
                model.destroy()
            set_device_grant(None)
