"""Paged KV allocator + shared prefix cache — host-side block bookkeeping.

The generation worker's decode memory used to be one contiguous K/V ring
per slot: HBM cost ``slots x max_context`` whatever the actual sequence
lengths, which caps co-resident streams at the worst case. This module
implements the block-granular alternative (PagedAttention, Kwon et al.
2023): a fixed pool of ``block_tokens``-sized pages plus a per-slot block
table, so a stream only holds pages for tokens it has actually written —
slot count is bound by *used* tokens.

On top of the pool sits a **shared prefix cache** (RadixAttention-style
prefix reuse): after a prompt's prefill, its full blocks are published
under a content hash of the token prefix they hold, refcounted, and mapped
read-only into later streams that share the prefix — N streams with one
system prompt pay its prefill once. The partial tail block is published
too; any write into a shared block goes through **copy-on-write**
(``ensure_writable``), so two streams diverging after a shared prefix can
never corrupt each other's tails.

Division of labour: this class is pure host-side bookkeeping — block ids,
refcounts, tables, hashes, and *copy instructions*. The model owns the
device arrays (models/lm.py ``paged_prefill``/``paged_decode_step``/
``copy_kv_blocks``); the worker (worker/generation.py) is the only caller
and drives both from its single serve thread, so no locking is needed
here. Pool exhaustion is the caller's signal to preempt the youngest
stream (blocks freed, request re-queued) rather than crash a round.

Correctness contract for partial tail reuse: a matched tail block may
carry rows beyond the matched length that belong to the *publisher's*
prompt. Those rows sit at logical positions the new stream's own suffix
prefill (or decode) writes BEFORE attention can read them — the same
write-then-attend ordering the ring path already relies on for bucket
padding — so stale rows are never attended.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np


class KVPoolExhaustedError(RuntimeError):
    """The pool cannot hold even one stream's working set — a typed
    stream-level error (the caller fails THAT stream; siblings and the
    worker keep serving)."""


def _digest(tokens: Sequence[int]) -> str:
    return hashlib.sha1(
        np.asarray(list(tokens), np.int32).tobytes()).hexdigest()


def _common_prefix_len(a: Sequence[int], b: Sequence[int]) -> int:
    n = min(len(a), len(b))
    i = 0
    while i < n and a[i] == b[i]:
        i += 1
    return i


class AdmitPlan:
    """What :meth:`PagedKVAllocator.open_slot` resolved for a prompt:
    ``cached_tokens`` logical positions 0..cached_tokens-1 are already in
    the pool (shared chain blocks + a copied tail), and ``copies`` are
    (src, dst) block pairs the caller must apply to the device cache
    (``copy_kv_blocks``) before running any forward for this slot."""

    __slots__ = ("cached_tokens", "copies")

    def __init__(self, cached_tokens: int,
                 copies: List[Tuple[int, int]]) -> None:
        self.cached_tokens = cached_tokens
        self.copies = copies


class PagedKVAllocator:
    """Block pool + per-slot tables + refcounted prefix cache.

    ``pool_blocks`` physical pages of ``block_tokens`` K/V rows each;
    ``table_blocks`` is the fixed per-slot table width (ceil(max_context /
    block_tokens)) so the jitted decode program's shapes never change.
    The sentinel id ``pool_blocks`` marks unallocated table entries —
    the model layer drops writes through it.
    """

    def __init__(self, pool_blocks: int, block_tokens: int,
                 table_blocks: int, prefix_cache: bool = True,
                 max_tails_per_chain: int = 4) -> None:
        if pool_blocks < 1 or block_tokens < 1 or table_blocks < 1:
            raise ValueError(
                f"degenerate paged-KV geometry: pool_blocks={pool_blocks} "
                f"block_tokens={block_tokens} table_blocks={table_blocks}")
        self.pool_blocks = int(pool_blocks)
        self.block_tokens = int(block_tokens)
        self.table_blocks = int(table_blocks)
        self.sentinel = self.pool_blocks
        self.prefix_cache = bool(prefix_cache)
        self.max_tails_per_chain = int(max_tails_per_chain)
        self._free: List[int] = list(range(self.pool_blocks - 1, -1, -1))
        self._refs = [0] * self.pool_blocks
        self._tables: Dict[Any, List[int]] = {}
        self._shared: Dict[Any, set] = {}
        #: LRU-ordered cache entries: chain entries keyed by the prefix
        #: digest, tail entries by ("tail", chain_digest, tokens_tuple)
        self._entries: "OrderedDict[Any, Dict[str, Any]]" = OrderedDict()
        self._tails: Dict[str, List[tuple]] = {}
        # counters (mirrored into the PR-6 registry by the worker)
        self.hits = 0
        self.misses = 0
        self.hit_tokens = 0
        self.cow_copies = 0
        self.cache_evictions = 0

    # -- pool primitives -----------------------------------------------------

    def free_blocks(self) -> int:
        return len(self._free)

    def used_blocks(self) -> int:
        return self.pool_blocks - len(self._free)

    def evictable_blocks(self) -> int:
        """Cache-only blocks (refcount 1, held by no slot) LRU eviction
        could reclaim right now."""
        return sum(1 for e in self._entries.values()
                   if self._refs[e["block"]] == 1)

    def blocks_for(self, tokens: int) -> int:
        return -(-max(int(tokens), 0) // self.block_tokens)

    def _alloc_one(self) -> Optional[int]:
        """One private block (refcount 1), evicting LRU cache-only
        entries if the free list is dry. None = genuinely exhausted."""
        if not self._free and not self._evict_lru():
            return None
        b = self._free.pop()
        self._refs[b] = 1
        return b

    def _evict_lru(self) -> bool:
        for key, e in self._entries.items():
            if self._refs[e["block"]] == 1:
                self._drop_entry(key)
                self.cache_evictions += 1
                return True
        return False

    def _drop_entry(self, key: Any) -> None:
        e = self._entries.pop(key)
        b = e["block"]
        self._refs[b] -= 1
        if self._refs[b] == 0:
            self._free.append(b)
        if e["kind"] == "tail":
            toks = self._tails.get(e["chain"], [])
            if e["tokens"] in toks:
                toks.remove(e["tokens"])
                if not toks:
                    self._tails.pop(e["chain"], None)

    # -- slot lifecycle ------------------------------------------------------

    def open_slot(self, slot: Any, prompt: Sequence[int]) -> AdmitPlan:
        """Map the longest cached prefix of ``prompt`` into a new slot's
        table (shared chain blocks refcounted; a matching partial tail is
        COPIED into a private block — the 'copy' of copy-on-write). At
        most ``len(prompt) - 1`` tokens come from cache: the last prompt
        token is always forwarded so prefill has logits to return."""
        if slot in self._tables:
            raise ValueError(f"slot {slot!r} already open")
        prompt = list(prompt)
        usable = len(prompt) - 1
        bt = self.block_tokens
        table: List[int] = []
        shared: set = set()
        copies: List[Tuple[int, int]] = []
        cached = 0
        if self.prefix_cache and usable > 0:
            c = 0
            while (c + 1) * bt <= usable and c < self.table_blocks:
                d = _digest(prompt[:(c + 1) * bt])
                e = self._entries.get(d)
                if e is None:
                    break
                table.append(e["block"])
                self._refs[e["block"]] += 1
                shared.add(c)
                self._entries.move_to_end(d)
                c += 1
            cached = c * bt
            chain_d = _digest(prompt[:cached])
            best_key = None
            best_t = 0
            for toks in self._tails.get(chain_d, ()):
                key = ("tail", chain_d, toks)
                e = self._entries.get(key)
                if e is None:
                    continue
                t = _common_prefix_len(toks, prompt[cached:usable])
                if t > best_t:
                    best_t, best_key = t, key
            if best_key is not None and len(table) < self.table_blocks:
                # pin the source entry across the allocation: _alloc_one
                # may LRU-evict refcount-1 cache entries, and the matched
                # tail (not yet touched this admission) is a prime victim
                # — unpinned, its freed block could even be handed back
                # as the copy TARGET
                src_block = self._entries[best_key]["block"]
                self._refs[src_block] += 1
                dst = self._alloc_one()
                self._refs[src_block] -= 1
                if dst is not None:
                    copies.append((src_block, dst))
                    table.append(dst)
                    cached += best_t
                    self._entries.move_to_end(best_key)
                    self.cow_copies += 1
        if cached > 0:
            self.hits += 1
            self.hit_tokens += cached
        else:
            self.misses += 1
        self._tables[slot] = table
        self._shared[slot] = shared
        return AdmitPlan(cached, copies)

    def close_slot(self, slot: Any) -> None:
        """Release every block the slot maps: private refcounts drop to
        zero and return to the free list; shared blocks stay alive under
        the cache's own reference."""
        table = self._tables.pop(slot, None)
        self._shared.pop(slot, None)
        if table is None:
            return
        for b in table:
            self._refs[b] -= 1
            if self._refs[b] == 0:
                self._free.append(b)

    def truncate_to(self, slot: Any, tokens: int) -> int:
        """Shed blocks wholly past logical row ``tokens - 1`` — the
        speculative-decode rollback: a verify round allocates capacity
        for all k drafted positions up front, and when fewer are accepted
        the blocks that only ever held rejected-suffix K/V go back to the
        pool (no device-side work: the model layer's write-then-attend
        ordering guarantees stale rows are overwritten before any query
        can attend them). Shared blocks are dereferenced exactly like
        :meth:`close_slot` — a published prefix can never sit past the
        committed frontier anyway. Returns blocks freed to the pool."""
        table = self._tables[slot]
        keep = self.blocks_for(tokens)
        freed = 0
        while len(table) > keep:
            b = table.pop()
            self._shared[slot].discard(len(table))
            self._refs[b] -= 1
            if self._refs[b] == 0:
                self._free.append(b)
                freed += 1
        return freed

    def ensure_capacity(self, slot: Any, position: int) -> bool:
        """Grow the slot's table until it covers logical ``position``
        (the next write). False = pool exhausted even after cache
        eviction — the caller preempts the youngest stream and retries."""
        if position >= self.table_blocks * self.block_tokens:
            raise KVPoolExhaustedError(
                f"position {position} is past the table "
                f"({self.table_blocks} x {self.block_tokens} tokens)")
        table = self._tables[slot]
        need = position // self.block_tokens + 1
        while len(table) < need:
            b = self._alloc_one()
            if b is None:
                return False
            table.append(b)
        return True

    def ensure_writable(self, slot: Any, position: int
                        ) -> Optional[List[Tuple[int, int]]]:
        """Copy-on-write barrier: if the block holding ``position`` is
        shared (a published tail another stream — or the cache — still
        references), move this slot onto a private copy first. Returns
        the (src, dst) copy list to apply (usually empty), or None when
        the pool cannot supply the copy target (caller preempts)."""
        ix = position // self.block_tokens
        table = self._tables[slot]
        if ix >= len(table) or ix not in self._shared[slot]:
            return []
        dst = self._alloc_one()
        if dst is None:
            return None
        src = table[ix]
        table[ix] = dst
        self._shared[slot].discard(ix)
        self._refs[src] -= 1
        if self._refs[src] == 0:  # defensive: shared implies a cache ref
            self._free.append(src)
        self.cow_copies += 1
        return [(src, dst)]

    def publish(self, slot: Any, prompt: Sequence[int]) -> None:
        """Offer a freshly-prefilled prompt to the prefix cache: every
        full block under its chain digest, the partial tail block (if
        any) under its chain + token tuple. Published blocks gain a cache
        reference and become copy-on-write for the OWNER too — its next
        decode write into the tail block goes through a private copy,
        leaving the cached content immutable."""
        if not self.prefix_cache:
            return
        prompt = list(prompt)
        bt = self.block_tokens
        table = self._tables[slot]
        shared = self._shared[slot]
        fb = len(prompt) // bt
        for i in range(min(fb, len(table))):
            d = _digest(prompt[:(i + 1) * bt])
            if d in self._entries:
                self._entries.move_to_end(d)
                continue
            b = table[i]
            self._entries[d] = {"kind": "chain", "block": b}
            self._refs[b] += 1
            shared.add(i)
        r = len(prompt) - fb * bt
        if r > 0 and fb < len(table):
            chain_d = _digest(prompt[:fb * bt])
            toks = tuple(prompt[fb * bt:])
            key = ("tail", chain_d, toks)
            tails = self._tails.setdefault(chain_d, [])
            if key not in self._entries \
                    and len(tails) < self.max_tails_per_chain:
                b = table[fb]
                self._entries[key] = {"kind": "tail", "block": b,
                                      "chain": chain_d, "tokens": toks}
                tails.append(toks)
                self._refs[b] += 1
                shared.add(fb)

    # -- views ---------------------------------------------------------------

    def table_row(self, slot: Any) -> np.ndarray:
        """The slot's fixed-width table row, sentinel-padded — what the
        jitted paged forwards consume."""
        row = np.full(self.table_blocks, self.sentinel, np.int32)
        t = self._tables[slot]
        row[:len(t)] = t
        return row

    def idle_row(self) -> np.ndarray:
        return np.full(self.table_blocks, self.sentinel, np.int32)

    def refcounts(self) -> List[int]:
        return list(self._refs)

    def drop_cache(self) -> int:
        """Evict every cache-only entry (deploy/rollback flush and the
        refcount drill); returns blocks freed. Entries still mapped by a
        live slot stay until that slot closes."""
        freed = 0
        for key in [k for k, e in self._entries.items()
                    if self._refs[e["block"]] == 1]:
            self._drop_entry(key)
            freed += 1
            self.cache_evictions += 1
        return freed

    def stats(self) -> Dict[str, int]:
        return {
            "pool_blocks": self.pool_blocks,
            "block_tokens": self.block_tokens,
            "used_blocks": self.used_blocks(),
            "free_blocks": self.free_blocks(),
            "cache_entries": len(self._entries),
            "evictable_blocks": self.evictable_blocks(),
            "prefix_hits": self.hits,
            "prefix_misses": self.misses,
            "prefix_hit_tokens": self.hit_tokens,
            "cow_copies": self.cow_copies,
            "cache_evictions": self.cache_evictions,
        }
