"""JAX/XLA training backend for model templates.

This is the seam the whole rebuild pivots on: where the reference's model
templates each hand-rolled a TF1 session loop on whatever GPU the container
saw (e.g. reference examples/models/image_classification/TfFeedForward.py:55-67),
models here describe *pure functions* — ``init_fn(rng) -> params`` and
``loss_fn(params, batch, rng) -> (loss, aux)`` — and the framework:

- jits one fused train step (forward + backward + optimizer) with donated
  buffers, so weights never leave HBM between steps;
- shards the batch over the mesh's ``data`` axis and replicates params; XLA
  inserts the gradient ``psum`` over ICI (the TPU-native replacement for the
  reference's only collective, ``tf.contrib.nccl.all_sum`` at
  pg_gans.py:1165-1170);
- keeps shapes static (remainder batches are dropped in training and padded +
  masked in eval) so the step compiles once per (model, static-knob) bucket.
"""

from __future__ import annotations

import collections
import logging
import os
import threading
import time
from functools import partial
from typing import Any, Callable, Dict, Hashable, Iterator, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from rafiki_tpu.parallel.mesh import DATA_AXIS, get_default_mesh, visible_devices
from rafiki_tpu.sdk.log import StopTrialEarly

LossFn = Callable[[Any, Any, jax.Array], Tuple[jax.Array, Dict[str, jax.Array]]]

logger = logging.getLogger(__name__)


# ---------------------------------------------------------------------------
# Cross-trial compile reuse (SURVEY.md §7.3's trials/hour lever).
#
# The reference paid a container boot + pip install per trial (reference
# scripts/start_worker.py:6-9); the TPU-native equivalent of that tax is XLA
# recompilation. Two layers kill it:
#
# 1. `cached_trainer`: a process-level cache of trainer objects keyed by
#    (model-declared static signature, this thread's device grant). Trials
#    whose knobs differ only in *dynamic* hyperparameters (lr via
#    `tunable_optimizer`) reuse the same jitted train step — zero retrace.
# 2. `enable_persistent_compile_cache`: JAX's on-disk executable cache, so
#    even fresh executor *processes* (ProcessPlacementManager) skip
#    compilation for programs any previous process already built.

_trainer_cache: "collections.OrderedDict[Hashable, Any]" = collections.OrderedDict()
_trainer_cache_lock = threading.Lock()
_TRAINER_CACHE_CAP = int(os.environ.get("RAFIKI_TRAINER_CACHE_CAP", "8"))
# datasets at or below this size are replicated on-device so fit() can run
# each epoch as a single lax.scan dispatch (see DataParallelTrainer.fit)
_SCAN_EPOCH_MAX_BYTES = int(
    os.environ.get("RAFIKI_SCAN_EPOCH_MAX_BYTES", str(256 << 20)))


def cached_trainer(key: Hashable, build: Callable[[], Any]) -> Any:
    """Return a cached trainer for `key` (scoped to this thread's device
    grant), building it with `build()` on first use.

    The key must cover every knob that changes the *compiled program*:
    architecture knobs, batch/image sizes if they alter shapes the trainer
    bakes in, and the model class identity. Dynamic knobs (lr through
    `tunable_optimizer`) stay out of the key — that is the point. LRU-capped
    (RAFIKI_TRAINER_CACHE_CAP, default 8): evicted trainers just free their
    executables; params live outside the trainer so nothing else is lost.
    """
    grant = tuple(d.id for d in visible_devices())
    full_key = (key, grant)
    with _trainer_cache_lock:
        if full_key in _trainer_cache:
            _trainer_cache.move_to_end(full_key)
            return _trainer_cache[full_key]
    trainer = build()
    with _trainer_cache_lock:
        if full_key not in _trainer_cache:
            _trainer_cache[full_key] = trainer
            while len(_trainer_cache) > _TRAINER_CACHE_CAP:
                _trainer_cache.popitem(last=False)
        _trainer_cache.move_to_end(full_key)
        return _trainer_cache[full_key]


def trainer_cache_clear() -> None:
    with _trainer_cache_lock:
        _trainer_cache.clear()


def tunable_optimizer(make: Callable[..., optax.GradientTransformation],
                      **hyperparams: float) -> optax.GradientTransformation:
    """Wrap an optax factory so its hyperparameters become *dynamic* state
    (optax.inject_hyperparams): ``tunable_optimizer(optax.adamw,
    learning_rate=3e-4)``. The jitted train step is then identical for every
    value — trials differing only in these knobs share one executable; the
    per-trial value is set at ``DataParallelTrainer.init(...,
    hyperparams={...})`` time."""
    return optax.inject_hyperparams(make)(**hyperparams)


def set_opt_hyperparams(opt_state: Any, hyperparams: Dict[str, float]) -> Any:
    """Override injected hyperparameter values in an opt_state produced by a
    `tunable_optimizer` (no-op keys raise — a typo must not silently train
    at the wrong lr)."""
    hp = getattr(opt_state, "hyperparams", None)
    if hp is None:
        raise ValueError(
            "opt_state has no injected hyperparams; build the optimizer "
            "with tunable_optimizer(...) to tune it across cached trials")
    for k, v in hyperparams.items():
        if k not in hp:
            raise KeyError(f"optimizer has no hyperparam {k!r}; has {list(hp)}")
        hp[k] = jnp.asarray(v, dtype=jnp.asarray(hp[k]).dtype)
    return opt_state


def enable_persistent_compile_cache(cache_dir: Optional[str] = None) -> Optional[str]:
    """Turn on JAX's on-disk compilation cache (idempotent). Executables
    persist across processes, so a fresh worker re-running a known program
    skips XLA entirely. Returns the cache dir, or None if unavailable.

    Thin alias for :func:`rafiki_tpu.sdk.compile_cache.enable`, which owns
    the topology keying, the typed degrade path, and the hit telemetry
    (docs/failure-model.md "Cold-start faults")."""
    from rafiki_tpu.sdk import compile_cache

    return compile_cache.enable(cache_dir)


def restore_checkpoint_host(path: str, params: Any, opt_state: Any,
                            state: Any = None) -> Dict[str, Any]:
    """Read a fit checkpoint into host pytrees shaped like the given
    targets (the single place the on-disk format is interpreted — both
    DataParallelTrainer and PopulationTrainer restore through here).
    Checkpoints written before the stateful-trainer change carry no
    "state" entry; from_bytes rejects extra target keys, so fall back to a
    matching stateless target (resume must survive a worker upgrade
    mid-trial). try/except rather than pre-parsing: a second full msgpack
    parse would double restore time and host memory."""
    from flax import serialization

    from rafiki_tpu.sdk.artifact import read_artifact

    # verified read: checksummed checkpoints raise the typed
    # ArtifactCorruptError on damage; pre-checksum files pass through
    blob = read_artifact(path)
    target = {"params": params, "opt_state": opt_state,
              "state": state if state is not None else {}, "epoch": 0}
    try:
        return serialization.from_bytes(target, blob)
    except ValueError:
        target = dict(target)
        target.pop("state")
        restored = dict(serialization.from_bytes(target, blob))
        restored["state"] = state if state is not None else {}
        return restored


def shuffled_batches(
    n: int, batch_size: int, rng: np.random.Generator, drop_remainder: bool = True
) -> Iterator[np.ndarray]:
    """Yield shuffled index batches of a fixed size (static shapes for XLA)."""
    perm = rng.permutation(n)
    n_full = n // batch_size
    for i in range(n_full):
        yield perm[i * batch_size : (i + 1) * batch_size]
    if not drop_remainder and n % batch_size:
        yield perm[n_full * batch_size :]


class DataParallelTrainer:
    """Data-parallel trainer over a device mesh.

    Parameters are replicated; batches are sharded on the ``data`` axis.
    Works identically on one chip (mesh of 1) and a v5e-8 slice — only the
    mesh changes, which the placement layer provides.
    """

    def __init__(
        self,
        loss_fn: LossFn,
        optimizer: optax.GradientTransformation,
        predict_fn: Optional[Callable[..., jax.Array]] = None,
        mesh: Optional[Mesh] = None,
        stateful: bool = False,
        serve_int8: Optional[bool] = None,
    ):
        """``stateful=True`` threads a non-trained model state pytree
        (BatchNorm running statistics, EMA copies, ...) through training:

        - ``loss_fn(params, state, batch, rng) -> (loss, (aux, new_state))``
        - ``init_fn(rng) -> (params, state)``; ``init`` returns
          ``(params, opt_state, state)``
        - ``fit(..., state=state)`` returns ``(params, opt_state, state)``
        - ``predict_fn(params, state, x)``; predict/warm take ``state=``

        The state is replicated like params, carried by value through the
        jitted step (donated, so it never leaves HBM), checkpointed next to
        params, and explicitly NOT touched by the optimizer — the trap of
        stuffing it into the params pytree (zero gradients, but weight
        decay would still corrupt it)."""
        self.mesh = mesh or get_default_mesh()
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.predict_fn = predict_fn
        self.stateful = stateful
        self._repl = NamedSharding(self.mesh, P())
        self._data = NamedSharding(self.mesh, P(DATA_AXIS))
        self.n_data = self.mesh.shape[DATA_AXIS]

        # one step body for both modes: `state` is an empty tuple when
        # stateless, so grads/updates/donation logic can't diverge between
        # the two variants
        n_state = 1 if stateful else 0

        def train_step(params, opt_state, state, batch, rng):
            if stateful:
                (loss, (aux, state)), grads = jax.value_and_grad(
                    self.loss_fn, has_aux=True)(params, state, batch, rng)
            else:
                (loss, aux), grads = jax.value_and_grad(
                    self.loss_fn, has_aux=True)(params, batch, rng)
            updates, opt_state = self.optimizer.update(
                grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, state, loss, aux

        self._train_step = jax.jit(
            train_step,
            donate_argnums=(0, 1, 2),
            in_shardings=(self._repl,) * 3 + (self._data, self._repl),
            out_shardings=(self._repl,) * 5,
        )

        # Device-resident epoch scan: the whole epoch as ONE dispatch. The
        # per-step loop pays a host->device put plus a dispatch per batch —
        # ~15-20 ms each through a remote-chip tunnel, which for small
        # AutoML datasets dwarfs the compute. Here the dataset is uploaded
        # once (replicated), the shuffled index matrix ships as a single
        # (n_steps, batch) array, and lax.scan runs the SAME train_step
        # body per row — identical op order and rng schedule to the loop,
        # so the two paths are numerically interchangeable.
        def epoch_scan(params, opt_state, state, data_dev, idx_mat,
                       epoch_key):
            def body(carry, step):
                p, o, s = carry
                i, idx = step
                batch = tuple(
                    jax.lax.with_sharding_constraint(
                        jnp.take(d, idx, axis=0), self._data)
                    for d in data_dev)
                p, o, s, loss, _ = train_step(
                    p, o, s, batch, jax.random.fold_in(epoch_key, i))
                return (p, o, s), loss

            (params, opt_state, state), losses = jax.lax.scan(
                body, (params, opt_state, state),
                (jnp.arange(idx_mat.shape[0]), idx_mat))
            return params, opt_state, state, losses

        self._epoch_scan = jax.jit(
            epoch_scan,
            donate_argnums=(0, 1, 2),
            in_shardings=(self._repl,) * 6,
            out_shardings=(self._repl,) * 4,
        )
        # int8 weight-only serving (sdk/quant.py): quantize once per
        # params identity host-side; the jitted predict dequantizes
        # in-graph so the int8 copy is the HBM-resident one. Explicit
        # arg wins over the env switch.
        from rafiki_tpu.sdk.quant import serve_int8_enabled

        self.serve_int8 = (serve_int8 if serve_int8 is not None
                           else serve_int8_enabled())
        self._qcache: Tuple[Any, Any] = (None, None)  # (params_ref, qparams)
        if predict_fn is not None:
            serving_fn = predict_fn
            if self.serve_int8:
                from rafiki_tpu.sdk.quant import dequantize_pytree

                def serving_fn(qp, *rest, _fn=predict_fn):
                    return _fn(dequantize_pytree(qp), *rest)

            self._predict = jax.jit(
                serving_fn,
                in_shardings=(self._repl,) * (1 + n_state) + (self._data,),
                out_shardings=self._data,
            )

    # -- helpers ----------------------------------------------------------

    def round_batch(self, batch_size: int) -> int:
        """Round a batch size up to a multiple of the data-axis size."""
        r = -(-batch_size // self.n_data)
        return r * self.n_data

    # Smallest predict bucket: padding 1 query to 8 wastes negligible
    # compute, while halving the number of distinct compiled shapes.
    MIN_PREDICT_BUCKET = 8

    def predict_buckets(self, cap: int) -> list:
        """The fixed ladder of compiled predict batch shapes: powers of two
        from MIN_PREDICT_BUCKET up to ``cap`` (each rounded to a multiple of
        the data-axis size) — at most log2(cap) executables ever exist, no
        matter what batch sizes arrive at serving time."""
        buckets = []
        b = self.MIN_PREDICT_BUCKET
        while b < cap:
            buckets.append(self.round_batch(b))
            b *= 2
        buckets.append(self.round_batch(cap))
        # rounding can collapse adjacent powers of two on wide meshes
        return sorted(set(buckets))

    def _bucket_for(self, n: int, cap: int) -> int:
        for b in self.predict_buckets(cap):
            if b >= n:
                return b
        return self.predict_buckets(cap)[-1]

    def device_put_params(self, params: Any) -> Any:
        return jax.device_put(params, self._repl)

    def init(self, init_fn: Callable[[jax.Array], Any], seed: int = 0,
             hyperparams: Optional[Dict[str, float]] = None):
        """Initialize (params, opt_state[, state]), replicated over the
        mesh (state only for stateful trainers, whose ``init_fn`` returns
        ``(params, state)``).

        ``hyperparams`` overrides injected optimizer values (see
        `tunable_optimizer`) — how a cached trainer gets this trial's lr."""
        out = init_fn(jax.random.key(seed))
        state = None
        if self.stateful:
            params, state = out
            state = jax.device_put(state, self._repl)
        else:
            params = out
        params = self.device_put_params(params)
        opt_state = self.optimizer.init(params)
        if hyperparams:
            opt_state = set_opt_hyperparams(opt_state, hyperparams)
        opt_state = jax.device_put(opt_state, self._repl)
        if self.stateful:
            return params, opt_state, state
        return params, opt_state

    # -- training ---------------------------------------------------------

    def fit(
        self,
        params: Any,
        opt_state: Any,
        data: Tuple[np.ndarray, ...],
        epochs: int,
        batch_size: int,
        seed: int = 0,
        log: Optional[Callable[..., None]] = None,
        checkpoint_path: Optional[str] = None,
        checkpoint_every_epochs: int = 1,
        state: Any = None,
        scan_epoch: Optional[bool] = None,
    ):
        """Run the epoch loop over in-memory arrays. Returns
        ``(params, opt_state)``, or ``(params, opt_state, state)`` for
        stateful trainers (pass the initial ``state=`` in).

        ``data`` is a tuple of arrays with equal leading dim; each step gets
        the corresponding tuple slice as ``batch``.

        Mid-trial checkpointing (an upgrade over the reference, whose only
        persistence was the end-of-trial params pickle — a killed trial
        restarted from scratch, reference worker/train.py:122-132): with
        ``checkpoint_path`` set, (params, opt_state, epoch) are written
        atomically every ``checkpoint_every_epochs``, and a fit() that finds
        the file resumes from the saved epoch. The rng schedule is a pure
        function of (seed, epoch), so a resumed run takes exactly the steps
        the uninterrupted run would have.

        ``scan_epoch`` selects the device-resident epoch scan (one dispatch
        per epoch; see ``epoch_scan`` in ``__init__``). Default ``None`` =
        auto: on when the dataset fits the replication budget
        (``RAFIKI_SCAN_EPOCH_MAX_BYTES``, 256 MB; ``RAFIKI_SCAN_EPOCH`` =
        on/off/auto overrides). Both paths produce the same result.
        """
        n = len(data[0])
        # Largest multiple of the data-axis size that fits in the dataset;
        # if the dataset is smaller than the mesh, resample with replacement
        # up to one full device batch so fit() always takes >= 1 step/epoch.
        fit_cap = (n // self.n_data) * self.n_data
        batch_size = min(self.round_batch(batch_size), fit_cap or self.n_data)
        start_epoch = 0
        if checkpoint_path and os.path.exists(checkpoint_path):
            try:
                params, opt_state, state, start_epoch = (
                    self._restore_checkpoint(
                        checkpoint_path, params, opt_state, state))
                logger.info("resuming fit from %s at epoch %d",
                            checkpoint_path, start_epoch)
            except Exception:
                # corrupt/unreadable checkpoint (failed checksum, torn
                # legacy file): warn and train from scratch — losing the
                # saved epochs beats crashing the whole trial over a
                # damaged cache of them
                logger.warning(
                    "checkpoint %s is corrupt or unreadable; restarting "
                    "the trial from scratch", checkpoint_path,
                    exc_info=True)
                start_epoch = 0
        if scan_epoch is None:
            env = os.environ.get("RAFIKI_SCAN_EPOCH", "auto").lower()
            if env in ("0", "off", "false"):
                scan_epoch = False
            elif env in ("1", "on", "true"):
                scan_epoch = True
            else:
                scan_epoch = (sum(int(d.nbytes) for d in data)
                              <= _SCAN_EPOCH_MAX_BYTES)
        data_dev = None  # uploaded lazily: a resume at epoch==epochs skips it
        # Cross-fit device cache: HPO trials of one job call fit() with the
        # SAME host arrays (dataset_utils memoizes loads), and this trainer
        # object persists across trials (cached_trainer) — re-uploading
        # ~100 MB through a remote-chip tunnel per trial is the single
        # biggest remaining per-trial cost. Keyed by array identity; the
        # cached entry holds the host arrays too, so ids cannot be reused
        # while the key is alive. One entry (one job, one dataset).
        cache_key = tuple(id(d) for d in data)
        cached = getattr(self, "_fit_data_cache", None)
        if cached is not None and cached[0] == cache_key:
            data_dev = cached[2]
        elif cached is not None:
            # different dataset: drop the stale entry NOW so its device
            # replication frees before the new upload (and doesn't leak if
            # this fit takes the non-scan path)
            self._fit_data_cache = None
        base_key = jax.random.key(seed + 1)
        for epoch in range(start_epoch, epochs):
            t0 = time.time()
            epoch_rng = np.random.default_rng([seed, epoch])
            epoch_key = jax.random.fold_in(base_key, epoch)
            if fit_cap == 0:
                batches: Any = [epoch_rng.choice(n, self.n_data)]
            else:
                batches = shuffled_batches(n, batch_size, epoch_rng)
            if scan_epoch:
                if data_dev is None:
                    data_dev = tuple(
                        jax.device_put(np.asarray(d), self._repl)
                        for d in data)
                    self._fit_data_cache = (cache_key, tuple(data), data_dev)
                idx_mat = jnp.asarray(np.stack(list(batches)), jnp.int32)
                params, opt_state, state, losses = self._epoch_scan(
                    params, opt_state, state, data_dev, idx_mat, epoch_key)
            else:
                losses = []
                for i, idx in enumerate(batches):
                    batch = tuple(
                        jax.device_put(d[idx], self._data) for d in data)
                    step_rng = jax.random.fold_in(epoch_key, i)
                    params, opt_state, state, loss, _ = self._train_step(
                        params, opt_state, state, batch, step_rng)
                    losses.append(loss)
                losses = jnp.stack(losses) if losses else jnp.zeros((0,))
            stop_early = False
            if len(losses) and log is not None:
                try:
                    log(loss=float(jnp.mean(losses)), epoch=float(epoch),
                        epoch_time=time.time() - t0)
                except StopTrialEarly:
                    # scheduler verdict (ASHA): this trial is not
                    # competitive — stop training here and return what it
                    # learned; the caller evaluates and completes normally
                    logger.info("early stop after epoch %d", epoch)
                    stop_early = True
            if checkpoint_path and (
                    (epoch + 1) % max(checkpoint_every_epochs, 1) == 0
                    or epoch + 1 == epochs or stop_early):
                self._save_checkpoint(checkpoint_path, params, opt_state,
                                      epoch + 1, state)
            if stop_early:
                break
        if self.stateful:
            return params, opt_state, state
        return params, opt_state

    @staticmethod
    def _save_checkpoint(path: str, params: Any, opt_state: Any,
                         next_epoch: int, state: Any = None) -> None:
        from flax import serialization

        from rafiki_tpu.sdk.params import _to_host

        # to_bytes state-dict-ifies optax's tuple/NamedTuple states (raw
        # msgpack cannot pack tuples); from_bytes restores into the live
        # structures
        blob = serialization.to_bytes({
            "params": _to_host(params),
            "opt_state": _to_host(opt_state),
            "state": _to_host(state) if state is not None else {},
            "epoch": next_epoch,
        })
        from rafiki_tpu.sdk.artifact import write_artifact

        # atomic (tmp + fsync + rename) AND checksummed: a resumed fit
        # must be able to TELL a bit-rotten checkpoint from a valid one
        # and fall back to a fresh start instead of crashing the trial
        write_artifact(path, blob)

    def _restore_checkpoint(self, path: str, params: Any, opt_state: Any,
                            state: Any = None) -> Tuple[Any, Any, Any, int]:
        """Restore into the shapes of freshly-initialized (params,
        opt_state[, state]) — flax's from-target restore keeps optax's
        NamedTuple state structure intact.

        Restored param shapes are verified against the fit's own target
        BEFORE anything reaches the device: flax takes the blob's array
        shapes at face value, so a checkpoint written under a different
        program — a population-stacked (K, ...) checkpoint left behind by
        a crashed vmapped batch whose lead trial is now re-run scalar, or
        an architecture-knob change — would otherwise restore "cleanly"
        and die later as a cryptic shape error inside the jitted step
        (classified USER, terminally erroring a perfectly good trial). A
        mismatch is typed artifact corruption: fit()'s restore guard logs
        it and starts fresh, the standard corrupt-checkpoint contract."""
        from rafiki_tpu.sdk.artifact import ArtifactCorruptError

        restored = restore_checkpoint_host(path, params, opt_state, state)
        got = [np.shape(x) for x in jax.tree.leaves(restored["params"])]
        want = [np.shape(x) for x in jax.tree.leaves(params)]
        if got != want:
            raise ArtifactCorruptError(
                path,
                f"checkpoint param shapes {got[:4]}{'…' if len(got) > 4 else ''} "
                f"do not match this trial's {want[:4]}"
                f"{'…' if len(want) > 4 else ''} — written under a different "
                f"program (population-stacked, or different architecture "
                f"knobs); treating as corrupt (fresh start)")
        params = self.device_put_params(restored["params"])
        opt_state = jax.device_put(restored["opt_state"], self._repl)
        if state is not None:
            state = jax.device_put(restored["state"], self._repl)
        return params, opt_state, state, int(restored["epoch"])

    # -- inference --------------------------------------------------------

    def _serving_params(self, params: Any) -> Any:
        """The params actually fed to the jitted predict: the int8 copy
        when serve_int8 is on (quantized once per params object — the
        cache holds the source pytree so CPython id reuse can't alias a
        different trial's weights)."""
        if not self.serve_int8:
            return params
        src, qp = self._qcache
        if src is not params:
            from rafiki_tpu.sdk.quant import quantize_pytree

            qp = jax.device_put(quantize_pytree(params), self._repl)
            self._qcache = (params, qp)
        return qp

    def _run_predict(self, params: Any, chunk: np.ndarray,
                     state: Any) -> jax.Array:
        params = self._serving_params(params)
        dev = jax.device_put(chunk, self._data)
        if self.stateful:
            return self._predict(params, state, dev)
        return self._predict(params, dev)

    def predict_batched(
        self, params: Any, x: np.ndarray, batch_size: int = 256,
        state: Any = None,
    ) -> np.ndarray:
        """Run ``predict_fn`` over `x` in power-of-two padded buckets.

        Serving batch sizes vary with load (the continuous batcher drains
        1..cap queries per tick); compiling a shape per distinct size would
        recompile mid-traffic and blow the tail latency. Instead every chunk
        is padded up to the fixed bucket ladder (`predict_buckets`), so the
        set of compiled shapes is small, static, and warmable at deploy.
        """
        assert self.predict_fn is not None, "no predict_fn configured"
        outs = []
        for chunk, pad in self._bucket_chunks(x, batch_size):
            out = np.asarray(self._run_predict(params, chunk, state))
            outs.append(out[: len(out) - pad] if pad else out)
        return np.concatenate(outs) if outs else np.zeros((0,))

    def warm_predict(self, params: Any, example: np.ndarray,
                     batch_size: int = 256, state: Any = None) -> int:
        """Compile every predict bucket up front by running ``predict_fn``
        on copies of ``example`` (one query's worth of input) at each bucket
        size. Called at serving deploy so no real request ever pays a
        compile. Returns the number of buckets warmed."""
        assert self.predict_fn is not None, "no predict_fn configured"
        return self._warm_buckets(
            lambda chunk: self._run_predict(params, chunk, state),
            example, batch_size)

    # -- fused ensemble serving -------------------------------------------

    def _stacked_jit(self):
        """The vmapped predict executable for fused-ensemble serving:
        ``(stacked_params, x) -> (n_models, batch, ...)`` — every co-served
        model answers the batch in ONE device dispatch instead of one
        dispatch per trial (SURVEY §7 "ensembles across trials on one chip
        set"). Runs under the trainer's mesh shardings — params replicated,
        batch over the data axis — so CHIPS_PER_WORKER grants shard the
        fused dispatch exactly like the single-model predict. int8 serving
        composes: each model is quantized individually (see
        ``stack_ensemble_params``) and dequantized in-graph per vmap
        instance."""
        jitted = getattr(self, "_predict_stacked", None)
        if jitted is None:
            assert self.predict_fn is not None, "no predict_fn configured"
            assert not self.stateful, (
                "fused ensemble serving supports stateless predict only")
            serving_fn = self.predict_fn
            if self.serve_int8:
                from rafiki_tpu.sdk.quant import dequantize_pytree

                def serving_fn(qp, x, _fn=self.predict_fn):
                    return _fn(dequantize_pytree(qp), x)

            jitted = self._predict_stacked = jax.jit(
                jax.vmap(serving_fn, in_axes=(0, None)),
                in_shardings=(self._repl, self._data),
                out_shardings=NamedSharding(self.mesh, P(None, DATA_AXIS)),
            )
        return jitted

    def stack_ensemble_params(self, params_list: list) -> Any:
        """Stack N models' param trees along a new leading axis and place
        them on the serving devices — the co-resident ensemble's HBM
        layout. Under int8 serving each model's tree is quantized
        INDIVIDUALLY first (its own per-channel scales, its own
        small-leaf pass-through gates — identical numerics to its solo
        int8 serving) and the q/scale leaves are then stacked."""
        if self.serve_int8:
            from rafiki_tpu.sdk.quant import is_quantized_leaf, quantize_pytree

            qlist = [quantize_pytree(p) for p in params_list]

            def stack_leaf(*xs):
                if is_quantized_leaf(xs[0]):
                    return {
                        "q": np.stack([np.asarray(x["q"]) for x in xs]),
                        "scale": np.stack(
                            [np.asarray(x["scale"]) for x in xs]),
                    }
                return np.stack([np.asarray(x) for x in xs])

            stacked = jax.tree.map(stack_leaf, *qlist,
                                   is_leaf=is_quantized_leaf)
        else:
            stacked = jax.tree.map(
                lambda *xs: np.stack([np.asarray(x) for x in xs]),
                *params_list)
        return jax.device_put(stacked, self._repl)

    def _bucket_chunks(self, x: np.ndarray, batch_size: int):
        """Shared bucket walk for the predict paths: yields
        ``(padded_chunk, pad)`` per bucket on the fixed ladder (the single
        home of the pad-with-repeat rule — the stacked and single-model
        paths must never drift)."""
        n = len(x)
        cap = self.round_batch(max(batch_size, 1))
        i = 0
        while i < n:
            chunk = x[i: i + cap]
            bucket = self._bucket_for(len(chunk), cap)
            pad = bucket - len(chunk)
            if pad:
                chunk = np.concatenate(
                    [chunk, np.repeat(chunk[-1:], pad, axis=0)])
            yield chunk, pad
            i += bucket - pad

    def predict_batched_stacked(
        self, stacked_params: Any, x: np.ndarray, batch_size: int = 256,
    ) -> np.ndarray:
        """``predict_batched`` for a fused ensemble: returns
        ``(n_models, len(x), ...)`` predictions, one vmapped dispatch per
        padded bucket (same bucket ladder/compile-count guarantees)."""
        jitted = self._stacked_jit()
        outs = []
        for chunk, pad in self._bucket_chunks(x, batch_size):
            out = np.asarray(jitted(stacked_params, chunk))
            outs.append(out[:, : out.shape[1] - pad] if pad else out)
        if not outs:
            n_models = np.shape(jax.tree.leaves(stacked_params)[0])[0]
            return np.zeros((n_models, 0))
        return np.concatenate(outs, axis=1)

    def warm_predict_stacked(self, stacked_params: Any, example: np.ndarray,
                             batch_size: int = 256) -> int:
        """``warm_predict`` for the fused-ensemble path."""
        jitted = self._stacked_jit()
        return self._warm_buckets(
            lambda chunk: jitted(stacked_params, chunk), example, batch_size)

    def _warm_buckets(self, run, example: np.ndarray,
                      batch_size: int) -> int:
        """Shared deploy-time bucket warm-up: run ``run(chunk)`` once per
        ladder rung so no real request ever pays an XLA compile."""
        example = np.asarray(example)
        cap = self.round_batch(max(batch_size, 1))
        buckets = self.predict_buckets(cap)
        for b in buckets:
            chunk = np.broadcast_to(example[None], (b,) + example.shape)
            run(np.ascontiguousarray(chunk))
        return len(buckets)


def trainer_ensemble_stack(models: list, example: np.ndarray,
                           to_predictions=None, to_batch=None):
    """Generic ``BaseModel.ensemble_stack`` implementation for SDK-trainer
    templates: fuse ``models`` (each with ``_trainer`` / ``_params``
    attributes, the full co-served group) into one vmapped predict over
    stacked params, or return None when they cannot share a compiled
    predict. ``example`` is one query's worth of input for deploy warm-up;
    ``to_predictions(out_row) -> list`` converts one model's raw output
    batch (default: ``.tolist()`` per row); ``to_batch(queries) ->
    np.ndarray`` converts raw queries into the predict batch (default:
    ``np.asarray(queries, np.float32)`` — text templates pass their
    tokenizer here, see JaxBert). Templates opt in with::

        def ensemble_stack(self, models):
            return trainer_ensemble_stack(
                models, np.zeros(self._example_shape, np.float32))

    Fusion requires every model to hold the SAME trainer instance (the
    ``cached_trainer`` bucket — same template, same architecture knobs)
    and identically-shaped param trees."""
    from rafiki_tpu import config as rconfig

    first = models[0]
    trainer = getattr(first, "_trainer", None)
    if trainer is None or getattr(first, "_params", None) is None:
        return None
    # enforce the contract here, not as a deploy-time assert in the worker:
    # a stateful trainer (batch norm) or one without a predict_fn cannot
    # share a vmapped compiled predict — fall back to sequential serving
    if trainer.stateful or trainer.predict_fn is None:
        return None
    for m in models:
        if getattr(m, "_trainer", None) is not trainer:
            return None
    params_list = [m._params for m in models]
    struct0 = jax.tree.structure(params_list[0])
    shapes0 = [np.shape(x) for x in jax.tree.leaves(params_list[0])]
    for p in params_list[1:]:
        if (jax.tree.structure(p) != struct0
                or [np.shape(x) for x in jax.tree.leaves(p)] != shapes0):
            return None
    stacked = trainer.stack_ensemble_params(params_list)
    # the stacked copy is now the HBM-resident ensemble; keeping every
    # model's own device tree alive too would double the footprint of
    # exactly the worker whose point is co-residency — move the per-model
    # params to host (the sequential fallback never runs once fusion
    # succeeded; plain predict would just re-upload)
    for m in models:
        m._params = jax.tree.map(np.asarray, m._params)
    example = np.asarray(example)
    convert = to_predictions or (lambda out: [row.tolist() for row in out])
    batchify = to_batch or (
        lambda queries: np.asarray(queries, dtype=np.float32))

    class _Fused:
        n_models = len(models)

        @staticmethod
        def predict_all(queries):
            x = batchify(queries)
            out = trainer.predict_batched_stacked(
                stacked, x, batch_size=rconfig.PREDICT_MAX_BATCH_SIZE)
            return [convert(per_model) for per_model in out]

        @staticmethod
        def warm_up():
            trainer.warm_predict_stacked(
                stacked, example,
                batch_size=rconfig.PREDICT_MAX_BATCH_SIZE)

    return _Fused()


def softmax_classifier_loss(apply_fn: Callable[..., jax.Array]) -> LossFn:
    """Standard cross-entropy loss for an ``apply_fn(params, x) -> logits``
    classifier; batch = (x, labels)."""

    def loss_fn(params, batch, rng):
        x, y = batch
        logits = apply_fn(params, x)
        loss = optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()
        acc = (jnp.argmax(logits, -1) == y).mean()
        return loss, {"acc": acc}

    return loss_fn


def classification_accuracy(
    trainer: DataParallelTrainer, params: Any, x: np.ndarray, y: np.ndarray
) -> float:
    logits = trainer.predict_batched(params, x)
    return float((np.argmax(logits, -1) == np.asarray(y)).mean())
