"""JAX/XLA training backend for model templates.

This is the seam the whole rebuild pivots on: where the reference's model
templates each hand-rolled a TF1 session loop on whatever GPU the container
saw (e.g. reference examples/models/image_classification/TfFeedForward.py:55-67),
models here describe *pure functions* — ``init_fn(rng) -> params`` and
``loss_fn(params, batch, rng) -> (loss, aux)`` — and the framework:

- jits one fused train step (forward + backward + optimizer) with donated
  buffers, so weights never leave HBM between steps;
- shards the batch over the mesh's ``data`` axis and replicates params; XLA
  inserts the gradient ``psum`` over ICI (the TPU-native replacement for the
  reference's only collective, ``tf.contrib.nccl.all_sum`` at
  pg_gans.py:1165-1170);
- keeps shapes static (remainder batches are dropped in training and padded +
  masked in eval) so the step compiles once per (model, static-knob) bucket.
"""

from __future__ import annotations

import time
from functools import partial
from typing import Any, Callable, Dict, Iterator, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from rafiki_tpu.parallel.mesh import DATA_AXIS, get_default_mesh

LossFn = Callable[[Any, Any, jax.Array], Tuple[jax.Array, Dict[str, jax.Array]]]


def shuffled_batches(
    n: int, batch_size: int, rng: np.random.Generator, drop_remainder: bool = True
) -> Iterator[np.ndarray]:
    """Yield shuffled index batches of a fixed size (static shapes for XLA)."""
    perm = rng.permutation(n)
    n_full = n // batch_size
    for i in range(n_full):
        yield perm[i * batch_size : (i + 1) * batch_size]
    if not drop_remainder and n % batch_size:
        yield perm[n_full * batch_size :]


class DataParallelTrainer:
    """Data-parallel trainer over a device mesh.

    Parameters are replicated; batches are sharded on the ``data`` axis.
    Works identically on one chip (mesh of 1) and a v5e-8 slice — only the
    mesh changes, which the placement layer provides.
    """

    def __init__(
        self,
        loss_fn: LossFn,
        optimizer: optax.GradientTransformation,
        predict_fn: Optional[Callable[[Any, Any], jax.Array]] = None,
        mesh: Optional[Mesh] = None,
    ):
        self.mesh = mesh or get_default_mesh()
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.predict_fn = predict_fn
        self._repl = NamedSharding(self.mesh, P())
        self._data = NamedSharding(self.mesh, P(DATA_AXIS))
        self.n_data = self.mesh.shape[DATA_AXIS]

        def train_step(params, opt_state, batch, rng):
            (loss, aux), grads = jax.value_and_grad(self.loss_fn, has_aux=True)(
                params, batch, rng
            )
            updates, opt_state = self.optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss, aux

        self._train_step = jax.jit(
            train_step,
            donate_argnums=(0, 1),
            in_shardings=(self._repl, self._repl, self._data, self._repl),
            out_shardings=(self._repl, self._repl, self._repl, self._repl),
        )
        if predict_fn is not None:
            self._predict = jax.jit(
                predict_fn,
                in_shardings=(self._repl, self._data),
                out_shardings=self._data,
            )

    # -- helpers ----------------------------------------------------------

    def round_batch(self, batch_size: int) -> int:
        """Round a batch size up to a multiple of the data-axis size."""
        r = -(-batch_size // self.n_data)
        return r * self.n_data

    def device_put_params(self, params: Any) -> Any:
        return jax.device_put(params, self._repl)

    def init(self, init_fn: Callable[[jax.Array], Any], seed: int = 0) -> Tuple[Any, Any]:
        """Initialize (params, opt_state), replicated over the mesh."""
        params = init_fn(jax.random.key(seed))
        params = self.device_put_params(params)
        opt_state = jax.device_put(self.optimizer.init(params), self._repl)
        return params, opt_state

    # -- training ---------------------------------------------------------

    def fit(
        self,
        params: Any,
        opt_state: Any,
        data: Tuple[np.ndarray, ...],
        epochs: int,
        batch_size: int,
        seed: int = 0,
        log: Optional[Callable[..., None]] = None,
    ) -> Tuple[Any, Any]:
        """Run the epoch loop over in-memory arrays.

        ``data`` is a tuple of arrays with equal leading dim; each step gets
        the corresponding tuple slice as ``batch``.
        """
        n = len(data[0])
        # Largest multiple of the data-axis size that fits in the dataset;
        # if the dataset is smaller than the mesh, resample with replacement
        # up to one full device batch so fit() always takes >= 1 step/epoch.
        fit_cap = (n // self.n_data) * self.n_data
        batch_size = min(self.round_batch(batch_size), fit_cap or self.n_data)
        host_rng = np.random.default_rng(seed)
        step_key = jax.random.key(seed + 1)
        step = 0
        for epoch in range(epochs):
            t0 = time.time()
            losses = []
            if fit_cap == 0:
                batches: Any = [host_rng.choice(n, self.n_data)]
            else:
                batches = shuffled_batches(n, batch_size, host_rng)
            for idx in batches:
                batch = tuple(jax.device_put(d[idx], self._data) for d in data)
                step_key, sub = jax.random.split(step_key)
                params, opt_state, loss, _ = self._train_step(
                    params, opt_state, batch, sub
                )
                losses.append(loss)
                step += 1
            if losses and log is not None:
                mean_loss = float(jnp.mean(jnp.stack(losses)))
                log(loss=mean_loss, epoch=float(epoch), epoch_time=time.time() - t0)
        return params, opt_state

    # -- inference --------------------------------------------------------

    def predict_batched(
        self, params: Any, x: np.ndarray, batch_size: int = 256
    ) -> np.ndarray:
        """Run ``predict_fn`` over `x` in fixed-size padded batches (static
        shapes; at most log2 distinct compiled sizes)."""
        assert self.predict_fn is not None, "no predict_fn configured"
        n = len(x)
        batch_size = self.round_batch(min(batch_size, max(n, 1)))
        outs = []
        i = 0
        while i < n:
            chunk = x[i : i + batch_size]
            pad = batch_size - len(chunk)
            if pad:
                chunk = np.concatenate([chunk, np.repeat(chunk[-1:], pad, axis=0)])
            out = self._predict(params, jax.device_put(chunk, self._data))
            out = np.asarray(out)
            outs.append(out[: len(out) - pad] if pad else out)
            i += batch_size
        return np.concatenate(outs) if outs else np.zeros((0,))


def softmax_classifier_loss(apply_fn: Callable[..., jax.Array]) -> LossFn:
    """Standard cross-entropy loss for an ``apply_fn(params, x) -> logits``
    classifier; batch = (x, labels)."""

    def loss_fn(params, batch, rng):
        x, y = batch
        logits = apply_fn(params, x)
        loss = optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()
        acc = (jnp.argmax(logits, -1) == y).mean()
        return loss, {"acc": acc}

    return loss_fn


def classification_accuracy(
    trainer: DataParallelTrainer, params: Any, x: np.ndarray, y: np.ndarray
) -> float:
    logits = trainer.predict_batched(params, x)
    return float((np.argmax(logits, -1) == np.asarray(y)).mean())
