"""Best-effort OS sandbox for untrusted model-template code.

The reference got isolation for free: every trial executor was a Docker
container with only its own volume mounts
(/root/reference/dockerfiles/worker.Dockerfile:1-31,
rafiki/container/docker_swarm.py:128-148). A process-native TPU stack
needs its own story — SURVEY.md §7 lists it as a hard part. This module
runs the untrusted slice of a trial (model import, train, evaluate,
dump_parameters) in a restricted CHILD process; everything trusted —
store access, advisor coordination, params persistence, budget
accounting — stays in the worker (worker/train.py), which talks to the
child over a line-framed pipe protocol.

Threat model (documented, not absolute):

- PROTECTED against an uploaded template that tries to (a) read OR
  write other trials' jails (params, mid-trial checkpoints — each jail
  is 0700 and owned by its own per-trial uid), (b) read/modify the
  metadata store (SQLite file), (c) see admin credentials / agent keys /
  store paths in its environment, (d) exhaust fds or address space,
  (e) scribble outside its jail cwd via relative paths, or (f) read
  group-root files (0640 root:root) — the credential drop clears
  supplementary groups and drops gid too (``os.setgroups([])`` +
  ``setgid``), unlike r4's gid-0-retained design.
  Mechanisms: scrubbed environment (allowlist), cwd jailed to a
  per-trial directory, RLIMIT_NOFILE/RLIMIT_AS/RLIMIT_CORE,
  PR_SET_NO_NEW_PRIVS, and — when the worker runs as root (the TPU-VM
  deployment default) — a drop to a PER-TRIAL uid (hashed from the jail
  name into [RAFIKI_SANDBOX_UID_BASE, +RAFIKI_SANDBOX_UID_RANGE); set
  RAFIKI_SANDBOX_UID_RANGE=0 for the r4-style single
  ``RAFIKI_SANDBOX_UID``) and to gid ``RAFIKI_SANDBOX_GID`` (default
  65534; ``RAFIKI_SANDBOX_KEEP_GID0=1`` restores gid 0 for deployments
  whose TPU device nodes are group-0 gated). Owner-only files (params
  dir 0700, DB 0600 — enforced by db/database.py and worker/train.py)
  and sibling jails are unreadable; world-readable code (repo, venv,
  stdlib) still imports — the grants the parent makes to ensure that
  (directory-traversal bits along the repo/dataset paths) are logged.
- NOT protected BY DEFAULT: network access — the child shares the host
  network namespace because the TPU tunnel itself needs sockets, so a
  hostile template can dial loopback control-plane ports (which is why
  the admin REST requires JWTs and agents require keys even from
  localhost). ``RAFIKI_SANDBOX_NETNS=1`` closes this for CPU-only
  trials by unsharing the network namespace (child keeps a down
  loopback, no reachability at all). Also not bounded: CPU time
  (trials legitimately train for hours; TRIAL_TIMEOUT_S covers
  runaways via the stop protocol). Uid-drop isolation is unavailable
  when the worker itself runs unprivileged — then only the env scrub +
  cwd jail + rlimits apply. Full containment still calls for VMs/gVisor
  at the fleet boundary.

Protocol (child = python -m rafiki_tpu.sdk.sandbox_child):

- parent -> child stdin: one setup JSON line, then optionally ``STOP\\n``
  (the mid-trial stop verdict — TRIAL_TIMEOUT_S / TIME_HOURS / ASHA);
- child -> parent stdout, one JSON frame per line:
    {"t": "log",  "line": <ModelLogger serialized record>}
    {"t": "done", "score": float, "params_b64": str}
    {"t": "err",  "error": str, "traceback": str}
  METRICS log frames double as the parent's stop-check decision points,
  exactly like the in-process logger wiring they replace.

Serving runs under the same flag: inference workers host the uploaded
template in a persistent serve-mode child (``SandboxedModelServer``) that
answers one predict frame per batch — the trusted worker keeps the params
file, store, and data plane (worker/inference.py).

Enable with ``RAFIKI_SANDBOX=1`` (worker/train.py checks per trial).
"""

from __future__ import annotations

import base64
import json
import logging
import os
import signal
import stat
import subprocess
import sys
import threading
from typing import Any, Callable, Dict, Optional, Tuple

logger = logging.getLogger(__name__)

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# env vars the child KEEPS (everything else is scrubbed). Compute needs
# the JAX/XLA/TPU-tunnel configuration; PATH/TMP for the interpreter.
ENV_ALLOWLIST_PREFIXES = (
    "JAX_", "XLA_", "TPU_", "PALLAS_", "LIBTPU_", "PJRT_", "AXON_",
    "PYTHON", "LC_", "LANG",
)
ENV_ALLOWLIST = ("PATH", "TMPDIR", "TZ", "RAFIKI_CHIP_GRANT",
                 "RAFIKI_COMPILE_CACHE_DIR",
                 # serving-numerics switch (sdk/quant.py) — config, not a
                 # secret; the sandboxed trainer must see the same value
                 # the in-process path would
                 "RAFIKI_SERVE_INT8")


class SandboxError(Exception):
    """The sandboxed trial failed (model error, limit hit, or protocol
    breakdown); carries the child-side traceback when there is one.

    Subclasses carry a ``kind`` from the trial fault taxonomy
    (worker/faults.py — plain strings here so the sdk layer stays
    import-free of the worker layer): the worker's retry/quarantine
    machinery branches on it instead of parsing messages."""

    kind = "INFRA"


class SandboxInfraError(SandboxError):
    """The platform failed the child: spawn failure, protocol breakdown,
    killed by an unexplained signal. Retryable (same trial id)."""

    kind = "INFRA"


class SandboxMemError(SandboxError):
    """The child breached its memory envelope: RLIMIT_AS MemoryError
    from model code, or SIGKILL while RAFIKI_SANDBOX_MEM_MB was
    active (kernel/OOM enforcement)."""

    kind = "MEM"


class SandboxUserError(SandboxError):
    """Model code raised (an ``err`` frame from sandbox_child with
    where=model). Terminal: the knobs are infeasible, not the infra."""

    kind = "USER"


class SandboxStallError(SandboxError):
    """The child went mute before its first frame for
    RAFIKI_TRIAL_STALL_S and the no-frame watchdog killed its process
    group (wedged import, dead TPU tunnel). Retryable."""

    kind = "STALL"


class SandboxTimeoutError(SandboxError):
    """The trial blew through its TRIAL_TIMEOUT_S budget and ignored
    the STOP verdict (a mute runaway); the watchdog terminated it."""

    kind = "TIMEOUT"


def stall_deadline_s() -> float:
    """RAFIKI_TRIAL_STALL_S: how long a sandbox child may produce NO
    frame at all before the watchdog kills it (0 disables). Armed only
    until the first frame — once the template has spoken, mid-training
    silence is legitimate (an epoch can take longer than any sane stall
    deadline) and TRIAL_TIMEOUT_S owns runaways."""
    return float(os.environ.get("RAFIKI_TRIAL_STALL_S", "600"))


def sandbox_enabled() -> bool:
    return os.environ.get("RAFIKI_SANDBOX") == "1"


def sandbox_uid() -> Optional[int]:
    """The fixed fallback uid (RAFIKI_SANDBOX_UID_RANGE=0 mode), or None
    when the worker is unprivileged (no drop possible — the remaining
    layers still apply). Per-jail uids come from :func:`uid_for_jail`."""
    if os.geteuid() != 0:
        return None
    return int(os.environ.get("RAFIKI_SANDBOX_UID", "65534"))


def _uid_range() -> Tuple[int, int]:
    base = int(os.environ.get("RAFIKI_SANDBOX_UID_BASE", "210000"))
    rng = int(os.environ.get("RAFIKI_SANDBOX_UID_RANGE", "4096"))
    return base, rng


def _hashed_uid(ident: str, base: int, rng: int) -> int:
    """THE uid-hash: make_jail's collision probe reserves this value for
    still-root-owned sibling jails, so it must stay byte-identical with
    what uid_for_jail computes — one copy only."""
    import zlib

    return base + (zlib.crc32(ident.encode()) % rng)


def uid_for_jail(jail_dir: str) -> Optional[int]:
    """Uid the child in this jail drops to. STICKY: once make_jail has
    chowned the jail, its owner IS the answer (so a resumed trial maps
    to the uid that wrote its mid-trial checkpoint even across a
    base/range reconfiguration — and collision probing stays stable).
    For a jail that doesn't exist yet, the basename (trial id / serve
    id) hashes into [RAFIKI_SANDBOX_UID_BASE, +RAFIKI_SANDBOX_UID_RANGE)
    — make_jail then probes that choice against live sibling jails.
    Distinct uids + 0700 jails are what isolate concurrent trials from
    EACH OTHER (advisor r4 finding: a shared uid let one trial corrupt a
    sibling's checkpoint). Range 0 restores the single shared
    RAFIKI_SANDBOX_UID. None when the worker is unprivileged."""
    if os.geteuid() != 0:
        return None
    base, rng = _uid_range()
    if rng <= 0:
        return sandbox_uid()
    try:
        owner = os.stat(jail_dir).st_uid
        if base <= owner < base + rng:
            return owner
    except OSError:
        pass
    return _hashed_uid(os.path.basename(os.path.abspath(jail_dir)),
                       base, rng)


def sandbox_gid() -> int:
    """Gid the child drops to. Default 65534 (nogroup); gid 0 only via
    the explicit RAFIKI_SANDBOX_KEEP_GID0=1 escape hatch (TPU device
    nodes gated on group 0 in some deployments)."""
    if os.environ.get("RAFIKI_SANDBOX_KEEP_GID0") == "1":
        return 0
    return int(os.environ.get("RAFIKI_SANDBOX_GID", "65534"))


def _child_env(jail_dir: str) -> Dict[str, str]:
    env = {
        k: v for k, v in os.environ.items()
        if k in ENV_ALLOWLIST or k.startswith(ENV_ALLOWLIST_PREFIXES)
    }
    env["HOME"] = jail_dir
    env["TMPDIR"] = jail_dir
    env["PYTHONPATH"] = _REPO_ROOT
    return env


def _ensure_traversal(path: str, read: bool = False) -> None:
    """Give the dropped child directory-traversal (execute) bits on
    ``path`` and every ancestor this process may widen — group AND
    other x, since the child may run with gid 0 (KEEP_GID0 mode) or an
    anonymous gid. ``read=True`` additionally grants read on ``path``
    itself (package roots need listing for import; ancestors never do).

    An unprivileged worker never touches files it doesn't own; a ROOT
    worker (the only case where uid drops — and therefore traversal
    grants — matter at all) additionally widens non-owned directories,
    but with the *execute bit only*, never read: a repo checkout under
    e.g. a /root whose directory is owned by some provisioning uid
    would otherwise make EVERY sandboxed trial fail at import with a
    spawn-class fault, while an x-only grant exposes nothing listable —
    reaching a file still requires knowing its path and passing its own
    mode bits. On a multi-user host where even that is unacceptable
    (an o+x'd home directory persists after the worker exits),
    ``RAFIKI_SANDBOX_WIDEN_NONOWNED=0`` restores the strict owner-only
    rule — the operator then pre-grants traversal along the repo path
    themselves. Every widening is LOGGED (advisor r4: these are
    system-visible side effects — e.g. /root gains o+x so the jailed
    uid can reach /root/repo — and operators must be able to see
    them)."""
    travers = stat.S_IXGRP | stat.S_IXOTH
    p = os.path.abspath(path)
    want = travers | (stat.S_IRGRP | stat.S_IROTH if read else 0)
    is_root = (os.geteuid() == 0 and os.environ.get(
        "RAFIKI_SANDBOX_WIDEN_NONOWNED", "1") != "0")
    while True:
        try:
            st = os.stat(p)
            owned = st.st_uid == os.getuid()
            # non-owned dirs (root only): traversal x, never read bits
            eff = want if owned else (want & travers if is_root else 0)
            if eff and (st.st_mode & eff) != eff:
                os.chmod(p, st.st_mode | eff)
                logger.info(
                    "sandbox: widened %s %o -> %o (traversal grant for "
                    "jailed uids)", p, stat.S_IMODE(st.st_mode),
                    stat.S_IMODE(st.st_mode | eff))
        except OSError:
            pass
        parent = os.path.dirname(p)
        if parent == p:
            return
        p = parent
        want = travers  # ancestors get x only, never read


def grant_dataset_access(uri: str) -> None:
    """Local-file dataset URIs must be readable by the jailed uid: add
    group+other read on the file and traversal on its ancestors (no-ops
    for http(s) URIs and files we don't own)."""
    path = uri[7:] if uri.startswith("file://") else uri
    if not os.path.isabs(path) or not os.path.exists(path):
        return
    _ensure_traversal(os.path.dirname(path))
    try:
        st = os.stat(path)
        want = stat.S_IRGRP | stat.S_IROTH
        if st.st_uid == os.getuid() and (st.st_mode & want) != want:
            os.chmod(path, st.st_mode | want)
            logger.info("sandbox: widened dataset %s %o -> %o", path,
                        stat.S_IMODE(st.st_mode),
                        stat.S_IMODE(st.st_mode | want))
    except OSError:
        pass


def jail_path(base_dir: str, trial_id: str) -> str:
    """THE definition of where a trial's jail lives — cleanup code
    (worker/train.py _cleanup_ckpt) resolves through this too."""
    return os.path.join(base_dir, "jail", trial_id)


def make_jail(base_dir: str, trial_id: str) -> str:
    """Per-trial jail cwd: 0700 and owned by THIS trial's uid (when the
    worker is root), so sibling trials — distinct uids, no shared
    group — can neither read nor corrupt its mid-trial checkpoints.
    Stable across worker restarts (an existing jail keeps its owner uid,
    see uid_for_jail) so checkpoints resume; a fresh jail's hashed uid
    is linear-probed against every sibling jail's owner so two LIVE
    trials can never silently share a uid (review r5: crc32 % 4096
    collides with ~50% odds by ~75 jails)."""
    jail = jail_path(base_dir, trial_id)
    existed = os.path.isdir(jail)
    os.makedirs(jail, exist_ok=True)
    uid = uid_for_jail(jail)
    if uid is not None:
        base, rng = _uid_range()
        sticky = False
        if existed and rng > 0:
            try:
                owner = os.stat(jail).st_uid
                sticky = base <= owner < base + rng
            except OSError:
                pass
        if rng > 0 and not sticky:
            # Serialize (probe + chown) across worker processes sharing
            # this WORKDIR: without the flock, two jails hashing to the
            # same uid could both probe before either chown lands and
            # silently share a uid (review r5 TOCTOU). A sibling that is
            # still root-owned inside the lock is a creator WAITING on
            # this lock — reserve the uid its name hashes to.
            import fcntl

            parent = os.path.dirname(jail)
            # 0600 — the lock lives in a tree jailed children can
            # traverse, and flock works on a read-only fd: a hostile
            # template holding it would wedge all future jail creation
            lock_fd = os.open(os.path.join(parent, ".uidlock"),
                              os.O_WRONLY | os.O_CREAT, 0o600)
            lockf = os.fdopen(lock_fd, "w")
            try:
                os.fchmod(lock_fd, 0o600)  # pre-existing wider file
                fcntl.flock(lockf, fcntl.LOCK_EX)
                taken = set()
                for name in os.listdir(parent):
                    p = os.path.join(parent, name)
                    if p == jail or not os.path.isdir(p):
                        continue
                    try:
                        owner = os.stat(p).st_uid
                    except OSError:
                        continue
                    if base <= owner < base + rng:
                        taken.add(owner)
                    else:
                        taken.add(_hashed_uid(name, base, rng))
                for _ in range(rng):
                    if uid not in taken:
                        break
                    uid = base + ((uid - base + 1) % rng)
                else:
                    logger.warning(
                        "sandbox: uid range exhausted (%d jails in a "
                        "range of %d) — jail %s SHARES uid %d with a "
                        "live sibling; raise RAFIKI_SANDBOX_UID_RANGE",
                        len(taken), rng, jail, uid)
                os.chown(jail, uid, sandbox_gid())
            finally:
                lockf.close()  # releases the flock
        else:
            os.chown(jail, uid, sandbox_gid())
        # a pre-existing jail may hold files owned under an earlier
        # uid scheme (r4's shared 65534, or a base/range edit): rechown
        # them or the resumed child can't read its own checkpoint
        for root, dirs, files in os.walk(jail):
            for name in dirs + files:
                p = os.path.join(root, name)
                try:
                    if os.lstat(p).st_uid != uid:
                        os.lchown(p, uid, sandbox_gid())
                except OSError:
                    pass
    os.chmod(jail, 0o700)
    _ensure_traversal(os.path.dirname(jail))
    return jail


def _base_setup(jail_dir: str) -> Dict[str, Any]:
    """Isolation policy shared by trial and serve children — ONE place to
    add a new rlimit or env knob."""
    return {
        "jail_dir": jail_dir,
        "drop_uid": uid_for_jail(jail_dir),
        "drop_gid": sandbox_gid(),
        "netns": os.environ.get("RAFIKI_SANDBOX_NETNS") == "1",
        "nofile": int(os.environ.get("RAFIKI_SANDBOX_NOFILE", "1024")),
        "mem_mb": int(os.environ.get("RAFIKI_SANDBOX_MEM_MB", "0")),
    }


def _spawn_child(jail_dir: str, extra_pythonpath: Optional[str]):
    """Launch a sandbox child with the shared env policy and a bounded
    concurrent stderr drain (an undrained pipe deadlocks a chatty child;
    the tail is the only diagnostic when a child dies frameless).
    Returns (proc, stderr_chunks, drain_thread)."""
    env = _child_env(jail_dir)
    if extra_pythonpath:
        # per-model dependency prefix (sdk/deps.py) — pins shadow base
        env["PYTHONPATH"] = (
            extra_pythonpath + os.pathsep + env["PYTHONPATH"])
        _ensure_traversal(extra_pythonpath, read=True)
    # the dropped uid must still import this package — grant traversal
    # along the repo path (e.g. /root is 0700 by default) and listing on
    # the package root itself (import's FileFinder lists it)
    _ensure_traversal(_REPO_ROOT, read=True)
    # start_new_session: the child leads its OWN process group, so a
    # kill (stall/timeout watchdog, teardown) reaches every process the
    # template forked — a daemonized grandchild must not outlive its
    # trial holding a chip grant. The cost is that a SIGKILLed worker no
    # longer takes the child down via shared process group; the
    # explicit teardown paths (finally blocks here, placement destroy)
    # and the jail's resource limits bound that window.
    proc = subprocess.Popen(
        [sys.executable, "-m", "rafiki_tpu.sdk.sandbox_child"],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True,
        env=env, cwd=jail_dir, start_new_session=True,
    )
    stderr_chunks: list = []

    def _drain_stderr() -> None:
        try:
            for line in proc.stderr:
                stderr_chunks.append(line)
                if len(stderr_chunks) > 500:
                    del stderr_chunks[:250]
        except (OSError, ValueError):
            pass

    drain = threading.Thread(target=_drain_stderr, daemon=True)
    drain.start()
    return proc, stderr_chunks, drain


def _signal_group(proc, sig: int) -> None:
    """Deliver ``sig`` to the child's whole process group (it leads its
    own session — see _spawn_child), falling back to the process itself
    when the group is already gone or unsignalable."""
    try:
        os.killpg(proc.pid, sig)
        return
    except (ProcessLookupError, PermissionError, OSError):
        pass
    try:
        proc.send_signal(sig)
    except (ProcessLookupError, OSError):
        pass


def _reap_child_group(proc, grace_s: float = 10.0) -> None:
    """Teardown contract: TERM the group, wait, KILL the group, and
    sweep the group once more after the direct child is reaped so a
    forked grandchild can't outlive the trial."""
    if proc.poll() is None:
        _signal_group(proc, signal.SIGTERM)
        try:
            proc.wait(timeout=grace_s)
        except subprocess.TimeoutExpired:
            _signal_group(proc, signal.SIGKILL)
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                pass
    # final sweep: the group may still hold the template's forked
    # grandchildren even though the leader is reaped
    try:
        os.killpg(proc.pid, signal.SIGKILL)
    except (ProcessLookupError, PermissionError, OSError):
        pass


def run_trial_sandboxed(
    model_bytes: bytes,
    model_class: str,
    knobs: Dict[str, Any],
    train_uri: str,
    test_uri: str,
    jail_dir: str,
    on_log_line: Callable[[str], None],
    stop_check: Optional[Callable[[Dict[str, float]], bool]] = None,
    timeout_s: Optional[float] = None,
    extra_pythonpath: Optional[str] = None,
) -> Tuple[float, bytes]:
    """Run one trial's untrusted slice in the sandbox child.

    Forwards every child log line to ``on_log_line`` (the worker's
    trial-log sink); runs ``stop_check`` on each METRICS record and sends
    the STOP verdict down the pipe when it fires — the child's logger
    then raises StopTrialEarly at its next log call, the same contract
    as the in-process wiring. Returns (score, params_bytes)."""
    setup = {
        **_base_setup(jail_dir),
        "model_b64": base64.b64encode(model_bytes).decode(),
        "model_class": model_class,
        "knobs": knobs,
        "train_uri": train_uri,
        "test_uri": test_uri,
    }
    for uri in (train_uri, test_uri):
        grant_dataset_access(uri)
    proc, stderr_chunks, stderr_thread = _spawn_child(
        jail_dir, extra_pythonpath)
    stop_sent = threading.Event()

    def send_stop() -> None:
        if stop_sent.is_set():
            return
        stop_sent.set()
        try:
            proc.stdin.write("STOP\n")
            proc.stdin.flush()
        except (BrokenPipeError, OSError, ValueError):
            pass

    result: Dict[str, Any] = {}
    rc: Optional[int] = None
    first_frame = threading.Event()
    stalled = threading.Event()
    timed_out = threading.Event()
    # Runaway guard the in-process path can't have: a template that never
    # logs cannot be stopped at a METRICS decision point, so past the
    # trial deadline the child gets a STOP (in case it logs soon), then a
    # grace period, then SIGTERM to its whole group — and, one more
    # grace period later, SIGKILL: an untrusted template may install
    # SIG_IGN for SIGTERM, and without the hard escalation the parent
    # would block on child frames forever, the exact hang class the
    # watchdogs exist to eliminate. The frame loop below unblocks on
    # EOF and the exit is classified TIMEOUT.
    watchdogs = []

    def _timeout_kill(sig: int) -> None:
        timed_out.set()
        _signal_group(proc, sig)

    if timeout_s:
        watchdogs = [
            threading.Timer(timeout_s, send_stop),
            threading.Timer(timeout_s + 60.0, _timeout_kill,
                            args=(signal.SIGTERM,)),
            threading.Timer(timeout_s + 120.0, _timeout_kill,
                            args=(signal.SIGKILL,)),
        ]
        for w in watchdogs:
            w.daemon = True
            w.start()

    # Stall watchdog (RAFIKI_TRIAL_STALL_S): without it the parent
    # blocks on child frames INDEFINITELY when the child goes mute
    # before its first line — a wedged import or dead TPU tunnel held
    # the executor forever. Armed only until the first frame arrives;
    # a template that has spoken is governed by TRIAL_TIMEOUT_S.
    stall_s = stall_deadline_s()

    def _stall_monitor() -> None:
        if first_frame.wait(timeout=stall_s):
            return
        if proc.poll() is None and not result:
            stalled.set()
            logger.warning(
                "sandbox child produced no frame within %.0fs "
                "(RAFIKI_TRIAL_STALL_S); killing its process group",
                stall_s)
            _signal_group(proc, signal.SIGKILL)

    if stall_s > 0:
        threading.Thread(target=_stall_monitor, daemon=True).start()
    try:
        try:
            proc.stdin.write(json.dumps(setup) + "\n")
            proc.stdin.flush()
        except (BrokenPipeError, OSError) as e:
            # spawn/interpreter-init failure: the child died before it
            # could read its setup line — the platform's fault
            raise SandboxInfraError(
                f"sandbox child died before setup ({e!r})")
        for raw in proc.stdout:
            first_frame.set()
            try:
                frame = json.loads(raw)
            except json.JSONDecodeError:
                frame = None
            if (not isinstance(frame, dict)
                    or frame.get("t") not in ("log", "done", "err")):
                # stray output that slipped past the child's stdout
                # redirection (defense in depth — including valid-JSON
                # prints and unknown-t dicts): surface it as a log line
                on_log_line(json.dumps({
                    "type": "MESSAGE", "message": raw.rstrip("\n"),
                    "time": __import__("time").time()}))
                continue
            t = frame.get("t")
            if t == "log":
                line = frame.get("line", "")
                on_log_line(line)
                if stop_check is not None and not stop_sent.is_set():
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        rec = {}
                    if rec.get("type") == "METRICS" and stop_check(
                            rec.get("metrics") or {}):
                        send_stop()
            elif t in ("done", "err"):
                result = frame
                break
        try:
            rc = proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            # a model thread the template didn't join can keep the child
            # interpreter alive past the done frame — with the result in
            # hand that is the CHILD's problem, not the trial's (the
            # finally kills it); without a result it stays a failure
            rc = None
    finally:
        for w in watchdogs:
            w.cancel()
        first_frame.set()  # disarm the stall monitor on every exit path
        # the untrusted child is NOT abandoned on teardown (unlike
        # backend-probe children, it can hold a chip grant) — and its
        # whole process group goes with it, so forked grandchildren
        # can't outlive the trial
        _reap_child_group(proc)
        for s in (proc.stdin, proc.stdout, proc.stderr):
            try:
                s.close()
            except OSError:
                pass
        stderr_thread.join(timeout=5)
    if result.get("t") == "done":
        return float(result["score"]), base64.b64decode(result["params_b64"])
    if result.get("t") == "err":
        detail = (f"{result.get('error')}\n--- child traceback ---\n"
                  f"{result.get('traceback', '')}")
        # the child says WHO failed: model code (where=model, default
        # for old children) vs the harness itself (e.g. lockdown)
        if result.get("where", "model") != "model":
            raise SandboxInfraError(detail)
        if result.get("error_type") == "MemoryError":
            # RLIMIT_AS enforcement surfaces as MemoryError inside the
            # template — the memory envelope, not the template's logic
            raise SandboxMemError(detail)
        raise SandboxUserError(detail)
    # frameless death: classify HOW the child died (exit code vs
    # signal, which watchdog fired) instead of a generic string
    stderr_tail = "".join(stderr_chunks)[-2000:]
    if stalled.is_set():
        raise SandboxStallError(
            f"sandbox child produced no frame within "
            f"{stall_s:.0f}s (RAFIKI_TRIAL_STALL_S) and was killed; "
            f"stderr tail:\n{stderr_tail}")
    if timed_out.is_set():
        raise SandboxTimeoutError(
            f"trial exceeded its {timeout_s:.0f}s budget "
            f"(TRIAL_TIMEOUT_S) and ignored the STOP verdict; child "
            f"killed; stderr tail:\n{stderr_tail}")
    if rc is not None and rc < 0:
        try:
            signame = signal.Signals(-rc).name
        except ValueError:
            signame = f"signal {-rc}"
        if -rc == signal.SIGKILL and int(setup.get("mem_mb") or 0) > 0:
            # SIGKILL under an active memory cap is the kernel/OOM
            # enforcement path (rss breach that never surfaced as a
            # python MemoryError)
            raise SandboxMemError(
                f"sandbox child SIGKILLed with RAFIKI_SANDBOX_MEM_MB="
                f"{setup['mem_mb']} active (rss breach); stderr tail:\n"
                f"{stderr_tail}")
        raise SandboxInfraError(
            f"sandbox child killed by {signame} without a result "
            f"frame; stderr tail:\n{stderr_tail}")
    raise SandboxInfraError(
        f"sandbox child exited rc={rc} without a result frame; "
        f"stderr tail:\n{stderr_tail}")


class SandboxedModelServer:
    """Serving-side sandbox: the uploaded template answers predict batches
    from a locked-down child (same isolation policy as the trial path),
    while the trusted inference worker keeps the store, the params file,
    and the data plane. One JSON frame per batch over the pipe — the same
    wire cost the shm broker already pays per batch, so the added latency
    is encode/decode, not an extra scheduling hop. Serialized per worker:
    one batch in flight, exactly like the in-process serve loop."""

    def __init__(self, model_bytes: bytes, model_class: str,
                 knobs: Dict[str, Any], params_bytes: bytes,
                 jail_dir: str, extra_pythonpath: Optional[str] = None,
                 ready_timeout_s: float = 600.0):
        from rafiki_tpu.utils.jsonutil import dumps

        self._jail_dir = jail_dir
        self._lock = threading.Lock()
        self._proc, self._stderr_chunks, self._stderr_thread = _spawn_child(
            jail_dir, extra_pythonpath)
        # frames arrive through a reader thread + queue so every wait is a
        # REAL timeout — a silently hung child can never block the worker
        # in readline() past its deadline
        import queue as _queue

        self._frames: "_queue.Queue" = _queue.Queue()

        def _read_stdout() -> None:
            try:
                for raw in self._proc.stdout:
                    try:
                        frame = json.loads(raw)
                    except json.JSONDecodeError:
                        continue  # stray print from model code
                    if (not isinstance(frame, dict)
                            or frame.get("t") not in (
                                "ready", "preds", "err", "log")):
                        # JSON-looking print (42, [..], {"step":1}, or a
                        # dict with an unknown "t"): NOT a protocol
                        # frame — enqueuing it would pair stale answers
                        # with later queries
                        continue
                    if frame["t"] != "log":
                        self._frames.put(frame)
            except (OSError, ValueError):
                pass
            finally:
                self._frames.put(None)  # EOF sentinel, on every exit path

        self._reader = threading.Thread(target=_read_stdout, daemon=True)
        self._reader.start()
        setup = {
            **_base_setup(jail_dir),
            "mode": "serve",
            "model_b64": base64.b64encode(model_bytes).decode(),
            "model_class": model_class,
            "knobs": knobs,
            "params_b64": base64.b64encode(params_bytes).decode(),
        }
        try:
            self._proc.stdin.write(dumps(setup) + "\n")
            self._proc.stdin.flush()
        except (BrokenPipeError, OSError) as e:
            # child died before reading stdin (e.g. broken deps prefix
            # crashes interpreter init): reap it, THEN read the tail —
            # close() joins the drain thread, so the diagnostic is
            # complete rather than racing the reader
            self.close()
            tail = "".join(self._stderr_chunks)[-2000:]
            raise SandboxError(
                f"sandbox serve child died before setup ({e!r}); "
                f"stderr tail:\n{tail}")
        frame = self._next_frame(timeout_s=ready_timeout_s)
        if frame.get("t") != "ready":
            err = frame.get("error", "no ready frame")
            self.close()  # joins the stderr drain: tail is complete below
            tail = "".join(self._stderr_chunks)[-2000:]
            raise SandboxError(f"sandboxed model failed to start: {err}\n"
                               f"{frame.get('traceback', '')}\n"
                               f"stderr tail:\n{tail}")

    def _next_frame(self, timeout_s: float) -> Dict[str, Any]:
        import queue as _queue

        try:
            frame = self._frames.get(timeout=timeout_s)
        except _queue.Empty:
            return {"t": "err", "timeout": True,
                    "error": f"no frame within {timeout_s:.0f}s"}
        if frame is None:
            return {"t": "err", "error": "sandbox child exited "
                    f"(rc={self._proc.poll()})"}
        return frame

    @property
    def dead(self) -> bool:
        """True once the child can no longer serve. The worker loop exits
        on this (worker/inference.py) so placement restarts the service —
        unlike a transient model error, a dead child never recovers."""
        return self._proc.poll() is not None

    def warm_up(self) -> None:
        """No-op: the child warmed up before its ready frame — this keeps
        the object duck-compatible with a model in the worker serve loop."""

    def predict(self, queries: list) -> list:
        from rafiki_tpu import config as _config
        from rafiki_tpu.utils.jsonutil import dumps

        with self._lock:
            if self.dead:
                raise SandboxError(
                    f"sandboxed model is gone (rc={self._proc.returncode})")
            try:
                self._proc.stdin.write(dumps(
                    {"op": "predict", "queries": queries}) + "\n")
                self._proc.stdin.flush()
            except (BrokenPipeError, OSError, ValueError) as e:
                raise SandboxError(f"sandboxed model pipe broken: {e}")
            frame = self._next_frame(
                timeout_s=_config.PREDICT_TIMEOUT_S + 60.0)
            if frame.get("timeout"):
                # the in-flight answer would desynchronize every later
                # batch (stale preds for fresh queries) — a timed-out
                # child is killed AND reaped here, so `dead` is already
                # True when the worker's error handler checks it
                self._proc.kill()
                try:
                    self._proc.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    pass
                raise SandboxError(
                    f"sandboxed predict timed out; child killed: "
                    f"{frame.get('error')}")
        if frame.get("t") == "preds":
            return list(frame["predictions"])
        raise SandboxError(
            f"sandboxed predict failed: {frame.get('error')}\n"
            f"{frame.get('traceback', '')}")

    def destroy(self) -> None:
        self.close()

    def close(self) -> None:
        try:
            self._proc.stdin.write(json.dumps({"op": "exit"}) + "\n")
            self._proc.stdin.flush()
        except (BrokenPipeError, OSError, ValueError):
            pass
        try:
            self._proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            pass
        # group teardown (incl. the post-reap sweep): a template that
        # forked inside the serve child must not keep answering — or
        # holding chips — after its service stops
        _reap_child_group(self._proc, grace_s=5.0)
        for s in (self._proc.stdin, self._proc.stdout, self._proc.stderr):
            try:
                s.close()
            except OSError:
                pass
        self._stderr_thread.join(timeout=5)
        # serving jails hold no resumable state (unlike trial jails)
        import shutil

        shutil.rmtree(self._jail_dir, ignore_errors=True)
