"""Best-effort OS sandbox for untrusted model-template code.

The reference got isolation for free: every trial executor was a Docker
container with only its own volume mounts
(/root/reference/dockerfiles/worker.Dockerfile:1-31,
rafiki/container/docker_swarm.py:128-148). A process-native TPU stack
needs its own story — SURVEY.md §7 lists it as a hard part. This module
runs the untrusted slice of a trial (model import, train, evaluate,
dump_parameters) in a restricted CHILD process; everything trusted —
store access, advisor coordination, params persistence, budget
accounting — stays in the worker (worker/train.py), which talks to the
child over a line-framed pipe protocol.

Threat model (documented, not absolute):

- PROTECTED against an uploaded template that tries to (a) read other
  trials' params or mid-trial checkpoints, (b) read/modify the metadata
  store (SQLite file), (c) see admin credentials / agent keys / store
  paths in its environment, (d) exhaust fds or address space, or
  (e) scribble outside its jail cwd via relative paths.
  Mechanisms: scrubbed environment (allowlist), cwd jailed to a
  per-trial directory, RLIMIT_NOFILE/RLIMIT_AS/RLIMIT_CORE, and — when
  the worker runs as root (the TPU-VM deployment default) — a uid drop
  to ``RAFIKI_SANDBOX_UID`` (default 65534) with gid 0 retained, so
  owner-only files (params dir 0700, DB 0600 — enforced by
  db/database.py and worker/train.py) are unreadable while group
  -readable code (repo, venv) still imports.
- NOT protected: network access (the child may dial out — the TPU
  tunnel itself needs sockets), CPU time by default (trials legitimately
  train for hours; TRIAL_TIMEOUT_S covers runaways via the stop
  protocol), and uid-drop isolation is unavailable when the worker
  itself runs unprivileged — then only the env scrub + cwd jail +
  rlimits apply. Full containment still calls for VMs/gVisor at the
  fleet boundary.

Protocol (child = python -m rafiki_tpu.sdk.sandbox_child):

- parent -> child stdin: one setup JSON line, then optionally ``STOP\\n``
  (the mid-trial stop verdict — TRIAL_TIMEOUT_S / TIME_HOURS / ASHA);
- child -> parent stdout, one JSON frame per line:
    {"t": "log",  "line": <ModelLogger serialized record>}
    {"t": "done", "score": float, "params_b64": str}
    {"t": "err",  "error": str, "traceback": str}
  METRICS log frames double as the parent's stop-check decision points,
  exactly like the in-process logger wiring they replace.

Serving runs under the same flag: inference workers host the uploaded
template in a persistent serve-mode child (``SandboxedModelServer``) that
answers one predict frame per batch — the trusted worker keeps the params
file, store, and data plane (worker/inference.py).

Enable with ``RAFIKI_SANDBOX=1`` (worker/train.py checks per trial).
"""

from __future__ import annotations

import base64
import json
import logging
import os
import stat
import subprocess
import sys
import threading
from typing import Any, Callable, Dict, Optional, Tuple

logger = logging.getLogger(__name__)

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# env vars the child KEEPS (everything else is scrubbed). Compute needs
# the JAX/XLA/TPU-tunnel configuration; PATH/TMP for the interpreter.
ENV_ALLOWLIST_PREFIXES = (
    "JAX_", "XLA_", "TPU_", "PALLAS_", "LIBTPU_", "PJRT_", "AXON_",
    "PYTHON", "LC_", "LANG",
)
ENV_ALLOWLIST = ("PATH", "TMPDIR", "TZ", "RAFIKI_CHIP_GRANT",
                 "RAFIKI_COMPILE_CACHE_DIR",
                 # serving-numerics switch (sdk/quant.py) — config, not a
                 # secret; the sandboxed trainer must see the same value
                 # the in-process path would
                 "RAFIKI_SERVE_INT8")


class SandboxError(Exception):
    """The sandboxed trial failed (model error, limit hit, or protocol
    breakdown); carries the child-side traceback when there is one."""


def sandbox_enabled() -> bool:
    return os.environ.get("RAFIKI_SANDBOX") == "1"


def sandbox_uid() -> Optional[int]:
    """Uid to drop to, or None when the worker is unprivileged (no drop
    possible — the remaining layers still apply)."""
    if os.geteuid() != 0:
        return None
    return int(os.environ.get("RAFIKI_SANDBOX_UID", "65534"))


def _child_env(jail_dir: str) -> Dict[str, str]:
    env = {
        k: v for k, v in os.environ.items()
        if k in ENV_ALLOWLIST or k.startswith(ENV_ALLOWLIST_PREFIXES)
    }
    env["HOME"] = jail_dir
    env["TMPDIR"] = jail_dir
    env["PYTHONPATH"] = _REPO_ROOT
    return env


def _ensure_group_traversal(path: str) -> None:
    """Give gid-0 the directory-execute bit on every ancestor this uid
    owns, so the uid-dropped child (gid 0 retained) can reach its jail
    and datasets; never widens beyond group, never touches files we
    don't own."""
    p = os.path.abspath(path)
    while True:
        try:
            st = os.stat(p)
            if st.st_uid == os.getuid() and not st.st_mode & stat.S_IXGRP:
                os.chmod(p, st.st_mode | stat.S_IXGRP | stat.S_IRGRP)
        except OSError:
            pass
        parent = os.path.dirname(p)
        if parent == p:
            return
        p = parent


def grant_dataset_access(uri: str) -> None:
    """Local-file dataset URIs must be readable by the jailed uid: add
    group-read on the file and traversal on its ancestors (no-ops for
    http(s) URIs and files we don't own)."""
    path = uri[7:] if uri.startswith("file://") else uri
    if not os.path.isabs(path) or not os.path.exists(path):
        return
    _ensure_group_traversal(os.path.dirname(path))
    try:
        st = os.stat(path)
        if st.st_uid == os.getuid():
            os.chmod(path, st.st_mode | stat.S_IRGRP)
    except OSError:
        pass


def jail_path(base_dir: str, trial_id: str) -> str:
    """THE definition of where a trial's jail lives — cleanup code
    (worker/train.py _cleanup_ckpt) resolves through this too."""
    return os.path.join(base_dir, "jail", trial_id)


def make_jail(base_dir: str, trial_id: str) -> str:
    """Per-trial jail cwd: group-writable (the dropped uid keeps gid 0),
    stable across worker restarts so mid-trial checkpoints resume."""
    jail = jail_path(base_dir, trial_id)
    os.makedirs(jail, exist_ok=True)
    os.chmod(jail, 0o770)
    _ensure_group_traversal(jail)
    return jail


def _base_setup(jail_dir: str) -> Dict[str, Any]:
    """Isolation policy shared by trial and serve children — ONE place to
    add a new rlimit or env knob."""
    return {
        "jail_dir": jail_dir,
        "drop_uid": sandbox_uid(),
        "nofile": int(os.environ.get("RAFIKI_SANDBOX_NOFILE", "1024")),
        "mem_mb": int(os.environ.get("RAFIKI_SANDBOX_MEM_MB", "0")),
    }


def _spawn_child(jail_dir: str, extra_pythonpath: Optional[str]):
    """Launch a sandbox child with the shared env policy and a bounded
    concurrent stderr drain (an undrained pipe deadlocks a chatty child;
    the tail is the only diagnostic when a child dies frameless).
    Returns (proc, stderr_chunks, drain_thread)."""
    env = _child_env(jail_dir)
    if extra_pythonpath:
        # per-model dependency prefix (sdk/deps.py) — pins shadow base
        env["PYTHONPATH"] = (
            extra_pythonpath + os.pathsep + env["PYTHONPATH"])
        _ensure_group_traversal(extra_pythonpath)
    # the dropped uid (gid 0 kept) must still import this package — give
    # group traversal along the repo path (e.g. /root is 0700 by default)
    _ensure_group_traversal(_REPO_ROOT)
    # NOT start_new_session: the child must die with the worker's process
    # group (a stopped/killed worker may never reach explicit teardown)
    proc = subprocess.Popen(
        [sys.executable, "-m", "rafiki_tpu.sdk.sandbox_child"],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True,
        env=env, cwd=jail_dir,
    )
    stderr_chunks: list = []

    def _drain_stderr() -> None:
        try:
            for line in proc.stderr:
                stderr_chunks.append(line)
                if len(stderr_chunks) > 500:
                    del stderr_chunks[:250]
        except (OSError, ValueError):
            pass

    drain = threading.Thread(target=_drain_stderr, daemon=True)
    drain.start()
    return proc, stderr_chunks, drain


def run_trial_sandboxed(
    model_bytes: bytes,
    model_class: str,
    knobs: Dict[str, Any],
    train_uri: str,
    test_uri: str,
    jail_dir: str,
    on_log_line: Callable[[str], None],
    stop_check: Optional[Callable[[Dict[str, float]], bool]] = None,
    timeout_s: Optional[float] = None,
    extra_pythonpath: Optional[str] = None,
) -> Tuple[float, bytes]:
    """Run one trial's untrusted slice in the sandbox child.

    Forwards every child log line to ``on_log_line`` (the worker's
    trial-log sink); runs ``stop_check`` on each METRICS record and sends
    the STOP verdict down the pipe when it fires — the child's logger
    then raises StopTrialEarly at its next log call, the same contract
    as the in-process wiring. Returns (score, params_bytes)."""
    setup = {
        **_base_setup(jail_dir),
        "model_b64": base64.b64encode(model_bytes).decode(),
        "model_class": model_class,
        "knobs": knobs,
        "train_uri": train_uri,
        "test_uri": test_uri,
    }
    for uri in (train_uri, test_uri):
        grant_dataset_access(uri)
    proc, stderr_chunks, stderr_thread = _spawn_child(
        jail_dir, extra_pythonpath)
    stop_sent = threading.Event()

    def send_stop() -> None:
        if stop_sent.is_set():
            return
        stop_sent.set()
        try:
            proc.stdin.write("STOP\n")
            proc.stdin.flush()
        except (BrokenPipeError, OSError, ValueError):
            pass

    result: Dict[str, Any] = {}
    rc: Optional[int] = None
    # Runaway guard the in-process path can't have: a template that never
    # logs cannot be stopped at a METRICS decision point, so past the
    # trial deadline the child gets a STOP (in case it logs soon), then a
    # grace period, then SIGTERM — the frame loop below unblocks on EOF.
    watchdogs = []
    if timeout_s:
        watchdogs = [threading.Timer(timeout_s, send_stop),
                     threading.Timer(timeout_s + 60.0, proc.terminate)]
        for w in watchdogs:
            w.daemon = True
            w.start()
    try:
        proc.stdin.write(json.dumps(setup) + "\n")
        proc.stdin.flush()
        for raw in proc.stdout:
            try:
                frame = json.loads(raw)
            except json.JSONDecodeError:
                frame = None
            if (not isinstance(frame, dict)
                    or frame.get("t") not in ("log", "done", "err")):
                # stray output that slipped past the child's stdout
                # redirection (defense in depth — including valid-JSON
                # prints and unknown-t dicts): surface it as a log line
                on_log_line(json.dumps({
                    "type": "MESSAGE", "message": raw.rstrip("\n"),
                    "time": __import__("time").time()}))
                continue
            t = frame.get("t")
            if t == "log":
                line = frame.get("line", "")
                on_log_line(line)
                if stop_check is not None and not stop_sent.is_set():
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        rec = {}
                    if rec.get("type") == "METRICS" and stop_check(
                            rec.get("metrics") or {}):
                        send_stop()
            elif t in ("done", "err"):
                result = frame
                break
        try:
            rc = proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            # a model thread the template didn't join can keep the child
            # interpreter alive past the done frame — with the result in
            # hand that is the CHILD's problem, not the trial's (the
            # finally kills it); without a result it stays a failure
            rc = None
    finally:
        for w in watchdogs:
            w.cancel()
        if proc.poll() is None:
            # the untrusted child is NOT abandoned on teardown (unlike
            # backend-probe children, it can hold a chip grant)
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
        for s in (proc.stdin, proc.stdout, proc.stderr):
            try:
                s.close()
            except OSError:
                pass
        stderr_thread.join(timeout=5)
    if result.get("t") == "done":
        return float(result["score"]), base64.b64decode(result["params_b64"])
    if result.get("t") == "err":
        raise SandboxError(
            f"{result.get('error')}\n--- child traceback ---\n"
            f"{result.get('traceback', '')}")
    stderr_tail = "".join(stderr_chunks)[-2000:]
    raise SandboxError(
        f"sandbox child exited rc={rc} without a result frame; "
        f"stderr tail:\n{stderr_tail}")


class SandboxedModelServer:
    """Serving-side sandbox: the uploaded template answers predict batches
    from a locked-down child (same isolation policy as the trial path),
    while the trusted inference worker keeps the store, the params file,
    and the data plane. One JSON frame per batch over the pipe — the same
    wire cost the shm broker already pays per batch, so the added latency
    is encode/decode, not an extra scheduling hop. Serialized per worker:
    one batch in flight, exactly like the in-process serve loop."""

    def __init__(self, model_bytes: bytes, model_class: str,
                 knobs: Dict[str, Any], params_bytes: bytes,
                 jail_dir: str, extra_pythonpath: Optional[str] = None,
                 ready_timeout_s: float = 600.0):
        from rafiki_tpu.utils.jsonutil import dumps

        self._jail_dir = jail_dir
        self._lock = threading.Lock()
        self._proc, self._stderr_chunks, self._stderr_thread = _spawn_child(
            jail_dir, extra_pythonpath)
        # frames arrive through a reader thread + queue so every wait is a
        # REAL timeout — a silently hung child can never block the worker
        # in readline() past its deadline
        import queue as _queue

        self._frames: "_queue.Queue" = _queue.Queue()

        def _read_stdout() -> None:
            try:
                for raw in self._proc.stdout:
                    try:
                        frame = json.loads(raw)
                    except json.JSONDecodeError:
                        continue  # stray print from model code
                    if (not isinstance(frame, dict)
                            or frame.get("t") not in (
                                "ready", "preds", "err", "log")):
                        # JSON-looking print (42, [..], {"step":1}, or a
                        # dict with an unknown "t"): NOT a protocol
                        # frame — enqueuing it would pair stale answers
                        # with later queries
                        continue
                    if frame["t"] != "log":
                        self._frames.put(frame)
            except (OSError, ValueError):
                pass
            finally:
                self._frames.put(None)  # EOF sentinel, on every exit path

        self._reader = threading.Thread(target=_read_stdout, daemon=True)
        self._reader.start()
        setup = {
            **_base_setup(jail_dir),
            "mode": "serve",
            "model_b64": base64.b64encode(model_bytes).decode(),
            "model_class": model_class,
            "knobs": knobs,
            "params_b64": base64.b64encode(params_bytes).decode(),
        }
        try:
            self._proc.stdin.write(dumps(setup) + "\n")
            self._proc.stdin.flush()
        except (BrokenPipeError, OSError) as e:
            # child died before reading stdin (e.g. broken deps prefix
            # crashes interpreter init): reap it, THEN read the tail —
            # close() joins the drain thread, so the diagnostic is
            # complete rather than racing the reader
            self.close()
            tail = "".join(self._stderr_chunks)[-2000:]
            raise SandboxError(
                f"sandbox serve child died before setup ({e!r}); "
                f"stderr tail:\n{tail}")
        frame = self._next_frame(timeout_s=ready_timeout_s)
        if frame.get("t") != "ready":
            err = frame.get("error", "no ready frame")
            self.close()  # joins the stderr drain: tail is complete below
            tail = "".join(self._stderr_chunks)[-2000:]
            raise SandboxError(f"sandboxed model failed to start: {err}\n"
                               f"{frame.get('traceback', '')}\n"
                               f"stderr tail:\n{tail}")

    def _next_frame(self, timeout_s: float) -> Dict[str, Any]:
        import queue as _queue

        try:
            frame = self._frames.get(timeout=timeout_s)
        except _queue.Empty:
            return {"t": "err", "timeout": True,
                    "error": f"no frame within {timeout_s:.0f}s"}
        if frame is None:
            return {"t": "err", "error": "sandbox child exited "
                    f"(rc={self._proc.poll()})"}
        return frame

    @property
    def dead(self) -> bool:
        """True once the child can no longer serve. The worker loop exits
        on this (worker/inference.py) so placement restarts the service —
        unlike a transient model error, a dead child never recovers."""
        return self._proc.poll() is not None

    def warm_up(self) -> None:
        """No-op: the child warmed up before its ready frame — this keeps
        the object duck-compatible with a model in the worker serve loop."""

    def predict(self, queries: list) -> list:
        from rafiki_tpu import config as _config
        from rafiki_tpu.utils.jsonutil import dumps

        with self._lock:
            if self.dead:
                raise SandboxError(
                    f"sandboxed model is gone (rc={self._proc.returncode})")
            try:
                self._proc.stdin.write(dumps(
                    {"op": "predict", "queries": queries}) + "\n")
                self._proc.stdin.flush()
            except (BrokenPipeError, OSError, ValueError) as e:
                raise SandboxError(f"sandboxed model pipe broken: {e}")
            frame = self._next_frame(
                timeout_s=_config.PREDICT_TIMEOUT_S + 60.0)
            if frame.get("timeout"):
                # the in-flight answer would desynchronize every later
                # batch (stale preds for fresh queries) — a timed-out
                # child is killed AND reaped here, so `dead` is already
                # True when the worker's error handler checks it
                self._proc.kill()
                try:
                    self._proc.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    pass
                raise SandboxError(
                    f"sandboxed predict timed out; child killed: "
                    f"{frame.get('error')}")
        if frame.get("t") == "preds":
            return list(frame["predictions"])
        raise SandboxError(
            f"sandboxed predict failed: {frame.get('error')}\n"
            f"{frame.get('traceback', '')}")

    def destroy(self) -> None:
        self.close()

    def close(self) -> None:
        try:
            self._proc.stdin.write(json.dumps({"op": "exit"}) + "\n")
            self._proc.stdin.flush()
        except (BrokenPipeError, OSError, ValueError):
            pass
        try:
            self._proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            self._proc.terminate()
            try:
                self._proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self._proc.kill()
        for s in (self._proc.stdin, self._proc.stdout, self._proc.stderr):
            try:
                s.close()
            except OSError:
                pass
        self._stderr_thread.join(timeout=5)
        # serving jails hold no resumable state (unlike trial jails)
        import shutil

        shutil.rmtree(self._jail_dir, ignore_errors=True)
