"""Model parameter serialization: pytree <-> bytes.

The reference pickles arbitrary ``dump_parameters()`` dicts to a shared volume
(reference rafiki/worker/train.py:177-183) and unpickles them in inference
workers and clients (reference rafiki/worker/inference.py:86-92,
rafiki/client/client.py:487-506). Pickle executes arbitrary code on load and
can't represent device arrays portably, so here parameters are a *pytree* of
numpy/JAX arrays + JSON-able scalars, serialized with msgpack (flax's
serialization extension handles ndarray leaves). Device arrays are pulled to
host numpy on save; models re-shard on load.
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np
from flax import serialization


def _to_host(tree: Any) -> Any:
    """Convert all array leaves to host numpy (device -> host transfer)."""

    def leaf(x):
        if isinstance(x, jax.Array):
            return np.asarray(x)
        return x

    return jax.tree_util.tree_map(leaf, tree)


def dump_params(params: Any) -> bytes:
    """Serialize a parameter pytree to bytes (msgpack)."""
    return serialization.msgpack_serialize(_to_host(params))


def load_params(data: bytes) -> Any:
    """Deserialize bytes back into a parameter pytree of numpy leaves."""
    return serialization.msgpack_restore(data)
