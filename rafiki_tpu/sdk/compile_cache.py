"""Persistent XLA executable cache, managed (docs/failure-model.md
"Cold-start faults").

Every process that compiles — trial workers, inference/generation
workers, the bench — calls :func:`enable` at startup, so compiled
programs survive process death, control-plane recovery, reschedules, and
autoscaler scale-up: a replacement replica's jit programs become a disk
read instead of an XLA compile.

Contract (the artifact-frame contract applied to XLA executables):

- **Keyed per topology.** Entries live under
  ``RAFIKI_COMPILE_CACHE_DIR/<topology key>`` where the key folds in the
  backend, device kind, device count, and the jax/jaxlib versions — an
  executable compiled for one topology or library version is never
  offered to another (the version-mismatch half of the contract; JAX's
  own cache key covers the program itself).
- **Typed degrade, never a crash.** An unusable cache dir (missing,
  unwritable, probe failure) disables the cache for this process and
  records *why* (``stats()["reason"]``, surfaced by the doctor); the
  worker compiles fresh. Corrupt entries are absorbed by JAX's reader
  and recompiled — a damaged cache can cost time, not correctness — and
  the warm-up chokepoint evicts unreadable entries (:func:`evict_entries`)
  because jax never overwrites them in place.
- **Observable.** Cache hits are counted via JAX's monitoring events
  into ``rafiki_compile_cache_hits_total``; the warm-up chokepoint
  (worker/warmup.py) accounts misses and per-program compile seconds.

The CPU backend stays opted out by default (RAFIKI_COMPILE_CACHE_CPU=1
to force): CPU AOT entries are tied to exact machine-feature sets and
can fail to load — or SIGILL — when the features differ between compile
and load. The cache pays off on TPU, where compiles are slow.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Any, Dict, Optional

logger = logging.getLogger(__name__)

#: bump when the layout/meaning of the per-topology subdirs changes —
#: old entries are simply never read again (no in-place migration)
_SCHEMA = 1

_lock = threading.Lock()
#: process-wide cache state (guarded-by _lock): the active dir, or the
#: typed reason it is off
_state: Dict[str, Any] = {"enabled": False, "dir": None, "reason": None}
_listeners_installed = False
#: monotonically-increasing persistent-cache hit count for this process,
#: fed by the JAX monitoring listener (lock-free read: int writes are
#: atomic under the GIL and readers only diff snapshots)
_hit_count = 0


def topology_key() -> str:
    """The cache-partition key: same string <=> executables are
    interchangeable. Folds backend + device kind + device count +
    jax/jaxlib versions, so a TPU v4-8's entries never reach a v5e-4,
    and a jax upgrade starts a fresh partition instead of feeding
    incompatible serializations to the loader."""
    import jax

    backend = jax.default_backend()
    try:
        devs = jax.devices()
        kind = devs[0].device_kind.replace(" ", "_") if devs else "none"
        n = len(devs)
    # lint: absorb(an unprobeable backend still gets a usable — just coarser — partition key)
    except Exception:
        kind, n = "unknown", 0
    try:
        import jaxlib

        jaxlib_ver = getattr(jaxlib, "__version__", "0")
    # lint: absorb(jaxlib ships with jax; a missing version just coarsens the partition key)
    except Exception:  # pragma: no cover
        jaxlib_ver = "0"
    return (f"{backend}-{kind}-n{n}-jax{jax.__version__}"
            f"-jaxlib{jaxlib_ver}-v{_SCHEMA}")


def _install_listeners() -> None:
    """Count persistent-cache hits via JAX's monitoring events (best
    effort: the registration API is private; absence just means the
    warm-up chokepoint falls back to its compile-time heuristic)."""
    global _listeners_installed
    if _listeners_installed:
        return
    _listeners_installed = True
    try:
        from jax._src import monitoring as _mon

        def _on_event(event: str, **kw: Any) -> None:
            if event.endswith("/compilation_cache/cache_hits"):
                global _hit_count
                _hit_count += 1
                from rafiki_tpu.utils.metrics import REGISTRY

                REGISTRY.counter(
                    "rafiki_compile_cache_hits_total",
                    "persistent compile-cache hits in this process",
                ).inc()

        _mon.register_event_listener(_on_event)
    # lint: absorb(hit telemetry is best-effort: without the private listener API the warm heuristic still works)
    except Exception:
        logger.debug("jax monitoring listeners unavailable; compile-cache"
                     " hit counting disabled", exc_info=True)


def hit_count() -> int:
    """Persistent-cache hits recorded in this process so far (0 when the
    listener API is unavailable)."""
    return _hit_count


def events_available() -> bool:
    """Whether the JAX hit-event listener could be installed."""
    try:
        from jax._src import monitoring as _mon  # noqa: F401

        return True
    # lint: absorb(private API probe: unavailable just means the warm heuristic is used)
    except Exception:  # pragma: no cover
        return False


def record_misses(n: int, seconds: float = 0.0) -> None:
    """Account ``n`` compiled-fresh programs (the warm-up chokepoint's
    bookkeeping — JAX's miss event is write-path-conditional, so misses
    are counted where the compile time is actually measured)."""
    if n <= 0:
        return
    from rafiki_tpu.utils.metrics import REGISTRY

    REGISTRY.counter(
        "rafiki_compile_cache_misses_total",
        "programs compiled fresh (persistent-cache misses) in this process",
    ).inc(n)
    if seconds > 0:
        REGISTRY.histogram(
            "rafiki_compile_seconds",
            "wall-clock seconds spent compiling (cache misses) per program",
            buckets=[0.05, 0.25, 1, 5, 15, 60, 300],
        ).observe(seconds)


def enable(cache_dir: Optional[str] = None) -> Optional[str]:
    """Point JAX's persistent compilation cache at the shared,
    topology-keyed directory. Idempotent; returns the active dir, or
    None with a typed reason in ``stats()`` when the cache is off
    (disabled, CPU without the opt-in, or an unusable directory — the
    degrade path: the process compiles fresh, it never crashes)."""
    import jax

    from rafiki_tpu import config

    with _lock:
        if _state["enabled"]:
            return _state["dir"]
        if not config.COMPILE_CACHE:
            _state["reason"] = "disabled (RAFIKI_COMPILE_CACHE=0)"
            return None
        if jax.default_backend() == "cpu" and not config.COMPILE_CACHE_CPU:
            _state["reason"] = ("cpu backend (entries are machine-feature-"
                                "tied; set RAFIKI_COMPILE_CACHE_CPU=1 to "
                                "opt in)")
            return None
        root = (cache_dir or config.COMPILE_CACHE_DIR
                or os.path.join(config.WORKDIR, "xla_cache"))
        path = os.path.join(root, topology_key())
        try:
            os.makedirs(path, exist_ok=True)
            # a write probe up front: an unwritable dir must degrade HERE,
            # typed, not as N absorbed warnings inside XLA later
            probe = os.path.join(path, ".rafiki_probe")
            with open(probe, "w", encoding="utf-8") as f:
                f.write("ok")
            os.unlink(probe)
            jax.config.update("jax_compilation_cache_dir", path)
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              float(config.COMPILE_CACHE_MIN_COMPILE_S))
            _state.update(enabled=True, dir=path, reason=None)
        except Exception as e:
            logger.warning(
                "persistent compile cache unavailable at %s (%s: %s); "
                "compiling fresh", path, type(e).__name__, e)
            _state["reason"] = f"unusable dir {path}: {type(e).__name__}: {e}"
            return None
    _install_listeners()
    logger.info("persistent compile cache at %s", path)
    return path


def stats() -> Dict[str, Any]:
    """{enabled, dir, reason, cache_hits} — the doctor/health view."""
    with _lock:
        return {"enabled": _state["enabled"], "dir": _state["dir"],
                "reason": _state["reason"], "cache_hits": _hit_count}


def active_dir() -> Optional[str]:
    with _lock:
        return _state["dir"] if _state["enabled"] else None


def corrupt_entries() -> int:
    """Garble every cache entry in the active dir (RAFIKI_CHAOS
    site=compile action=corrupt — the deterministic bit-rot drill).
    Returns the number of files damaged; JAX's reader absorbs the
    damage and recompiles fresh."""
    path = active_dir()
    if path is None:
        return 0
    damaged = 0
    for name in os.listdir(path):
        full = os.path.join(path, name)
        if not os.path.isfile(full):
            continue
        try:
            with open(full, "r+b") as f:
                head = bytearray(f.read(64))
                if not head:
                    continue
                f.seek(0)
                f.write(bytes(b ^ 0xFF for b in head))
            damaged += 1
        # lint: absorb(a file the drill cannot damage — racing eviction — just stays intact)
        except OSError:
            continue
    return damaged


def evict_entries(program: str) -> int:
    """Delete one program's on-disk entries (bit-rot self-healing: jax
    warns and recompiles on an unreadable entry but never overwrites
    it, so without eviction a damaged entry would stay cold on EVERY
    later boot). Returns the number of files removed."""
    path = active_dir()
    if path is None:
        return 0
    removed = 0
    for name in os.listdir(path):
        if not name.startswith(program + "-"):
            continue
        try:
            os.unlink(os.path.join(path, name))
            removed += 1
        # lint: absorb(an entry racing eviction just survives until the next read error)
        except OSError:
            continue
    return removed


def reset_for_tests() -> None:
    """Drop the process-level enablement so a test can re-point the
    cache dir. Also resets jax's cache SINGLETON: jax initializes its
    cache object lazily from the configured dir and then keeps it — a
    config update alone would keep serving the previous directory."""
    global _hit_count
    with _lock:
        _state.update(enabled=False, dir=None, reason=None)
        _hit_count = 0
    try:
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()
    # lint: absorb(private API, best effort: without it only same-process dir re-pointing is affected)
    except Exception:
        pass
