"""Dataset utilities for model templates (reference rafiki/model/dataset.py).

Capability parity:
- URI fetch with a local cache (file paths, ``file://``, ``http(s)://``) —
  reference dataset.py:80-120;
- ``CorpusDataset``: zip archive containing ``corpus.tsv`` of tab-separated
  token/tag rows with blank-line sentence boundaries — reference
  dataset.py:140-209 and docs/src/user/datasets.rst;
- ``ImageFilesDataset``: zip archive containing ``images.csv`` (columns
  ``path,class``) plus image files, lazily decoded — reference
  dataset.py:211-268;
- ``resize_as_images`` — reference dataset.py:68.

TPU-first addition: ``NumpyDataset`` (a ``.npz`` of dense arrays) as the fast
path — image datasets decode once to a dense ``float32``/``int32`` array pair
so the training loop feeds the chip from pinned host memory instead of
re-decoding PNGs per epoch.
"""

from __future__ import annotations

import collections
import csv
import hashlib
import io
import os
import shutil
import tempfile
import urllib.request
import zipfile
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np


class InvalidDatasetError(Exception):
    pass


class DatasetUtils:
    """Singleton facade exposed to model code as ``dataset_utils``
    (reference rafiki/model/dataset.py:25)."""

    def __init__(self, cache_dir: Optional[str] = None):
        self._cache_dir = cache_dir or os.path.join(
            tempfile.gettempdir(), "rafiki_tpu_datasets"
        )
        # in-memory array cache for load_image_arrays: successive HPO
        # trials of one job load the SAME dataset — re-parsing the file
        # (and breaking downstream identity-keyed device caches, see
        # DataParallelTrainer.fit) per trial is pure waste. Keyed by
        # (resolved path, mtime, size, image_size); tiny LRU (a worker
        # serves one job: train + test sets).
        self._array_cache: "collections.OrderedDict" = collections.OrderedDict()
        self._array_cache_cap = 4

    def download_dataset_from_uri(self, uri: str) -> str:
        """Resolve a dataset URI to a local file path, downloading through a
        content-addressed cache when remote."""
        if uri.startswith("file://"):
            return uri[len("file://") :]
        if uri.startswith("http://") or uri.startswith("https://"):
            os.makedirs(self._cache_dir, exist_ok=True)
            key = hashlib.sha256(uri.encode()).hexdigest()[:24]
            dest = os.path.join(self._cache_dir, key + os.path.basename(uri))
            if not os.path.exists(dest):
                tmp = dest + ".part"
                with urllib.request.urlopen(uri) as r, open(tmp, "wb") as f:
                    shutil.copyfileobj(r, f)
                os.replace(tmp, dest)
            return dest
        # plain (possibly relative) filesystem path — allowed by the reference
        # loader too (reference dataset.py:113-114)
        if not os.path.exists(uri):
            raise InvalidDatasetError(f"Dataset not found: {uri}")
        return uri

    def load_dataset_of_corpus(self, uri: str) -> "CorpusDataset":
        return CorpusDataset(self.download_dataset_from_uri(uri))

    def load_dataset_of_image_files(
        self, uri: str, image_size: Optional[Tuple[int, int]] = None
    ) -> "ImageFilesDataset":
        return ImageFilesDataset(self.download_dataset_from_uri(uri), image_size)

    def load_image_arrays(
        self, uri: str, image_size: Optional[Tuple[int, int]] = None
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Either dataset format -> (x float32, y int32) dense arrays — the
        branch every image-classification template needs. Cached in memory
        per (file identity, image_size): repeat loads return the SAME
        array objects, which downstream device caches key on. Callers must
        treat the arrays as read-only (templates already do — jit tracing
        would not see an in-place mutation anyway)."""
        path = self.download_dataset_from_uri(uri)
        st = os.stat(path)
        # st_ino catches the atomic write-then-rename pattern even when
        # mtime granularity is coarse; an in-place same-size rewrite within
        # one timestamp tick can still alias — callers that rewrite
        # datasets in place should call invalidate_array_cache()
        key = (path, st.st_mtime_ns, st.st_size, st.st_ino, image_size)
        hit = self._array_cache.get(key)
        if hit is not None:
            self._array_cache.move_to_end(key)
            return hit
        if uri.endswith(".npz"):
            ds = NumpyDataset(path)
            out = (ds.x.astype(np.float32), ds.y.astype(np.int32))
        else:
            img_ds = ImageFilesDataset(path, image_size)
            x, y = img_ds.load_as_arrays()
            out = (x.astype(np.float32), y.astype(np.int32))
        self._array_cache[key] = out
        while len(self._array_cache) > self._array_cache_cap:
            self._array_cache.popitem(last=False)
        return out

    def invalidate_array_cache(self) -> None:
        """Drop the in-memory array cache (needed only after rewriting a
        dataset file in place — atomic replace is detected automatically)."""
        self._array_cache.clear()

    def load_dataset_of_arrays(self, uri: str) -> "NumpyDataset":
        return NumpyDataset(self.download_dataset_from_uri(uri))

    def resize_as_images(
        self, images: Sequence[Any], image_size: Tuple[int, int]
    ) -> np.ndarray:
        """Resize a batch of images (arrays or PIL images) to
        ``image_size=(H, W)``, returning a float32 array in [0, 1] of shape
        (N, H, W, C). (PIL's own convention is (W, H); the conversion is
        handled here so callers stay in array-land.)"""
        from PIL import Image

        out = []
        for img in images:
            if isinstance(img, np.ndarray):
                arr = img
                if arr.dtype != np.uint8:
                    arr = (np.clip(arr, 0.0, 1.0) * 255).astype(np.uint8)
                pil = Image.fromarray(arr.squeeze() if arr.ndim == 3 and arr.shape[-1] == 1 else arr)
            else:
                pil = img
            pil = pil.resize((image_size[1], image_size[0]))
            arr = np.asarray(pil, dtype=np.float32) / 255.0
            if arr.ndim == 2:
                arr = arr[..., None]
            out.append(arr)
        return np.stack(out)


class CorpusDataset:
    """Zip of ``corpus.tsv``: tab-separated token + tag columns, sentences
    separated by blank lines. Exposes (tokens, tags) sentence pairs and the
    tag vocabulary."""

    def __init__(self, path: str):
        self.path = path
        self.sentences: List[Tuple[List[str], List[List[str]]]] = []
        tag_vocab: List[set] = []
        with zipfile.ZipFile(path) as zf:
            if "corpus.tsv" not in zf.namelist():
                raise InvalidDatasetError("corpus zip must contain corpus.tsv")
            with zf.open("corpus.tsv") as f:
                text = io.TextIOWrapper(f, encoding="utf-8")
                tokens: List[str] = []
                tags: List[List[str]] = []
                for line in text:
                    line = line.rstrip("\n")
                    if not line.strip():
                        if tokens:
                            self.sentences.append((tokens, tags))
                            tokens, tags = [], []
                        continue
                    cols = line.split("\t")
                    tokens.append(cols[0])
                    row_tags = cols[1:]
                    tags.append(row_tags)
                    while len(tag_vocab) < len(row_tags):
                        tag_vocab.append(set())
                    for i, t in enumerate(row_tags):
                        tag_vocab[i].add(t)
                if tokens:
                    self.sentences.append((tokens, tags))
        self.tag_num_classes = [len(v) for v in tag_vocab]
        self.tag_vocabs = [sorted(v) for v in tag_vocab]
        self.size = len(self.sentences)
        self.max_len = max((len(t) for t, _ in self.sentences), default=0)

    def __iter__(self) -> Iterator[Tuple[List[str], List[List[str]]]]:
        return iter(self.sentences)

    def __len__(self) -> int:
        return self.size


class ImageFilesDataset:
    """Zip of ``images.csv`` (columns ``path,class``) + image files.

    Iterating yields (PIL image, class) lazily; ``load_as_arrays`` decodes the
    whole dataset once into dense arrays for the TPU input path.
    ``image_size`` is (H, W), matching the (N, H, W, C) array convention.
    """

    def __init__(self, path: str, image_size: Optional[Tuple[int, int]] = None):
        self.path = path
        self._image_size = image_size
        with zipfile.ZipFile(path) as zf:
            if "images.csv" not in zf.namelist():
                raise InvalidDatasetError("image dataset zip must contain images.csv")
            with zf.open("images.csv") as f:
                rows = list(csv.DictReader(io.TextIOWrapper(f, encoding="utf-8")))
        if not rows or "path" not in rows[0] or "class" not in rows[0]:
            raise InvalidDatasetError("images.csv must have columns: path, class")
        self._rows = [(r["path"], int(r["class"])) for r in rows]
        self.classes = sorted({c for _, c in self._rows})
        self.label_num_classes = max(self.classes) + 1 if self.classes else 0
        self.size = len(self._rows)

    def __len__(self) -> int:
        return self.size

    def __iter__(self):
        from PIL import Image

        with zipfile.ZipFile(self.path) as zf:
            for rel, cls in self._rows:
                with zf.open(rel) as f:
                    img = Image.open(io.BytesIO(f.read()))
                    if self._image_size is not None:
                        h, w = self._image_size
                        img = img.resize((w, h))
                    yield img, cls

    def load_as_arrays(
        self, image_size: Optional[Tuple[int, int]] = None
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Decode all images to (N, H, W, C) float32 in [0,1] + int32 labels."""
        size = image_size or self._image_size
        xs: List[np.ndarray] = []
        ys: List[int] = []
        for img, cls in self:
            if size is not None and img.size != (size[1], size[0]):
                img = img.resize((size[1], size[0]))
            arr = np.asarray(img, dtype=np.float32) / 255.0
            if arr.ndim == 2:
                arr = arr[..., None]
            xs.append(arr)
            ys.append(cls)
        return np.stack(xs), np.asarray(ys, dtype=np.int32)


class NumpyDataset:
    """A ``.npz`` with arrays ``x`` and ``y`` — the dense fast path."""

    def __init__(self, path: str):
        with np.load(path) as z:
            if "x" not in z or "y" not in z:
                raise InvalidDatasetError(".npz dataset must contain arrays x and y")
            self.x = z["x"]
            self.y = z["y"]
        if len(self.x) != len(self.y):
            raise InvalidDatasetError("x and y lengths differ")
        self.size = len(self.x)
        self.label_num_classes = int(self.y.max()) + 1 if self.size else 0

    def __len__(self) -> int:
        return self.size


def write_image_files_dataset(
    images: np.ndarray, labels: np.ndarray, out_path: str
) -> str:
    """Helper to build an IMAGE_FILES zip from dense arrays (the inverse of
    ImageFilesDataset; analogue of the reference's dataset converters at
    examples/datasets/image_classification/load_mnist_format.py)."""
    from PIL import Image

    with zipfile.ZipFile(out_path, "w", zipfile.ZIP_STORED) as zf:
        lines = ["path,class"]
        for i, (img, lbl) in enumerate(zip(images, labels)):
            arr = img
            if arr.dtype != np.uint8:
                arr = (np.clip(arr, 0.0, 1.0) * 255).astype(np.uint8)
            if arr.ndim == 3 and arr.shape[-1] == 1:
                arr = arr[..., 0]
            buf = io.BytesIO()
            Image.fromarray(arr).save(buf, format="PNG")
            rel = f"images/{i}.png"
            zf.writestr(rel, buf.getvalue())
            lines.append(f"{rel},{int(lbl)}")
        zf.writestr("images.csv", "\n".join(lines) + "\n")
    return out_path


def write_corpus_dataset(
    sentences: Sequence[Tuple[Sequence[str], Sequence[Sequence[str]]]], out_path: str
) -> str:
    """Helper to build a CORPUS zip from (tokens, tags) sentence pairs."""
    lines: List[str] = []
    for tokens, tags in sentences:
        for tok, row_tags in zip(tokens, tags):
            lines.append("\t".join([tok, *row_tags]))
        lines.append("")
    with zipfile.ZipFile(out_path, "w") as zf:
        zf.writestr("corpus.tsv", "\n".join(lines) + "\n")
    return out_path


def write_numpy_dataset(x: np.ndarray, y: np.ndarray, out_path: str) -> str:
    np.savez_compressed(out_path, x=x, y=y)
    return out_path


#: module singleton, mirroring the reference's `dataset_utils`
dataset_utils = DatasetUtils()
