"""In-model structured logging (reference rafiki/model/log.py:14-192).

Model code logs messages, metrics, and plot definitions through a
``ModelLogger``; each line is a typed JSON record. The train worker installs a
sink that persists every line to the trial's log in the metadata store, and
``parse_logs`` reassembles records into messages/metrics/plots for UIs
(reference usage: worker/train.py:158-165, admin/admin.py:333,
web TrialDetailPage.tsx:205).
"""

from __future__ import annotations

import json
import time
from typing import Any, Callable, Dict, List, Optional

LogLine = str
Sink = Callable[[LogLine], None]


class LogType:
    MESSAGE = "MESSAGE"
    METRICS = "METRICS"
    PLOT = "PLOT"


class StopTrialEarly(Exception):
    """Raised out of ``ModelLogger.log`` when the trial's scheduler decided
    this trial should stop (ASHA early stopping, advisor/asha.py). The
    SDK trainer's fit() catches it and returns the current params; the
    train worker also catches it around ``model.train`` for templates with
    hand-rolled loops — either way the trial proceeds to evaluate() and
    completes with the score its truncated training earned."""


class ModelLogger:
    """Structured logger injected into models as ``self.logger`` / the module
    singleton ``logger``. Thread-safe enough for one trial per logger instance
    (the worker swaps sinks per trial, mirroring reference set_logger at
    rafiki/model/log.py:104)."""

    def __init__(self) -> None:
        self._sink: Optional[Sink] = None
        self._echo = True
        self._stop_check: Optional[Callable[[Dict[str, float]], bool]] = None

    def set_sink(self, sink: Optional[Sink], echo: bool = False) -> None:
        self._sink = sink
        self._echo = echo or sink is None

    def set_stop_check(
        self, check: Optional[Callable[[Dict[str, float]], bool]]
    ) -> None:
        """Install a per-metrics-report early-stop predicate (the worker
        wires this to the sub-train-job's ASHA scheduler). ``check(metrics)
        -> True`` makes the next ``log(**metrics)`` raise StopTrialEarly."""
        self._stop_check = check

    def log(self, msg: str = "", **metrics: float) -> None:
        """Log a free-form message and/or named numeric metrics."""
        if msg:
            self._emit({"type": LogType.MESSAGE, "message": str(msg)})
        if metrics:
            clean = {k: float(v) for k, v in metrics.items()}
            self._emit({"type": LogType.METRICS, "metrics": clean})
            if self._stop_check is not None and self._stop_check(clean):
                raise StopTrialEarly(
                    f"scheduler stopped this trial at {clean}")

    def define_plot(
        self, title: str, metrics: List[str], x_axis: Optional[str] = None
    ) -> None:
        """Declare that `metrics` should be plotted against `x_axis`
        (default: log time)."""
        self._emit(
            {"type": LogType.PLOT, "title": title, "metrics": list(metrics), "x_axis": x_axis}
        )

    def _emit(self, record: Dict[str, Any]) -> None:
        record["time"] = time.time()
        line = json.dumps(record)
        if self._sink is not None:
            self._sink(line)
        if self._echo:
            print(f"[model] {line}")


def parse_logs(lines: List[LogLine]) -> Dict[str, List[Dict[str, Any]]]:
    """Reassemble raw log lines into messages / metrics / plots
    (reference rafiki/model/log.py:125-158)."""
    messages: List[Dict[str, Any]] = []
    metrics: List[Dict[str, Any]] = []
    plots: List[Dict[str, Any]] = []
    for line in lines:
        try:
            rec = json.loads(line)
        except (json.JSONDecodeError, TypeError):
            messages.append({"message": str(line), "time": None})
            continue
        rtype = rec.get("type")
        if rtype == LogType.MESSAGE:
            messages.append({"message": rec.get("message"), "time": rec.get("time")})
        elif rtype == LogType.METRICS:
            metrics.append({**rec.get("metrics", {}), "time": rec.get("time")})
        elif rtype == LogType.PLOT:
            plots.append(
                {
                    "title": rec.get("title"),
                    "metrics": rec.get("metrics"),
                    "x_axis": rec.get("x_axis"),
                }
            )
    return {"messages": messages, "metrics": metrics, "plots": plots}


#: module singleton used by model code: `from rafiki_tpu.sdk import logger`
logger = ModelLogger()
