"""The model-template contract — what users implement and upload.

Capability parity with the reference's BaseModel (reference
rafiki/model/model.py:20-127): ``get_knob_config`` (static), ``train``,
``evaluate`` -> float score, ``predict`` -> JSON-able list, parameter
dump/load, ``destroy``; plus ``load_model_class`` (deserialize an uploaded
``.py``, reference model.py:221-242) and the local contract harness
``test_model_class`` (reference model.py:129-219).

Differences by design:
- parameters are msgpack'd pytrees, not pickles (see sdk/params.py);
- declared dependencies are *validated as importable*, not pip-installed per
  worker boot (the reference ran ``pip install`` in every container,
  reference scripts/start_worker.py:6-9 — dead time the TPU build eliminates);
- models get a device mesh from the placement layer (chip affinity) instead
  of CUDA_VISIBLE_DEVICES.
"""

from __future__ import annotations

import abc
import importlib.util
import inspect
import json
import os
import sys
import tempfile
import traceback
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from rafiki_tpu.sdk.knob import (
    BaseKnob,
    KnobConfig,
    serialize_knob_config,
    validate_knobs,
)
from rafiki_tpu.sdk.log import ModelLogger, logger as _module_logger


class InvalidModelClassError(Exception):
    pass


class PopulationSpec:
    """Declares that a template can train a POPULATION of knob configs as
    one vmapped XLA program (the trials/hour/chip lever — SURVEY §7.3,
    ROADMAP item 3). Set as a class attribute::

        class MyModel(BaseModel):
            population_spec = PopulationSpec(dynamic_knobs=("learning_rate",))

    ``dynamic_knobs`` names the knobs that may DIFFER across members of
    one vmapped program — pure hyperparameters that ride the optimizer
    state (lr/momentum/weight-decay through ``tunable_optimizer``).
    Every other knob is treated as program-shaping (architecture, batch
    size, epochs): the worker's shape-bucketing partitioner
    (worker/vmap_partition.py) only stacks proposals whose remaining
    knobs are identical, so members of one program always share one
    compiled step.

    ``max_members`` caps how many members the worker stacks into one
    program — the per-chip memory heuristic (stacked params + opt state
    scale linearly with K).

    A template advertising a spec must also implement the three
    population methods on :class:`BaseModel` (``train_population``,
    ``evaluate_population``, ``dump_member_parameters``);
    :func:`population_capability` refuses specs whose methods are still
    the base stubs, so a half-wired template falls back to scalar trials
    instead of crashing the worker."""

    def __init__(self, dynamic_knobs, max_members: int = 8):
        self.dynamic_knobs = tuple(dynamic_knobs)
        if not self.dynamic_knobs:
            raise ValueError(
                "PopulationSpec needs at least one dynamic knob name")
        self.max_members = max(int(max_members), 1)

    def __repr__(self) -> str:
        return (f"PopulationSpec(dynamic_knobs={self.dynamic_knobs!r}, "
                f"max_members={self.max_members})")


class GenerationSpec:
    """Declares that a template can serve the ``TEXT_GENERATION`` task:
    KV-cached autoregressive decode with token-level continuous batching
    (worker/generation.py). Set as a class attribute::

        class MyLM(BaseModel):
            generation_spec = GenerationSpec(eos_token_id=0,
                                             max_context=128)

    ``eos_token_id`` ends a sequence the step it is emitted (None = run to
    ``max_tokens``); ``max_context`` is the KV-cache ring length per slot —
    prompt plus generated tokens must fit, and a sequence reaching it is
    finished with reason ``context``.

    A template advertising a spec must also implement the three decode
    methods on :class:`BaseModel` (``init_kv_cache``, ``prefill``,
    ``decode_step``); :func:`generation_capability` refuses specs whose
    methods are still the base stubs, so a half-wired template is a typed
    deploy error instead of a mid-serving crash."""

    def __init__(self, eos_token_id: Optional[int] = None,
                 max_context: int = 128):
        self.eos_token_id = (None if eos_token_id is None
                             else int(eos_token_id))
        self.max_context = max(int(max_context), 2)

    def __repr__(self) -> str:
        return (f"GenerationSpec(eos_token_id={self.eos_token_id!r}, "
                f"max_context={self.max_context})")


class BaseModel(abc.ABC):
    """Abstract contract every model template implements.

    Subclasses are instantiated once per trial as ``Model(**knobs)`` with a
    concrete knob assignment proposed by the advisor.
    """

    #: declared dependencies: {package_name: version_spec_or_None}
    dependencies: Dict[str, Optional[str]] = {}

    def __init__(self, **knobs: Any):
        self._knobs = knobs
        self.logger: ModelLogger = _module_logger
        #: set by the train worker before ``train()``: a per-trial file path
        #: templates MAY hand to ``DataParallelTrainer.fit(checkpoint_path=
        #: ...)`` for mid-trial checkpointing — a crashed-and-restarted trial
        #: then resumes from its last epoch instead of from scratch (the
        #: reference always restarted from scratch, reference
        #: worker/train.py:122-132). None when run outside a worker.
        self.checkpoint_path: Optional[str] = None

    @staticmethod
    @abc.abstractmethod
    def get_knob_config() -> KnobConfig:
        """The tunable hyperparameter space for this template."""

    @abc.abstractmethod
    def train(self, dataset_uri: str) -> None:
        """Train on the dataset at `dataset_uri`."""

    @abc.abstractmethod
    def evaluate(self, dataset_uri: str) -> float:
        """Return a scalar score (higher is better) on the dataset."""

    @abc.abstractmethod
    def predict(self, queries: List[Any]) -> List[Any]:
        """Return one JSON-able prediction per query."""

    @abc.abstractmethod
    def dump_parameters(self) -> Any:
        """Return a serializable pytree of trained parameters."""

    @abc.abstractmethod
    def load_parameters(self, params: Any) -> None:
        """Restore trained parameters produced by ``dump_parameters``."""

    def warm_up(self) -> None:
        """Optional serving warm-up, called once by the inference worker
        after ``load_parameters`` and before the service reports ready.

        Implementations should run ``predict`` on representative synthetic
        queries at the batch sizes serving will use (e.g.
        ``DataParallelTrainer.warm_predict``) so every compiled shape exists
        before real traffic arrives — no request ever pays an XLA compile.
        Default: no-op (non-JAX templates have nothing to warm)."""

    def destroy(self) -> None:
        """Release resources (default: no-op)."""

    # -- vectorized trial execution (opt-in via ``population_spec``) -------

    #: set to a :class:`PopulationSpec` to advertise that this template can
    #: train a population of knob configs as ONE vmapped program; the train
    #: worker then drains K advisor proposals per round and runs each
    #: shape-compatible bucket through ``train_population`` instead of one
    #: scalar trial per proposal (worker/train.py).
    population_spec: Optional[PopulationSpec] = None

    def train_population(self, dataset_uri: str,
                         member_knobs: List[Dict[str, Any]]) -> None:
        """Train every member of ``member_knobs`` simultaneously (one
        vmapped program — see sdk/population.PopulationTrainer). The
        instance was constructed with ``member_knobs[0]``; members differ
        only in the spec's ``dynamic_knobs``. ``self.checkpoint_path``
        checkpoints the STACKED pytrees, giving the whole batch the same
        mid-trial resume guarantee as scalar trials."""
        raise NotImplementedError

    def evaluate_population(self, dataset_uri: str) -> List[float]:
        """One score per member, in ``member_knobs`` order. A member whose
        score comes back NaN/inf is failed INDIVIDUALLY by the worker
        (typed INVALID_SCORE + infeasible feedback for that member only),
        never the batch."""
        raise NotImplementedError

    def dump_member_parameters(self, member: int) -> Any:
        """Member ``member``'s parameters in the SAME format
        ``dump_parameters`` produces — each member becomes its own trial
        row with its own params artifact, so serving deploys winners
        exactly like scalar trials."""
        raise NotImplementedError

    # -- generative serving (opt-in via ``generation_spec``) ----------------

    #: set to a :class:`GenerationSpec` to advertise that this template can
    #: serve TEXT_GENERATION: the generation worker then drives the three
    #: decode methods below in a continuous-batching slot loop
    #: (worker/generation.py) instead of the one-request/one-answer
    #: ``predict`` path.
    generation_spec: Optional["GenerationSpec"] = None

    def init_kv_cache(self, max_slots: int) -> Any:
        """Preallocate an opaque decode cache for ``max_slots`` co-resident
        sequences (fixed shapes: one jitted step program serves the cache's
        whole lifetime). Called once by the generation worker after
        ``load_parameters``."""
        raise NotImplementedError

    def prefill(self, cache: Any, slot: int,
                prompt_ids: List[int]) -> Tuple[int, Any]:
        """Ingest a prompt into ``slot`` of ``cache`` and return
        ``(first_generated_token_id, cache)``. Caches are values: return
        the updated cache (JAX pytrees are immutable)."""
        raise NotImplementedError

    def decode_step(self, cache: Any, ids: Any, positions: Any
                    ) -> Tuple[Any, Any]:
        """One token for EVERY slot: ``ids``/``positions`` are int arrays of
        length ``max_slots`` — the last emitted token per slot and the cache
        index it lands at (idle slots carry zeros; their outputs are
        ignored). Returns ``(next_token_ids, cache)``."""
        raise NotImplementedError

    # -- paged decode memory (opt-in refinement of the generation contract)

    def init_paged_kv_cache(self, pool_blocks: int,
                            block_tokens: int) -> Any:
        """Preallocate a BLOCK-POOL decode cache: ``pool_blocks`` pages of
        ``block_tokens`` K/V rows each, instead of one contiguous ring per
        slot. Templates that also override the three ``paged_*`` methods
        below serve under the paged allocator (worker/kv_paging.py) —
        co-resident streams are then bound by *used* tokens, not
        ``slots x max_context`` — and gain shared-prefix caching and
        chunked prefill for free. Templates without them keep the ring
        path unchanged."""
        raise NotImplementedError

    def paged_prefill(self, cache: Any, block_table: Any,
                      prompt_ids: List[int], start: int
                      ) -> Tuple[int, Any]:
        """Ingest prompt tokens at logical positions ``start ..
        start + len(prompt_ids) - 1`` of the slot whose physical pages
        are ``block_table`` (int32, fixed width, sentinel = pool size for
        unallocated entries). Returns ``(next_token_id, cache)`` — the
        token is only meaningful when this call covered the prompt's last
        position (chunked prefill ignores intermediate returns)."""
        raise NotImplementedError

    def paged_decode_step(self, cache: Any, ids: Any, positions: Any,
                          block_tables: Any) -> Tuple[Any, Any]:
        """One token for EVERY slot against the block pool:
        ``block_tables`` is (max_slots, table_blocks) int32 (idle slots
        carry all-sentinel rows). Same fixed-shape/one-program contract
        as ``decode_step``."""
        raise NotImplementedError

    def kv_copy_blocks(self, cache: Any, src: Any, dst: Any) -> Any:
        """Copy whole pool pages ``src[i] -> dst[i]`` — the allocator's
        copy-on-write primitive (models/lm.py ``copy_kv_blocks``)."""
        raise NotImplementedError

    # -- sampling + speculative decoding (opt-in refinements) ---------------

    def decode_step_sampled(self, cache: Any, ids: Any, positions: Any,
                            sampling: Any) -> Tuple[Any, Any, Any]:
        """``decode_step`` with an in-graph temperature/top-k/top-p draw.

        ``sampling`` is a dict of per-slot arrays — ``seed`` (uint32),
        ``temperature`` (f32), ``top_k`` (int32, 0 = off), ``top_p``
        (f32, 1.0 = off) — plus a scalar ``role`` (see models/lm.py
        ``ROLE_*``). Every draw MUST be keyed
        ``fold_in(fold_in(PRNGKey(seed), token_position), role)`` so
        sampled streams resume exactly after preemption, and
        temperature <= 0 MUST reproduce the greedy argmax bit-identically.
        Returns ``(token_ids, probs, cache)`` where ``probs`` is the FULL
        modified distribution per slot — a draft model's q, the
        denominator of the speculative accept test."""
        raise NotImplementedError

    def paged_decode_step_sampled(self, cache: Any, ids: Any,
                                  positions: Any, block_tables: Any,
                                  sampling: Any) -> Tuple[Any, Any, Any]:
        """``paged_decode_step`` with the same in-graph sampled draw and
        key discipline as ``decode_step_sampled``."""
        raise NotImplementedError

    def paged_verify_step(self, cache: Any, ids: Any, positions: Any,
                          block_tables: Any, draft_probs: Any,
                          sampling: Any) -> Tuple[Any, Any, Any]:
        """Verify k drafted tokens per slot in ONE fixed-shape forward
        (models/lm.py ``paged_verify_step``). ``ids`` (S, k+1) carries
        each slot's last committed token then the draft's k proposals,
        ``positions`` (S, k+1) their write positions, ``draft_probs``
        (S, k, V) the draft's modified distributions. Returns
        ``(accept_len, tokens, cache)``: per-slot accepted-prefix lengths
        (data, not shape — mixed acceptance never retraces) and the
        committed tokens left-packed per row (accept_len + 1 of them:
        accepted prefix plus the rejection-resample or bonus token)."""
        raise NotImplementedError

    def ensemble_stack(self, models: List["BaseModel"]) -> Optional[Any]:
        """Optional fused-ensemble serving hook (budget ``ENSEMBLE_FUSED``).

        ``models`` is the full co-served group, ``self`` included. Return an
        object with ``predict_all(queries) -> [n_models][n_queries]`` (and
        optionally ``warm_up()``) that answers for EVERY model in one device
        dispatch — for SDK-trainer templates that is
        ``DataParallelTrainer.predict_batched_stacked`` over
        ``stack_ensemble_params`` (see JaxCnn.ensemble_stack). Return None
        when the group cannot share a compiled predict (different
        architecture knobs, different param shapes, non-JAX template); the
        fused worker then serves the group sequentially in-process.
        Default: None."""
        return None


def population_capability(clazz: type) -> Optional[PopulationSpec]:
    """The template's :class:`PopulationSpec` iff it is fully wired:
    a spec instance AND all three population methods overridden. Anything
    less returns None — the worker then runs scalar trials (automatic
    fallback; the doctor's "vectorized trials" check surfaces the
    silent-fallback case when population mode was explicitly asked for)."""
    spec = getattr(clazz, "population_spec", None)
    if spec is None:
        return None
    import logging

    if not isinstance(spec, PopulationSpec):
        logging.getLogger(__name__).warning(
            "%s.population_spec is not a PopulationSpec (%s); ignoring — "
            "trials run scalar", clazz.__name__, type(spec).__name__)
        return None
    for name in ("train_population", "evaluate_population",
                 "dump_member_parameters"):
        if getattr(clazz, name, None) is getattr(BaseModel, name):
            logging.getLogger(__name__).warning(
                "%s declares population_spec but does not override %s(); "
                "ignoring — trials run scalar", clazz.__name__, name)
            return None
    return spec


#: the three decode methods a generation-capable template must override
GENERATION_METHODS = ("init_kv_cache", "prefill", "decode_step")


def generation_capability(clazz: type) -> Optional[GenerationSpec]:
    """The template's :class:`GenerationSpec` iff it is fully wired: a
    spec instance AND all three decode methods overridden. Anything less
    returns None — unlike the population fallback there is no scalar path
    to degrade to, so callers (upload validation, the generation worker)
    turn None into a typed error rather than a silent downgrade."""
    spec = getattr(clazz, "generation_spec", None)
    if spec is None:
        return None
    import logging

    if not isinstance(spec, GenerationSpec):
        logging.getLogger(__name__).warning(
            "%s.generation_spec is not a GenerationSpec (%s); ignoring",
            clazz.__name__, type(spec).__name__)
        return None
    for name in GENERATION_METHODS:
        if getattr(clazz, name, None) is getattr(BaseModel, name):
            logging.getLogger(__name__).warning(
                "%s declares generation_spec but does not override %s(); "
                "template is NOT generation-capable", clazz.__name__, name)
            return None
    return spec


#: the additional methods a template must override to serve under the
#: paged KV allocator (block pool + prefix cache + chunked prefill)
GENERATION_PAGED_METHODS = ("init_paged_kv_cache", "paged_prefill",
                            "paged_decode_step", "kv_copy_blocks")


def paged_generation_capability(clazz: type) -> Optional[GenerationSpec]:
    """The template's :class:`GenerationSpec` iff it is paged-capable:
    the full base generation contract PLUS all four paged methods
    overridden. None degrades the worker to the contiguous-ring path —
    a safe fallback (unlike the base contract, where None is a typed
    deploy error), surfaced by the doctor's generative-serving check."""
    spec = generation_capability(clazz)
    if spec is None:
        return None
    for name in GENERATION_PAGED_METHODS:
        if getattr(clazz, name, None) is getattr(BaseModel, name):
            return None
    return spec


#: counter-based RNG roles shared by every sampled draw (models/lm.py)
ROLE_TARGET = 0
ROLE_DRAFT = 1
ROLE_ACCEPT = 2

#: the sampled-decode methods (real temperature/top-k/top-p sampling).
#: ``decode_step_sampled`` is the base requirement; paged-capable
#: templates must also wire the paged variant or sampling stays off.
GENERATION_SAMPLING_METHODS = ("decode_step_sampled",
                               "paged_decode_step_sampled")

#: the one extra method of the speculative-verify contract
GENERATION_SPEC_METHODS = ("paged_verify_step",)


def sampling_capability(clazz: type) -> Optional[GenerationSpec]:
    """The template's :class:`GenerationSpec` iff real sampling is fully
    wired: the base generation contract plus ``decode_step_sampled``, and
    — when the template is paged-capable — ``paged_decode_step_sampled``
    too (the worker serves whichever plane the template supports; a
    sampled method the serving plane can't reach is half-wired). None
    degrades to greedy-only serving: the worker turns a sampled request
    against it into a typed request error, never a silent greedy answer."""
    spec = generation_capability(clazz)
    if spec is None:
        return None
    needed = ["decode_step_sampled"]
    if paged_generation_capability(clazz) is not None:
        needed.append("paged_decode_step_sampled")
    import logging

    for name in needed:
        if getattr(clazz, name, None) is getattr(BaseModel, name):
            logging.getLogger(__name__).warning(
                "%s does not override %s(); template is NOT "
                "sampling-capable — sampled requests will be refused",
                clazz.__name__, name)
            return None
    return spec


def draft_capability(clazz: type) -> Optional[GenerationSpec]:
    """The template's :class:`GenerationSpec` iff it can serve as a
    speculative DRAFT model: the base (ring) generation contract plus
    ``decode_step_sampled`` — drafts propose through their own contiguous
    ring (a small model's worst-case K/V is cheap) and must return their
    full modified distribution q for the accept test.

    A draft may ALSO provide ``decode_steps_sampled(cache, ids,
    positions, k, sampling) -> (tokens (S, k), q (S, k, V), cache)`` —
    the whole k-token proposal burst fused into one program. Optional
    fast path, not part of the capability: the worker falls back to k
    chained ``decode_step_sampled`` calls (each paying dispatch plus a
    host sync) when it is absent."""
    spec = generation_capability(clazz)
    if spec is None:
        return None
    if getattr(clazz, "decode_step_sampled", None) is \
            getattr(BaseModel, "decode_step_sampled"):
        import logging

        logging.getLogger(__name__).warning(
            "%s does not override decode_step_sampled(); template cannot "
            "serve as a speculative draft model", clazz.__name__)
        return None
    return spec


def spec_verify_capability(clazz: type) -> Optional[GenerationSpec]:
    """The template's :class:`GenerationSpec` iff it can serve as a
    speculative TARGET: paged-capable, sampling-capable, and
    ``paged_verify_step`` overridden. None degrades the worker to plain
    paged decode (a safe fallback, surfaced by the doctor's speculative-
    decoding check and the worker's ``gen_spec_degraded`` stats field)."""
    spec = paged_generation_capability(clazz)
    if spec is None:
        return None
    if sampling_capability(clazz) is None:
        return None
    if getattr(clazz, "paged_verify_step", None) is \
            getattr(BaseModel, "paged_verify_step"):
        import logging

        logging.getLogger(__name__).warning(
            "%s does not override paged_verify_step(); template cannot "
            "verify speculative drafts — serving plain paged decode",
            clazz.__name__)
        return None
    return spec


def load_model_class(
    model_bytes: bytes, class_name: str, temp_dir: Optional[str] = None
) -> type:
    """Import an uploaded model template's ``.py`` bytes and return its class
    (reference rafiki/model/model.py:221-242)."""
    tmp = tempfile.NamedTemporaryFile(
        "wb", suffix=".py", dir=temp_dir, delete=False
    )
    try:
        tmp.write(model_bytes)
        tmp.close()
        mod_name = f"rafiki_model_{os.path.basename(tmp.name)[:-3]}"
        spec = importlib.util.spec_from_file_location(mod_name, tmp.name)
        assert spec is not None and spec.loader is not None
        module = importlib.util.module_from_spec(spec)
        sys.modules[mod_name] = module
        spec.loader.exec_module(module)
        clazz = getattr(module, class_name, None)
        if clazz is None or not inspect.isclass(clazz):
            raise InvalidModelClassError(
                f"Class {class_name!r} not found in uploaded model file"
            )
        if not issubclass(clazz, BaseModel):
            raise InvalidModelClassError(
                f"{class_name} must subclass rafiki_tpu BaseModel"
            )
        return clazz
    finally:
        try:
            os.unlink(tmp.name)
        except OSError:
            pass


def validate_model_dependencies(clazz: type) -> List[str]:
    """Check declared dependencies are importable in this environment;
    return the missing ones. Provisioning (the reference's
    install-command synthesis, reference rafiki/model/model.py:244-273)
    lives in sdk/deps.py behind RAFIKI_INSTALL_DEPS."""
    from rafiki_tpu.sdk.deps import missing_dependencies

    return missing_dependencies(getattr(clazz, "dependencies", {}) or {})


def test_model_class(
    model_file_path: Optional[str] = None,
    model_class: Optional[str] = None,
    task: Optional[str] = None,
    dependencies: Optional[Dict[str, Optional[str]]] = None,
    train_dataset_uri: Optional[str] = None,
    test_dataset_uri: Optional[str] = None,
    queries: Optional[List[Any]] = None,
    clazz: Optional[type] = None,
    knobs: Optional[Dict[str, Any]] = None,
) -> List[Any]:
    """Local contract-conformance harness (reference rafiki/model/model.py:129-219).

    Runs the full lifecycle a deployed trial would: dependency check ->
    knob-config check -> in-process advisor proposal -> train -> evaluate ->
    parameter dump/restore round-trip through bytes -> destroy + fresh
    instance -> predict -> JSON-serializability check -> ensembling smoke
    test. Returns the predictions.

    Call with either ``clazz=`` (an already-imported class) or
    ``model_file_path=`` + ``model_class=``.
    """
    from rafiki_tpu.advisor.advisor import Advisor
    from rafiki_tpu.predictor.ensemble import ensemble_predictions
    from rafiki_tpu.sdk.params import dump_params, load_params

    if clazz is None:
        assert model_file_path is not None and model_class is not None
        with open(model_file_path, "rb") as f:
            clazz = load_model_class(f.read(), model_class)

    missing = validate_model_dependencies(clazz)
    if missing:
        raise InvalidModelClassError(f"Missing dependencies: {missing}")

    knob_config = clazz.get_knob_config()
    for name, knob in knob_config.items():
        if not isinstance(knob, BaseKnob):
            raise InvalidModelClassError(f"Knob {name!r} is not a BaseKnob")
    # knob config must survive the HTTP wire format
    serialize_knob_config(knob_config)

    if knobs is None:
        advisor = Advisor(knob_config)
        knobs = advisor.propose()
    validate_knobs(knob_config, knobs)
    print(f"[test_model_class] knobs: {knobs}")

    model = clazz(**knobs)
    assert train_dataset_uri is not None and test_dataset_uri is not None
    model.train(train_dataset_uri)
    score = model.evaluate(test_dataset_uri)
    try:
        score = float(score)  # accepts python/numpy/jax scalars alike
    except (TypeError, ValueError):
        raise InvalidModelClassError("evaluate() must return a float score")
    print(f"[test_model_class] score: {score}")

    # round-trip parameters through bytes, as the worker/predictor would
    params_bytes = dump_params(model.dump_parameters())
    model.destroy()

    model = clazz(**knobs)
    model.load_parameters(load_params(params_bytes))

    queries = queries if queries is not None else []
    predictions = model.predict(queries)
    if not isinstance(predictions, list) or len(predictions) != len(queries):
        raise InvalidModelClassError("predict() must return one prediction per query")
    try:
        json.dumps(predictions)
    except (TypeError, ValueError) as e:
        raise InvalidModelClassError(f"Predictions not JSON-serializable: {e}")

    if queries:
        # ensembling smoke test across two copies of the same predictions
        ensemble_predictions([predictions, predictions], task)

    model.destroy()
    print("[test_model_class] OK")
    return predictions
