"""Per-model dependency provisioning.

The reference synthesized ``pip install``/``conda install`` commands from
each model's declared dependencies and ran them at worker boot inside the
trial's container (/root/reference/rafiki/model/model.py:244-273,
scripts/start_worker.py:6-9) — paying the install on EVERY trial start.
Here provisioning is per *model*, cached on disk, and opt-in:

- default: validate-only (sdk/model.py validate_model_dependencies) —
  registration fails fast naming the missing packages and the exact
  install command an operator would run;
- ``RAFIKI_INSTALL_DEPS=1``: missing dependencies are pip-installed into
  a per-model prefix under ``$RAFIKI_WORKDIR/deps/<fingerprint>`` which
  is then put on ``sys.path`` for that model's trials. The fingerprint
  is the sorted (name, version) set, so models sharing a dependency set
  share one install and trials after the first pay nothing (the
  reference re-installed per container boot). ``RAFIKI_PIP_ARGS`` passes
  extra flags (e.g. ``--no-index --find-links /mirror`` for air-gapped
  TPU pods — this build environment itself has no egress, which is also
  why install mode is off by default).
"""

from __future__ import annotations

import hashlib
import importlib.util
import logging
import os
import subprocess
import sys
from typing import Dict, List, Optional

logger = logging.getLogger(__name__)

# import name != distribution name for these common cases
IMPORT_ALIASES = {"scikit-learn": "sklearn", "pillow": "PIL",
                  "pyyaml": "yaml", "opencv-python": "cv2"}


class DependencyError(Exception):
    pass


def install_enabled() -> bool:
    return os.environ.get("RAFIKI_INSTALL_DEPS") == "1"


def import_name(dep: str) -> str:
    return IMPORT_ALIASES.get(dep.lower(), dep.replace("-", "_"))


def synthesize_pip_command(
    deps: Dict[str, Optional[str]], target: Optional[str] = None,
) -> List[str]:
    """The exact pip invocation for a dependency dict ({name: version or
    None}) — the reference's install-command synthesis
    (reference model/model.py:244-273), pip-only and offline-overridable."""
    cmd = [sys.executable, "-m", "pip", "install", "--quiet",
           "--disable-pip-version-check"]
    cmd += os.environ.get("RAFIKI_PIP_ARGS", "").split()
    if target:
        cmd += ["--target", target]
    for name in sorted(deps):
        version = deps[name]
        cmd.append(f"{name}=={version}" if version else name)
    return cmd


def deps_prefix(deps: Dict[str, Optional[str]],
                workdir: Optional[str] = None) -> str:
    """Shared on-disk prefix for a dependency set (content-addressed)."""
    from rafiki_tpu import config

    fp = hashlib.sha256(json_dumps_sorted(deps).encode()).hexdigest()[:16]
    return os.path.join(workdir or config.WORKDIR, "deps", fp)


def json_dumps_sorted(deps: Dict[str, Optional[str]]) -> str:
    import json

    return json.dumps(sorted((k, v) for k, v in deps.items()))


def missing_dependencies(deps: Dict[str, Optional[str]],
                         extra_path: Optional[str] = None) -> List[str]:
    """Dependency names not importable right now (version pins are not
    re-checked for already-importable packages — matching the reference,
    which only guaranteed presence, not downgrade)."""
    missing = []
    for dep in deps or {}:
        mod = import_name(dep)
        if importlib.util.find_spec(mod) is not None:
            continue
        top = mod.split(".")[0]
        if extra_path and (
                os.path.isdir(os.path.join(extra_path, top))
                # single-file-module distributions (six.py style)
                or os.path.isfile(os.path.join(extra_path, top + ".py"))):
            continue
        missing.append(dep)
    return missing


def ensure_dependencies(deps: Dict[str, Optional[str]]) -> Optional[str]:
    """Make a model's declared dependencies available.

    Returns the per-set install prefix to put on ``sys.path`` (None when
    everything already imports from the base environment). Validate-only
    mode raises DependencyError for missing packages, naming the command
    an operator would run — the fail-fast the reference deferred to
    worker boot time."""
    deps = deps or {}
    prefix = deps_prefix(deps)
    miss = missing_dependencies(deps, extra_path=prefix)
    if not miss:
        return prefix if os.path.isdir(prefix) else None
    pinned = {k: deps[k] for k in miss}
    if not install_enabled():
        raise DependencyError(
            f"model dependencies not installed: {sorted(miss)}. Install "
            f"them (e.g. `{' '.join(synthesize_pip_command(pinned))}`) or "
            f"set RAFIKI_INSTALL_DEPS=1 to let workers provision them.")
    os.makedirs(prefix, exist_ok=True)
    cmd = synthesize_pip_command(pinned, target=prefix)
    logger.info("installing model dependencies: %s", " ".join(cmd))
    proc = subprocess.run(cmd, capture_output=True, text=True, timeout=900)
    if proc.returncode != 0:
        raise DependencyError(
            f"pip install of {sorted(miss)} failed (rc={proc.returncode}):\n"
            f"{(proc.stderr or '')[-2000:]}")
    still = missing_dependencies(deps, extra_path=prefix)
    if still:
        raise DependencyError(
            f"dependencies still missing after install: {sorted(still)}")
    return prefix


def activate_prefix(prefix: Optional[str]) -> None:
    """Put an install prefix at the FRONT of sys.path (pinned versions must
    shadow base-environment copies)."""
    if prefix and os.path.isdir(prefix) and prefix not in sys.path:
        sys.path.insert(0, prefix)
