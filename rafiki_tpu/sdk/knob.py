"""Typed hyperparameter ("knob") space.

Capability parity with the reference's knob types (reference
rafiki/model/knob.py:4-198): CategoricalKnob, FixedKnob, IntegerKnob,
FloatKnob (min/max, optional log-scale), plus JSON (de)serialization for
shipping knob configs over HTTP.

Design difference: each knob additionally knows how to encode itself into the
unit cube (`dims`, `to_unit`, `from_unit`). The Bayesian advisor
(rafiki_tpu.advisor) optimizes over [0,1]^d and never needs knob-type-specific
logic — in the reference that mapping lived inside the BTB adapter
(reference rafiki/advisor/btb_gp_advisor.py:20-52).
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Sequence

import numpy as np


class BaseKnob:
    """A single tunable hyperparameter."""

    #: number of unit-cube dimensions this knob occupies
    dims: int = 1

    def sample(self, rng: np.random.Generator) -> Any:
        return self.from_unit(rng.random(self.dims))

    def to_unit(self, value: Any) -> np.ndarray:
        raise NotImplementedError

    def from_unit(self, u: np.ndarray) -> Any:
        raise NotImplementedError

    def validate(self, value: Any) -> bool:
        raise NotImplementedError

    def to_json(self) -> Dict[str, Any]:
        raise NotImplementedError

    def __repr__(self) -> str:
        return f"{type(self).__name__}({self.to_json()})"

    def __eq__(self, other: object) -> bool:
        return type(self) is type(other) and self.to_json() == other.to_json()  # type: ignore[union-attr]


class FixedKnob(BaseKnob):
    """A knob pinned to one value (not tuned)."""

    dims = 0

    def __init__(self, value: Any):
        self.value = value

    def to_unit(self, value: Any) -> np.ndarray:
        return np.zeros(0)

    def from_unit(self, u: np.ndarray) -> Any:
        return self.value

    def validate(self, value: Any) -> bool:
        return value == self.value

    def to_json(self) -> Dict[str, Any]:
        return {"type": "fixed", "value": self.value}


class CategoricalKnob(BaseKnob):
    """A knob over a finite unordered set of values (str/int/float/bool)."""

    def __init__(self, values: Sequence[Any]):
        if len(values) == 0:
            raise ValueError("CategoricalKnob needs at least one value")
        self.values: List[Any] = list(values)

    dims = 1

    def to_unit(self, value: Any) -> np.ndarray:
        idx = self.values.index(value)
        # midpoint of the bucket, so from_unit(to_unit(v)) == v
        return np.array([(idx + 0.5) / len(self.values)])

    def from_unit(self, u: np.ndarray) -> Any:
        idx = min(int(float(u[0]) * len(self.values)), len(self.values) - 1)
        return self.values[idx]

    def validate(self, value: Any) -> bool:
        return value in self.values

    def to_json(self) -> Dict[str, Any]:
        return {"type": "categorical", "values": self.values}


def _range_to_unit(v: float, lo: float, hi: float, is_exp: bool) -> float:
    if is_exp:
        llo, lhi = math.log(lo), math.log(hi)
        x = (math.log(v) - llo) / (lhi - llo) if lhi > llo else 0.0
    else:
        x = (v - lo) / (hi - lo) if hi > lo else 0.0
    return min(max(x, 0.0), 1.0)


def _unit_to_range(x: float, lo: float, hi: float, is_exp: bool) -> float:
    if is_exp:
        llo, lhi = math.log(lo), math.log(hi)
        v = math.exp(llo + x * (lhi - llo))
    else:
        v = lo + x * (hi - lo)
    # exp(log(lo)) can round a hair OUTSIDE [lo, hi]; a decoded value the
    # knob's own validate() rejects would error a trial on a perfectly
    # legitimate advisor proposal
    return min(max(v, lo), hi)


class _NumericKnob(BaseKnob):
    """Shared min/max/log-scale machinery for Integer/Float knobs."""

    _json_type: str

    def __init__(self, value_min, value_max, is_exp: bool = False):
        if value_max < value_min:
            raise ValueError("value_max < value_min")
        if is_exp and value_min <= 0:
            raise ValueError("log-scale knob needs value_min > 0")
        self.value_min = value_min
        self.value_max = value_max
        self.is_exp = bool(is_exp)

    def to_unit(self, value: Any) -> np.ndarray:
        return np.array(
            [_range_to_unit(float(value), self.value_min, self.value_max, self.is_exp)]
        )

    def to_json(self) -> Dict[str, Any]:
        return {
            "type": self._json_type,
            "value_min": self.value_min,
            "value_max": self.value_max,
            "is_exp": self.is_exp,
        }


class IntegerKnob(_NumericKnob):
    """An integer knob in [value_min, value_max], optionally log-scaled."""

    _json_type = "integer"

    def __init__(self, value_min: int, value_max: int, is_exp: bool = False):
        super().__init__(int(value_min), int(value_max), is_exp)

    def from_unit(self, u: np.ndarray) -> int:
        v = _unit_to_range(float(u[0]), self.value_min, self.value_max, self.is_exp)
        return int(min(max(round(v), self.value_min), self.value_max))

    def validate(self, value: Any) -> bool:
        return (
            isinstance(value, (int, np.integer))
            and self.value_min <= value <= self.value_max
        )


class FloatKnob(_NumericKnob):
    """A float knob in [value_min, value_max], optionally log-scaled
    (``is_exp=True``, e.g. learning rates; reference rafiki/model/knob.py)."""

    _json_type = "float"

    def __init__(self, value_min: float, value_max: float, is_exp: bool = False):
        super().__init__(float(value_min), float(value_max), is_exp)

    def from_unit(self, u: np.ndarray) -> float:
        return float(
            _unit_to_range(float(u[0]), self.value_min, self.value_max, self.is_exp)
        )

    def validate(self, value: Any) -> bool:
        return (
            isinstance(value, (float, int, np.floating, np.integer))
            and self.value_min <= float(value) <= self.value_max + 1e-12
        )


_KNOB_TYPES = {
    "fixed": lambda j: FixedKnob(j["value"]),
    "categorical": lambda j: CategoricalKnob(j["values"]),
    "integer": lambda j: IntegerKnob(j["value_min"], j["value_max"], j.get("is_exp", False)),
    "float": lambda j: FloatKnob(j["value_min"], j["value_max"], j.get("is_exp", False)),
}

KnobConfig = Dict[str, BaseKnob]


def serialize_knob_config(knob_config: KnobConfig) -> Dict[str, Any]:
    """Knob config -> JSON-able dict (reference rafiki/model/knob.py:186-190)."""
    return {name: knob.to_json() for name, knob in knob_config.items()}


def deserialize_knob_config(config_json: Dict[str, Any]) -> KnobConfig:
    """JSON dict -> knob config (reference rafiki/model/knob.py:192-198)."""
    out: KnobConfig = {}
    for name, j in config_json.items():
        ktype = j.get("type")
        if ktype not in _KNOB_TYPES:
            raise ValueError(f"Unknown knob type: {ktype!r}")
        out[name] = _KNOB_TYPES[ktype](j)
    return out


def knob_config_dims(knob_config: KnobConfig) -> int:
    """Total unit-cube dimensionality of a knob config."""
    return sum(k.dims for k in knob_config.values())


def knobs_to_unit(knob_config: KnobConfig, knobs: Dict[str, Any]) -> np.ndarray:
    """Encode a concrete knob assignment into [0,1]^d (stable name order)."""
    parts = [knob_config[name].to_unit(knobs[name]) for name in sorted(knob_config)]
    return np.concatenate(parts) if parts else np.zeros(0)


def knobs_from_unit(knob_config: KnobConfig, u: np.ndarray) -> Dict[str, Any]:
    """Decode a point in [0,1]^d into a concrete knob assignment."""
    out: Dict[str, Any] = {}
    i = 0
    for name in sorted(knob_config):
        knob = knob_config[name]
        out[name] = knob.from_unit(u[i : i + knob.dims])
        i += knob.dims
    return out


def validate_knobs(knob_config: KnobConfig, knobs: Dict[str, Any]) -> None:
    """Raise ValueError if `knobs` doesn't satisfy `knob_config`."""
    missing = set(knob_config) - set(knobs)
    extra = set(knobs) - set(knob_config)
    if missing or extra:
        raise ValueError(f"Knob name mismatch: missing={missing}, extra={extra}")
    for name, knob in knob_config.items():
        if not knob.validate(knobs[name]):
            raise ValueError(f"Invalid value for knob {name!r}: {knobs[name]!r}")
