"""Model SDK (L1): the contract user model templates implement, plus the
JAX/XLA training backend, knob types, dataset utilities, parameter
serialization, and structured in-model logging.

Reference analogue: rafiki/model/ (SURVEY.md §2.1)."""

from rafiki_tpu.sdk.dataset import dataset_utils  # noqa: F401
from rafiki_tpu.sdk.jax_backend import (  # noqa: F401
    DataParallelTrainer,
    cached_trainer,
    classification_accuracy,
    enable_persistent_compile_cache,
    softmax_classifier_loss,
    trainer_ensemble_stack,
    tunable_optimizer,
)
from rafiki_tpu.sdk.knob import (  # noqa: F401
    BaseKnob,
    CategoricalKnob,
    FixedKnob,
    FloatKnob,
    IntegerKnob,
    deserialize_knob_config,
    serialize_knob_config,
)
from rafiki_tpu.sdk.log import (  # noqa: F401
    ModelLogger,
    StopTrialEarly,
    logger,
    parse_logs,
)
from rafiki_tpu.sdk.population import PopulationTrainer  # noqa: F401
from rafiki_tpu.sdk.model import (  # noqa: F401
    BaseModel,
    GenerationSpec,
    InvalidModelClassError,
    PopulationSpec,
    draft_capability,
    generation_capability,
    load_model_class,
    population_capability,
    sampling_capability,
    spec_verify_capability,
    test_model_class,
    validate_model_dependencies,
)
from rafiki_tpu.sdk.params import dump_params, load_params  # noqa: F401
