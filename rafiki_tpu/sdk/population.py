"""Population training: K hyperparameter variants of one model trained
simultaneously in ONE jitted program.

SURVEY §7.3's "vmap-over-knobs" lever — the trials/hour multiplier the
reference could never pull (its unit of work was one container per trial,
one GPU each, reference admin/services_manager.py:117-126). For small
models, one chip's MXU is far from saturated by a single trial; ``vmap``
over a population axis turns K trials into K-times-larger matmuls in the
same program, so K learning rates (or any dynamic-hyperparameter draws)
train for roughly the cost of one.

Design:
- member hyperparameters ride the optimizer state (``tunable_optimizer`` /
  ``optax.inject_hyperparams``), so vmapping over (params, opt_state)
  gives every member its own values with ONE compiled step;
- the data batch is shared across members (standard for population
  training) and sharded over the mesh's ``data`` axis like the
  single-trial trainer; the population axis stays unsharded (member count
  is small, and per-member tensors are what fills the MXU);
- each epoch runs as one ``lax.scan`` dispatch (the device-resident epoch
  scan of DataParallelTrainer.fit, vmapped) — populations exist for small
  models, exactly where per-step dispatch overhead dominates;
- rng: member k's step rng is ``fold_in(step_rng, k)``, so members with
  identical hyperparameters still explore distinct dropout/shuffle noise
  unless ``shared_member_rng=True``.

The product surface is a model template that trains a population inside
one AutoML trial and keeps the best member (see
examples/models/image_classification/JaxCnnPopulation.py) — each trial
then reports best-of-K, multiplying effective HPO throughput on top of the
trial-level parallelism and ASHA early stopping.
"""

from __future__ import annotations

import logging
import os
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from rafiki_tpu.parallel.mesh import DATA_AXIS, get_default_mesh
from rafiki_tpu.sdk.jax_backend import set_opt_hyperparams, shuffled_batches

logger = logging.getLogger(__name__)

LossFn = Callable[[Any, Any, jax.Array], Tuple[jax.Array, Dict[str, jax.Array]]]

#: process-local fit telemetry: how many times :meth:`PopulationTrainer.fit`
#: ran and with how many stacked members each time (bounded tail). The
#: vectorized-trial tests assert the tentpole's core claim against this —
#: K distinct knob vectors trained by ONE fit call — and the bench's
#: trials_vectorized phase reads it to prove the vmapped path actually
#: engaged rather than silently falling back to scalar trials.
FIT_STATS: Dict[str, Any] = {"fit_calls": 0, "member_counts": []}
_FIT_STATS_TAIL = 64


def reset_fit_stats() -> None:
    FIT_STATS["fit_calls"] = 0
    FIT_STATS["member_counts"] = []


class PopulationTrainer:
    """Train a population of K members that differ only in dynamic
    hyperparameters (and rng). Stateless models only — population members
    with BatchNorm-style mutable state belong in separate trials."""

    def __init__(
        self,
        loss_fn: LossFn,
        optimizer: optax.GradientTransformation,
        predict_fn: Optional[Callable[..., jax.Array]] = None,
        mesh=None,
        shared_member_rng: bool = False,
    ):
        self.mesh = mesh or get_default_mesh()
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.predict_fn = predict_fn
        self._repl = NamedSharding(self.mesh, P())
        self._data = NamedSharding(self.mesh, P(DATA_AXIS))
        self.n_data = self.mesh.shape[DATA_AXIS]

        def member_step(params, opt_state, batch, rng):
            (loss, _), grads = jax.value_and_grad(
                self.loss_fn, has_aux=True)(params, batch, rng)
            updates, opt_state = self.optimizer.update(
                grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        pop_step = jax.vmap(member_step,
                            in_axes=(0, 0, None, None if shared_member_rng
                                     else 0))

        def epoch_scan(params, opt_state, data_dev, idx_mat, epoch_key):
            n_members = jax.tree.leaves(params)[0].shape[0]

            def body(carry, step):
                p, o = carry
                i, idx = step
                batch = tuple(
                    jax.lax.with_sharding_constraint(
                        jnp.take(d, idx, axis=0), self._data)
                    for d in data_dev)
                step_rng = jax.random.fold_in(epoch_key, i)
                rngs = (step_rng if shared_member_rng
                        else jax.vmap(
                            lambda k: jax.random.fold_in(step_rng, k))(
                            jnp.arange(n_members)))
                p, o, losses = pop_step(p, o, batch, rngs)
                return (p, o), losses

            (params, opt_state), losses = jax.lax.scan(
                body, (params, opt_state),
                (jnp.arange(idx_mat.shape[0]), idx_mat))
            return params, opt_state, losses  # losses: (n_steps, K)

        self._epoch_scan = jax.jit(
            epoch_scan,
            donate_argnums=(0, 1),
            in_shardings=(self._repl,) * 5,
            out_shardings=(self._repl,) * 3,
        )
        if predict_fn is not None:
            # all members answer every query: (K, n, ...) predictions
            self._predict = jax.jit(
                jax.vmap(predict_fn, in_axes=(0, None)),
                in_shardings=(self._repl, self._data),
                out_shardings=self._repl,
            )

    # -- lifecycle ---------------------------------------------------------

    def init(
        self,
        init_fn: Callable[[jax.Array], Any],
        hyperparams: Dict[str, Sequence[float]],
        seed: int = 0,
    ):
        """Build the member-stacked (params, opt_state).

        ``hyperparams`` maps injected optimizer hyperparameter names to
        K-length value sequences (K inferred, all equal length). Member k
        gets ``init_fn(fold_in(key(seed), k))`` — distinct inits unless the
        caller's init_fn ignores its key."""
        lengths = {k: len(v) for k, v in hyperparams.items()}
        if not lengths:
            raise ValueError("hyperparams must name at least one "
                             "K-length value sequence")
        sizes = set(lengths.values())
        if len(sizes) != 1:
            raise ValueError(f"hyperparam lengths differ: {lengths}")
        (n_members,) = sizes
        if n_members < 1:
            raise ValueError("population must have at least one member")

        base = jax.random.key(seed)
        member_params = [init_fn(jax.random.fold_in(base, k))
                         for k in range(n_members)]
        params = jax.tree.map(lambda *xs: jnp.stack(xs), *member_params)
        member_opts = []
        for k in range(n_members):
            o = self.optimizer.init(
                jax.tree.map(lambda x: x[k], params))
            member_opts.append(set_opt_hyperparams(
                o, {name: values[k] for name, values in hyperparams.items()}))
        opt_state = jax.tree.map(lambda *xs: jnp.stack(xs), *member_opts)
        return (jax.device_put(params, self._repl),
                jax.device_put(opt_state, self._repl))

    def n_members(self, params: Any) -> int:
        return int(jax.tree.leaves(params)[0].shape[0])

    def member_params(self, params: Any, k: int) -> Any:
        """Extract one member's pytree (e.g. the winner, for dumping)."""
        return jax.tree.map(lambda x: x[k], params)

    # -- training ----------------------------------------------------------

    def fit(
        self,
        params: Any,
        opt_state: Any,
        data: Tuple[np.ndarray, ...],
        epochs: int,
        batch_size: int,
        seed: int = 0,
        log: Optional[Callable[..., None]] = None,
        checkpoint_path: Optional[str] = None,
        checkpoint_every_epochs: int = 1,
    ):
        """Epoch loop, one dispatch per epoch. ``log`` receives the
        population-mean loss as ``loss`` (the ASHA rung signal: the trial
        is competitive if its population is) plus the per-member vector.
        ``checkpoint_path`` gives population trials the same mid-trial
        resume guarantee as DataParallelTrainer.fit (the stacked pytrees
        serialize through the identical flax path); a ``StopTrialEarly``
        raised by the log callback truncates training gracefully — current
        members are returned for winner selection."""
        from rafiki_tpu.sdk.jax_backend import DataParallelTrainer
        from rafiki_tpu.sdk.log import StopTrialEarly

        FIT_STATS["fit_calls"] += 1
        FIT_STATS["member_counts"].append(self.n_members(params))
        del FIT_STATS["member_counts"][:-_FIT_STATS_TAIL]
        n = len(data[0])
        fit_cap = (n // self.n_data) * self.n_data
        if fit_cap == 0:
            raise ValueError(
                f"dataset ({n}) smaller than the data axis ({self.n_data})")
        batch_size = min(max(batch_size - batch_size % self.n_data,
                             self.n_data), fit_cap)
        start_epoch = 0
        if checkpoint_path and os.path.exists(checkpoint_path):
            try:
                params, opt_state, _, start_epoch = (
                    self._restore_checkpoint(
                        checkpoint_path, params, opt_state))
                logger.info("resuming population fit from %s at epoch %d",
                            checkpoint_path, start_epoch)
            except Exception:
                # same contract as DataParallelTrainer.fit: a corrupt
                # checkpoint costs the saved epochs, never the trial
                logger.warning(
                    "population checkpoint %s is corrupt or unreadable; "
                    "restarting from scratch", checkpoint_path,
                    exc_info=True)
                start_epoch = 0
        # cross-fit device cache, same rationale as DataParallelTrainer.fit:
        # HPO trials of one job pass the same (memoized) host arrays, and
        # this trainer persists via cached_trainer — upload once
        data_dev = None
        cache_key = tuple(id(d) for d in data)
        cached = getattr(self, "_fit_data_cache", None)
        if cached is not None and cached[0] == cache_key:
            data_dev = cached[2]
        elif cached is not None:
            self._fit_data_cache = None  # stale: free before re-uploading
        base_key = jax.random.key(seed + 1)
        import time as _time
        for epoch in range(start_epoch, epochs):
            t0 = _time.time()
            if data_dev is None:
                data_dev = tuple(
                    jax.device_put(np.asarray(d), self._repl) for d in data)
                self._fit_data_cache = (cache_key, tuple(data), data_dev)
            epoch_rng = np.random.default_rng([seed, epoch])
            idx_mat = jnp.asarray(
                np.stack(list(shuffled_batches(n, batch_size, epoch_rng))),
                jnp.int32)
            epoch_key = jax.random.fold_in(base_key, epoch)
            params, opt_state, losses = self._epoch_scan(
                params, opt_state, data_dev, idx_mat, epoch_key)
            stop_early = False
            if log is not None:
                member_mean = jnp.mean(losses, axis=0)  # (K,)
                try:
                    log(loss=float(jnp.mean(member_mean)),
                        epoch=float(epoch), epoch_time=_time.time() - t0,
                        **{f"member{k}_loss": float(v)
                           for k, v in enumerate(member_mean)})
                except StopTrialEarly:
                    logger.info("population early stop after epoch %d", epoch)
                    stop_early = True
            if checkpoint_path and (
                    (epoch + 1) % max(checkpoint_every_epochs, 1) == 0
                    or epoch + 1 == epochs or stop_early):
                DataParallelTrainer._save_checkpoint(
                    checkpoint_path, params, opt_state, epoch + 1)
            if stop_early:
                break
        return params, opt_state

    def _restore_checkpoint(self, path: str, params: Any, opt_state: Any):
        """Restore stacked (params, opt_state) through the shared on-disk
        format interpreter (jax_backend.restore_checkpoint_host) — one
        checkpoint shape platform-wide.

        The member count is verified against the fit's own stack BEFORE
        anything reaches the device: flax's from-target restore takes the
        blob's array shapes at face value, so a checkpoint written with a
        different K would otherwise sail through here and die later as a
        cryptic XLA reshape inside the epoch scan. A mismatch is typed
        artifact corruption — fit()'s restore guard then logs it and
        starts fresh, the same contract as a failed checksum."""
        from rafiki_tpu.sdk.artifact import ArtifactCorruptError
        from rafiki_tpu.sdk.jax_backend import restore_checkpoint_host

        restored = restore_checkpoint_host(path, params, opt_state)
        expect = self.n_members(params)
        leaves = jax.tree.leaves(restored["params"])
        got = int(np.shape(leaves[0])[0]) if leaves else 0
        if got != expect:
            raise ArtifactCorruptError(
                path,
                f"population checkpoint stacks {got} member(s) but this "
                f"fit stacks {expect} — resuming with a different "
                f"population size is not a resume; treating the checkpoint "
                f"as corrupt (fresh start)")
        params = jax.device_put(restored["params"], self._repl)
        opt_state = jax.device_put(restored["opt_state"], self._repl)
        return params, opt_state, None, int(restored["epoch"])

    # -- evaluation --------------------------------------------------------

    def member_scores(
        self,
        params: Any,
        x: np.ndarray,
        y: np.ndarray,
        batch_size: int = 256,
    ) -> np.ndarray:
        """Classification accuracy per member over (x, y) — the
        winner-selection signal. Chunked like predict_batched; remainder
        chunks are evaluated unpadded (population models are small, a few
        extra compiles beat masking complexity here)."""
        assert self.predict_fn is not None
        k = self.n_members(params)
        correct = np.zeros((k,), np.int64)
        batch_size = max(batch_size - batch_size % self.n_data, self.n_data)
        for i in range(0, len(x), batch_size):
            chunk = np.asarray(x[i:i + batch_size])
            n_real = len(chunk)
            pad = (-n_real) % self.n_data  # data axis needs even shards
            if pad:
                chunk = np.concatenate(
                    [chunk, np.zeros((pad,) + chunk.shape[1:], chunk.dtype)])
            dev = jax.device_put(chunk, self._data)
            probs = self._predict(params, dev)           # (K, n, classes)
            pred = np.asarray(jnp.argmax(probs, axis=-1))[:, :n_real]
            correct += (pred == np.asarray(y[i:i + n_real])[None, :]).sum(
                axis=1)
        return correct / float(len(x))
