"""Weight-only int8 quantization for the serving path.

Small-batch serving on TPU is weight-bandwidth-bound: each predict batch
streams every kernel out of HBM while the MXU idles. Storing kernels as
int8 with per-output-channel scales halves that traffic (f32 masters ->
1 byte + one f32 scale per channel); the dequantize happens INSIDE the
jitted predict, where XLA fuses it into the consuming matmul/conv, so
activations and accumulation keep their usual dtype and only the
weight-side memory format changes. On v5e the int8 path also unlocks the
2x int8 MXU rate when XLA chooses to use it; correctness is what this
module guarantees (per-channel symmetric round-to-nearest, max |error|
scale/2 per weight), and is CPU-verifiable — the bandwidth win is a TPU
property of the format.

The reference has no serving quantization story at all; this is a
TPU-first extra riding the DataParallelTrainer predict seam
(sdk/jax_backend.py): ``DataParallelTrainer(..., serve_int8=True)`` or
``RAFIKI_SERVE_INT8=1`` for any SDK-trainer template. Note the env
switch also applies to trial-time ``evaluate`` — deliberate: trials are
then SELECTED by the accuracy they will actually serve.

RETIRED FROM THE DEFAULTS (r8): the official bench measured
``int8_unloaded_speedup = 0.805`` — a slowdown — on the bench CNN's
matmul shapes (VERDICT r5): those kernels are small enough that the
in-graph dequantize costs more than the weight-stream saving returns.
The numerics stay correct and test-bounded, and the path remains
available for genuinely weight-bandwidth-bound models (large kernels,
batch ~1) — but ``doctor`` WARNs while ``RAFIKI_SERVE_INT8=1`` is set
and the bench phase is opt-in (``RAFIKI_BENCH_INT8=1``). See
docs/performance.md for the full account.
"""

from __future__ import annotations

import os
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np


def serve_int8_enabled() -> bool:
    return os.environ.get("RAFIKI_SERVE_INT8") == "1"


def _is_qleaf(x: Any) -> bool:
    return (isinstance(x, dict) and set(x.keys()) == {"q", "scale"}
            and getattr(x["q"], "dtype", None) == jnp.int8)


#: public name (fused-ensemble stacking walks quantized trees leaf-wise)
is_quantized_leaf = _is_qleaf


def quantize_pytree(params: Any, min_elems: int = 4096) -> Any:
    """Replace large float kernels (ndim >= 2) with
    ``{"q": int8, "scale": f32 per-last-axis-channel}``; biases, norms,
    and small leaves pass through untouched (their bytes are noise and
    their precision matters more). Symmetric round-to-nearest with the
    scale chosen so +-max maps to +-127."""

    def q(leaf):
        a = np.asarray(leaf)
        if (a.ndim < 2 or a.size < min_elems
                or not (np.issubdtype(a.dtype, np.floating)
                        or a.dtype == jnp.bfloat16)):
            return leaf
        orig_dtype = a.dtype
        a = a.astype(np.float32)
        amax = np.max(np.abs(a), axis=tuple(range(a.ndim - 1)),
                      keepdims=True)
        scale = np.maximum(amax / 127.0, 1e-12).astype(np.float32)
        qv = np.clip(np.rint(a / scale), -127, 127).astype(np.int8)
        # the scale carries the SOURCE dtype, so dequant reconstructs
        # exactly the dtype the model computed with (a bf16 kernel must
        # not come back f32 and silently promote the activation matmul)
        return {"q": jnp.asarray(qv),
                "scale": jnp.asarray(scale).astype(orig_dtype)}

    return jax.tree.map(q, params)


def dequantize_pytree(qparams: Any) -> Any:
    """Inverse of :func:`quantize_pytree`; traced inside the jitted
    predict so XLA fuses the multiply into each weight's consumer and the
    int8 copy is what lives in (and streams from) HBM. Reconstructs each
    kernel in its source dtype (carried by the scale)."""

    def dq(leaf):
        if _is_qleaf(leaf):
            dtype = leaf["scale"].dtype
            return leaf["q"].astype(dtype) * leaf["scale"]
        return leaf

    return jax.tree.map(dq, qparams, is_leaf=_is_qleaf)


def quantized_bytes(qparams: Any) -> int:
    """Serving-weight footprint in bytes (the HBM-traffic claim,
    inspectable)."""
    total = 0
    for leaf in jax.tree.leaves(
            qparams, is_leaf=_is_qleaf):
        if _is_qleaf(leaf):
            total += leaf["q"].size + leaf["scale"].size * 4
        elif hasattr(leaf, "nbytes"):
            total += leaf.nbytes
    return total
