"""Durable artifact I/O: atomic writes + checksummed framing.

Trial params and mid-trial checkpoints are the only state that outlives a
worker process, and both used to be written with a bare ``open().write``
(params) or an un-checksummed tmp+rename (checkpoints). A torn or
bit-rotten file then surfaced as a msgpack deserialize traceback deep
inside a serving worker or a client download — long after the damage, with
no hint of the cause (the reference had the same gap: pickled params on a
shared volume, reference rafiki/worker/train.py:177-183).

This module is the single place artifact durability lives:

- :func:`atomic_write_bytes` — tmp file in the target directory, flush +
  fsync, ``os.replace``: a crash mid-write leaves the old file (or
  nothing), never a torn one;
- :func:`wrap`/:func:`unwrap` — a small checksummed frame (magic +
  version + CRC32 + payload length) so damage is detected AT READ TIME
  and reported as the typed :class:`ArtifactCorruptError` instead of a
  deserialize traceback. Files written before this frame existed carry no
  magic and pass through unchanged (legacy compatibility: readers sniff).

The magic can never collide with a legacy artifact: both params and
checkpoints are msgpack maps, whose first byte is a fixmap/map16 tag
(0x80-0x8f, 0xde/0xdf) — never ASCII ``R``.
"""

from __future__ import annotations

import os
import struct
import tempfile
import zlib

#: frame layout: magic(4) | version(1) | crc32(4, BE) | payload_len(8, BE)
MAGIC = b"RFKA"
VERSION = 1
_HEADER = struct.Struct(">4sBIQ")
HEADER_SIZE = _HEADER.size


class ArtifactCorruptError(Exception):
    """A checksummed artifact failed verification (truncated, bit-rotten,
    or half-written by a crashed process). Carries the offending path so
    doors can surface a clean, typed error."""

    def __init__(self, path: str, detail: str):
        super().__init__(f"artifact {path!r} is corrupt: {detail}")
        self.path = path
        self.detail = detail


def wrap(payload: bytes) -> bytes:
    """Frame ``payload`` with the checksummed header."""
    return _HEADER.pack(MAGIC, VERSION,
                        zlib.crc32(payload) & 0xFFFFFFFF,
                        len(payload)) + payload


def unwrap(data: bytes, path: str = "<bytes>") -> bytes:
    """Verify and strip the frame. Un-framed data (legacy artifacts)
    passes through unchanged — the downstream deserializer keeps owning
    that case. A non-empty strict prefix of the magic IS corruption (a
    framed file truncated inside the magic): legacy msgpack artifacts can
    never start with ASCII ``R``, so the prefix is provably not legacy."""
    if len(data) < len(MAGIC):
        if data and MAGIC.startswith(data):
            raise ArtifactCorruptError(
                path, f"truncated inside the magic ({len(data)} bytes)")
        return data
    if not data.startswith(MAGIC):
        return data
    if len(data) < HEADER_SIZE:
        raise ArtifactCorruptError(
            path, f"truncated inside the header ({len(data)} bytes)")
    magic, version, crc, length = _HEADER.unpack_from(data)
    payload = data[HEADER_SIZE:]
    if version != VERSION:
        raise ArtifactCorruptError(
            path, f"unknown artifact frame version {version}")
    if len(payload) != length:
        raise ArtifactCorruptError(
            path, f"payload is {len(payload)} bytes, header says {length} "
                  "(truncated or half-written)")
    if zlib.crc32(payload) & 0xFFFFFFFF != crc:
        raise ArtifactCorruptError(path, "checksum mismatch (bit rot or "
                                         "torn write)")
    return payload


def atomic_write_bytes(path: str, data: bytes,
                       mode: int | None = None) -> None:
    """Write ``data`` to ``path`` via tmp + fsync + rename. Readers only
    ever observe the previous complete file or the new complete file."""
    directory = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(dir=directory,
                               prefix=os.path.basename(path) + ".",
                               suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        if mode is not None:
            os.chmod(tmp, mode)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise
    # fsync the directory so the rename itself survives a host crash;
    # best-effort — not every filesystem supports directory fds
    try:
        dfd = os.open(directory, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:
        pass


def write_artifact(path: str, payload: bytes,
                   mode: int | None = None) -> None:
    """Atomically persist ``payload`` inside a checksummed frame."""
    atomic_write_bytes(path, wrap(payload), mode=mode)


def read_artifact(path: str) -> bytes:
    """Read and verify an artifact file; raises :class:`ArtifactCorruptError`
    on checksum/length damage, passes legacy (un-framed) files through."""
    with open(path, "rb") as f:
        return unwrap(f.read(), path=path)
