"""Sandbox child: the untrusted half of a trial (see sdk/sandbox.py).

Reads one setup JSON line on stdin, locks itself down (rlimits, cwd
jail, uid drop when launched by a root worker), then runs the model
template's train -> evaluate -> dump_parameters cycle, streaming logger
lines as frames on stdout and finishing with a done/err frame. A
``STOP`` line on stdin (the worker's mid-trial verdict) flips a flag the
logger's stop-check reads — the next ``log()`` raises StopTrialEarly,
identical to the in-process wiring (worker/train.py _install_stop_check).

Isolation happens HERE, in the child, before any untrusted byte is
imported; the parent only chooses the policy. Frames are written before
the uid drop could matter: stdout/stderr are inherited pipes, writable
regardless of uid.
"""

from __future__ import annotations

import base64
import json
import os
import resource
import sys
import threading
import traceback


# The protocol channel is a PRIVATE dup of the original stdout fd,
# claimed before any untrusted code runs (_claim_protocol_channel): model
# prints — Python-level or C-level fd-1 writes — can then never be read
# as protocol frames (the desync class the parent-side filters only
# mitigate). Until claimed, frames go to plain stdout (e.g. lockdown
# errors).
_PROTO = sys.stdout


def _emit(frame: dict) -> None:
    # shared wire convention: numpy converts at any depth (a model's
    # predictions may nest arrays/scalars inside dicts/lists)
    from rafiki_tpu.utils.jsonutil import dumps

    _PROTO.write(dumps(frame) + "\n")
    _PROTO.flush()


class _PrintsToLogFrames:
    """sys.stdout replacement: model print() output becomes MESSAGE log
    frames on the protocol channel, line-buffered."""

    def __init__(self) -> None:
        self._buf = ""

    def write(self, text: str) -> int:
        import time

        self._buf += text
        while "\n" in self._buf:
            line, self._buf = self._buf.split("\n", 1)
            if line:
                _emit({"t": "log", "line": json.dumps({
                    "type": "MESSAGE", "message": line,
                    "time": time.time()})})
        return len(text)

    def flush(self) -> None:
        pass

    def isatty(self) -> bool:
        return False


def _claim_protocol_channel() -> None:
    """Make fd 1 unusable for protocol corruption: the harness keeps a
    private dup for frames, raw fd-1 writes land in stderr (drained by
    the parent), and Python-level prints become log frames."""
    global _PROTO

    _PROTO = os.fdopen(os.dup(1), "w")
    os.dup2(2, 1)
    sys.stdout = _PrintsToLogFrames()


def _unshare_netns() -> None:
    """Detach from the host network namespace (opt-in,
    RAFIKI_SANDBOX_NETNS=1): the child keeps only a down loopback, so it
    cannot reach the admin/agent control plane or dial out at all. Must
    run before the uid drop (needs CAP_SYS_ADMIN); incompatible with
    trials that use the TPU tunnel (which needs sockets)."""
    import ctypes

    CLONE_NEWNET = 0x40000000
    libc = ctypes.CDLL(None, use_errno=True)
    if libc.unshare(CLONE_NEWNET) != 0:
        err = ctypes.get_errno()
        raise OSError(err, "unshare(CLONE_NEWNET): " + os.strerror(err))


def _no_new_privs() -> None:
    """prctl(PR_SET_NO_NEW_PRIVS): execve of setuid/setcap binaries can
    never re-escalate this process tree. Best-effort (old kernels)."""
    import ctypes

    try:
        ctypes.CDLL(None, use_errno=True).prctl(38, 1, 0, 0, 0)
    # lint: absorb(prctl hardening is best-effort on old kernels)
    except Exception:
        pass


def _lockdown(setup: dict) -> None:
    resource.setrlimit(resource.RLIMIT_CORE, (0, 0))
    nofile = int(setup.get("nofile") or 0)
    if nofile:
        resource.setrlimit(resource.RLIMIT_NOFILE, (nofile, nofile))
    mem_mb = int(setup.get("mem_mb") or 0)
    if mem_mb:
        cap = mem_mb << 20
        resource.setrlimit(resource.RLIMIT_AS, (cap, cap))
    os.chdir(setup["jail_dir"])
    drop_uid = setup.get("drop_uid")
    if setup.get("netns") and os.geteuid() != 0:
        # fail LOUDLY: silently skipping would leave the operator
        # believing loopback is unreachable when it isn't
        raise PermissionError(
            "RAFIKI_SANDBOX_NETNS=1 requires a root worker "
            "(unshare(CLONE_NEWNET) needs CAP_SYS_ADMIN)")
    if os.geteuid() == 0:
        if setup.get("netns"):
            _unshare_netns()
        if drop_uid:
            # FULL credential drop: supplementary groups cleared, gid
            # dropped to the sandbox gid (65534 by default — gid 0 is
            # retained only when the operator sets
            # RAFIKI_SANDBOX_KEEP_GID0=1 for deployments whose TPU
            # device nodes are group-0 gated), then the per-trial uid.
            # Group-root files (0640 root:root) and sibling trials'
            # 0700 jails are unreachable; world-readable code (repo,
            # venv, stdlib) stays importable — the protection boundary
            # of the threat model in sdk/sandbox.py.
            os.setgroups([])
            os.setgid(int(setup.get("drop_gid", 65534)))
            os.setuid(int(drop_uid))
    _no_new_privs()


def main() -> int:
    setup = json.loads(sys.stdin.readline())
    try:
        _lockdown(setup)
    # lint: absorb(the err frame carries the failure to the parent as INFRA)
    except Exception:
        # where=lockdown: the HARNESS failed, not the template — the
        # parent classifies this INFRA (retryable), never USER
        _emit({"t": "err", "error": "sandbox lockdown failed",
               "where": "lockdown",
               "traceback": traceback.format_exc()})
        return 3

    _claim_protocol_channel()

    if setup.get("mode") == "serve":
        return _serve(setup)

    stop_flag = threading.Event()

    def stdin_watcher() -> None:
        for line in sys.stdin:
            if line.strip() == "STOP":
                stop_flag.set()

    threading.Thread(target=stdin_watcher, daemon=True).start()

    try:
        from rafiki_tpu.sdk.log import ModelLogger, StopTrialEarly
        from rafiki_tpu.sdk.model import load_model_class
        from rafiki_tpu.sdk.params import dump_params

        clazz = load_model_class(
            base64.b64decode(setup["model_b64"]), setup["model_class"])
        model = clazz(**setup["knobs"])
        model_logger = ModelLogger()
        model_logger.set_sink(lambda line: _emit({"t": "log", "line": line}))
        model_logger.set_stop_check(lambda metrics: stop_flag.is_set())
        model.logger = model_logger
        model.checkpoint_path = os.path.join(
            setup["jail_dir"], "trial.ckpt")
        try:
            try:
                model.train(setup["train_uri"])
            except StopTrialEarly:
                model_logger.log("trial stopped early by scheduler")
            model_logger.set_stop_check(None)
            score = float(model.evaluate(setup["test_uri"]))
            params_b64 = base64.b64encode(
                dump_params(model.dump_parameters())).decode()
        finally:
            model.destroy()
        _emit({"t": "done", "score": score, "params_b64": params_b64})
        return 0
    # lint: absorb(the err frame carries the failure to the parent for fault classification)
    except Exception as e:
        # error_type lets the parent map the failure into the fault
        # taxonomy (MemoryError -> MEM, everything else -> USER)
        # without parsing the message
        _emit({"t": "err", "error": f"{type(e).__name__}: {e}",
               "where": "model", "error_type": type(e).__name__,
               "traceback": traceback.format_exc()[-4000:]})
        return 1


def _serve(setup: dict) -> int:
    """Serving mode: load the template + TRUSTED-side-supplied params,
    warm up, then answer predict frames until stdin closes. One frame in
    ({"op":"predict","queries":[...]}), one frame out ({"t":"preds"} or
    {"t":"err"}) — a per-query error fails only that batch, never the
    loop (parity with worker/inference.py's in-process error handling)."""
    try:
        from rafiki_tpu.sdk.model import load_model_class
        from rafiki_tpu.sdk.params import load_params

        clazz = load_model_class(
            base64.b64decode(setup["model_b64"]), setup["model_class"])
        model = clazz(**setup["knobs"])
        model.load_parameters(
            load_params(base64.b64decode(setup["params_b64"])))
        try:
            model.warm_up()
        # lint: absorb(warm_up is optional; the failure is logged to the trial log frame)
        except Exception:
            _emit({"t": "log", "line": json.dumps({
                "type": "MESSAGE",
                "message": "warm_up failed in sandbox (serving anyway)",
                "time": 0})})
        _emit({"t": "ready"})
    # lint: absorb(warm_up is optional; the failure is logged to the trial log frame)
    except Exception as e:
        _emit({"t": "err", "error": f"{type(e).__name__}: {e}",
               "traceback": traceback.format_exc()[-4000:]})
        return 1
    try:
        for line in sys.stdin:
            try:
                frame = json.loads(line)
            except json.JSONDecodeError:
                continue
            if frame.get("op") == "exit":
                break
            if frame.get("op") != "predict":
                continue
            try:
                preds = model.predict(frame["queries"])
                _emit({"t": "preds", "predictions": list(preds)})
            # lint: absorb(per-request err frame; the serving loop must survive template bugs)
            except Exception as e:
                _emit({"t": "err", "error": f"{type(e).__name__}: {e}",
                       "traceback": traceback.format_exc()[-2000:]})
        return 0
    finally:
        model.destroy()


if __name__ == "__main__":
    sys.exit(main())
