"""Deployment health check: ``python -m rafiki_tpu.doctor``.

One bounded pass over everything a rafiki_tpu deployment depends on,
printing a PASS/WARN/FAIL line per check and exiting non-zero on FAIL.
The accelerator check goes through the bounded subprocess probe
(utils/backend_probe.py), so a wedged TPU tunnel costs one timeout here
— never a hang (the failure mode that motivated the probe; this command
is the operator's way to see it).

The reference's closest analogue was docker/compose healthchecks plus
reading container logs; a process-native stack gets a first-class
doctor instead.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
from typing import Any, Callable, Dict, List, Optional, Tuple

PASS, WARN, FAIL = "PASS", "WARN", "FAIL"

Check = Tuple[str, str, str]  # (name, status, detail)


def check_backend(timeout_s: float = 60.0) -> Check:
    from rafiki_tpu.utils.backend_probe import probe_device_count

    n, err = probe_device_count(timeout_s=timeout_s)
    if n >= 1:
        return ("accelerator", PASS, f"{n} device(s) visible")
    return ("accelerator", WARN,
            f"live backend unusable ({err}) — CPU fallbacks will engage")


def check_workdir() -> Check:
    from rafiki_tpu import config

    wd = config.WORKDIR
    try:
        os.makedirs(wd, exist_ok=True)
        probe = tempfile.NamedTemporaryFile(dir=wd, delete=True)
        probe.close()
    except OSError as e:
        return ("workdir", FAIL, f"{wd} not writable: {e}")
    return ("workdir", PASS, wd)


def check_store() -> Check:
    from rafiki_tpu import config
    from rafiki_tpu.db.database import Database

    target = str(config.DB_PATH)
    try:
        if target.startswith(("postgresql://", "postgres://")):
            db = Database(target)  # connects (or raises) against the server
            label = target
        elif os.path.exists(target):
            # exercise the REAL store the server will open (same WAL
            # sidecar behavior the server has) — a corrupt or
            # wrong-owner file must fail here, not at boot
            db = Database(target)
            label = target
        else:
            db = Database(":memory:")  # engine sanity; store not created yet
            label = f"{target} (not created yet; embedded engine ok)"
        db.get_users()
        db.close()
    # lint: absorb(doctor checks must never crash; the failure becomes the check detail)
    except Exception as e:
        return ("metadata store", FAIL, f"{target}: {type(e).__name__}: {e}")
    return ("metadata store", PASS, label)


def check_shm_broker() -> Check:
    """Native build, configured ring size, and which wire format shm
    traffic will actually ride — an operator who set RAFIKI_BROKER=shm
    for the binary data plane must SEE it when framing silently fell
    back to JSON (kill-switch, or a mixed-version fleet)."""
    shm_selected = os.environ.get("RAFIKI_BROKER") == "shm"
    try:
        from rafiki_tpu.cache import wire
        from rafiki_tpu.native.shm_queue import available, default_capacity

        if not available():
            if shm_selected:
                return ("shm data plane", WARN,
                        "RAFIKI_BROKER=shm but the native shmqueue did "
                        "not build — falling back to the in-process "
                        "broker (process placement/serving agents need "
                        "the native library)")
            return ("shm data plane", WARN,
                    "native shmqueue unavailable — in-process broker only "
                    "(process placement/serving agents need it)")
        from rafiki_tpu import config

        ring = default_capacity()
        if not wire.binary_enabled():
            return ("shm data plane", WARN,
                    f"binary wire framing DISABLED (RAFIKI_WIRE_BINARY=0): "
                    f"shm/relay traffic rides JSON float text — ~an order "
                    f"of magnitude more serialization CPU per dense query; "
                    f"re-enable once every peer speaks wire v{wire.VERSION} "
                    f"(ring {ring} B)")
        if ring < 4 * (1 << 20) and int(config.PREDICT_QUEUE_DEPTH) > 0:
            detail = (f"native queue library loads; ring {ring} B "
                      f"(RAFIKI_SHM_RING_BYTES), binary wire v{wire.VERSION}"
                      " — batched binary frames are larger than per-query "
                      "JSON; watch ring_used_bytes_hw in serving stats")
        else:
            detail = (f"native queue library loads; ring {ring} B "
                      f"(RAFIKI_SHM_RING_BYTES), binary wire "
                      f"v{wire.VERSION}")
    # lint: absorb(doctor checks must never crash; the failure becomes the check detail)
    except Exception as e:
        return ("shm data plane", WARN, f"{type(e).__name__}: {e}")
    return ("shm data plane", PASS, detail)


def check_sandbox() -> Check:
    from rafiki_tpu.sdk.sandbox import (_uid_range, sandbox_enabled,
                                        sandbox_gid, uid_for_jail)

    if not sandbox_enabled():
        return ("model sandbox", WARN,
                "RAFIKI_SANDBOX unset — uploaded model code runs with "
                "worker privileges")
    if uid_for_jail("doctor-probe") is None:
        return ("model sandbox", WARN,
                "enabled, but worker is not root: uid-drop layer inactive "
                "(env scrub + jail + rlimits still apply)")
    gid = sandbox_gid()
    note = " (gid 0 RETAINED — RAFIKI_SANDBOX_KEEP_GID0)" if gid == 0 else ""
    if _uid_range()[1] <= 0:
        return ("model sandbox", WARN,
                "enabled, but RAFIKI_SANDBOX_UID_RANGE=0: ONE shared "
                "sandbox uid — concurrent trials are not isolated from "
                f"each other, gid {gid}{note}")
    return ("model sandbox", PASS,
            f"enabled, per-trial uid drop, gid {gid}{note}")


def check_chaos() -> Check:
    from rafiki_tpu.utils import chaos

    if not os.environ.get(chaos.ENV_VAR):
        return ("fault injection", PASS, "off (RAFIKI_CHAOS unset)")
    if chaos.enabled():
        # loud on purpose: chaos left on after a failover drill makes a
        # healthy fleet look like it's dying
        return ("fault injection", WARN,
                f"RAFIKI_CHAOS is ACTIVE: "
                f"{os.environ[chaos.ENV_VAR]!r} — requests are being "
                "dropped/delayed/errored on schedule")
    return ("fault injection", WARN,
            f"RAFIKI_CHAOS set but unparseable (ignored): "
            f"{os.environ[chaos.ENV_VAR]!r}")


def check_overload_knobs() -> Check:
    """Serving-plane overload control (docs/failure-model.md "Overload
    faults"): the knobs must describe a coherent pipeline — a queue cap
    below the batch size silently caps batch occupancy, and an uncapped
    queue plus an uncapped door disables shedding entirely."""
    from rafiki_tpu import config

    depth = int(config.PREDICT_QUEUE_DEPTH)
    inflight = int(config.PREDICT_MAX_INFLIGHT)
    hedge = int(config.PREDICT_HEDGE_SUPPRESS_DEPTH)
    batch = int(config.PREDICT_MAX_BATCH_SIZE)
    if 0 < depth < batch:
        # serving still works (take_batch dispatches whatever is queued);
        # batches just can't reach max occupancy, and single requests
        # above the cap are refused outright
        return ("overload control", WARN,
                f"RAFIKI_PREDICT_QUEUE_DEPTH={depth} is below "
                f"PREDICT_MAX_BATCH_SIZE={batch}: batches cap at {depth} "
                f"queries and requests above {depth} queries are refused "
                "— intended?")
    if depth <= 0 and inflight <= 0:
        return ("overload control", WARN,
                "queue depth AND in-flight caps disabled "
                "(RAFIKI_PREDICT_QUEUE_DEPTH=0, "
                "RAFIKI_PREDICT_MAX_INFLIGHT=0): overload will queue "
                "unboundedly instead of shedding 429/503")
    detail = (f"queue depth {depth or 'uncapped'}, in-flight "
              f"{inflight or 'uncapped'}, hedge suppression at "
              f"{hedge or 'off'}")
    return ("overload control", PASS, detail)


def check_recovery() -> Check:
    """Control-plane crash recovery (docs/failure-model.md): flag
    non-terminal jobs with zero live services — the signature of a dead
    admin that has not been restarted to reconcile them — report the last
    reconcile outcome/duration, and WARN when the RAFIKI_RECOVER_* knobs
    disable adoption (restarts will fence surviving workers instead)."""
    from rafiki_tpu import config

    notes = []
    if not config.RECOVER_ADOPT:
        notes.append("RAFIKI_RECOVER_ADOPT=0: restarts FENCE surviving "
                     "workers instead of adopting them")
    # last reconcile outcome, persisted by admin/recovery.py
    last = None
    try:
        from rafiki_tpu.admin.recovery import report_path

        with open(report_path()) as f:
            last = json.load(f)
    except (OSError, ValueError):
        pass
    failed = bool(last and last.get("failed"))
    if last is not None:
        notes.append(
            f"last reconcile{' ABORTED' if failed else ''}: "
            f"{last.get('duration_s', '?')}s — "
            f"{last.get('adopted', 0)} adopted, "
            f"{last.get('rescheduled', 0)} rescheduled, "
            f"{last.get('fenced', 0)} fenced, "
            f"{last.get('errored', 0)} errored"
            + (f" ({last.get('error')})" if failed else ""))
    target = str(config.DB_PATH)
    orphaned = 0
    is_url = target.startswith(("postgresql://", "postgres://"))
    if is_url or os.path.exists(target):
        try:
            from rafiki_tpu.db.database import Database

            import time as _time

            # only jobs older than a deploy takes: a LIVE admin mid-deploy
            # legitimately has a STARTED job whose worker rows don't exist
            # yet, and that must not read as "restart your healthy admin"
            min_age_s = 120.0
            now = _time.time()
            db = Database(target)
            try:
                jobs = db.get_train_jobs_by_statuses(
                    ["STARTED", "RUNNING"])
                inf_jobs = db.get_inference_jobs_by_statuses(
                    ["STARTED", "RUNNING"])
                live_services = {
                    s["id"] for s in db.get_services(
                        statuses=["STARTED", "DEPLOYING", "RUNNING"])}
                for j in jobs + inf_jobs:
                    if now - (j.get("datetime_started") or now) < min_age_s:
                        continue
                    get_workers = (
                        db.get_workers_of_train_job
                        if "app" in j else db.get_workers_of_inference_job)
                    sids = {w["service_id"] for w in get_workers(j["id"])}
                    if not (sids & live_services):
                        orphaned += 1
            finally:
                db.close()
        # lint: absorb(doctor checks must never crash; the failure becomes the check detail)
        except Exception as e:
            return ("crash recovery", WARN,
                    f"could not scan {target}: {type(e).__name__}: {e}")
    if orphaned:
        notes.insert(0, f"{orphaned} non-terminal job(s) with ZERO live "
                        "services — orphaned by a dead admin; restarting "
                        "the admin reconciles them (adopt/reschedule/"
                        "fence)")
        return ("crash recovery", WARN, "; ".join(notes))
    if failed or not config.RECOVER_ADOPT:
        return ("crash recovery", WARN, "; ".join(notes))
    return ("crash recovery", PASS,
            "; ".join(notes) if notes else
            "no orphaned jobs; adoption enabled")


def check_rollouts() -> Check:
    """Safe live rollouts (docs/failure-model.md "Rollout faults"): WARN
    on service rows stuck in DEPLOYING longer than
    SERVICE_DEPLOY_TIMEOUT_S — a wedged placement nothing is waiting on
    (the deploy path marks rows DEPLOYING while it waits; a live admin's
    wait either resolves them or tears them down inside the timeout) —
    and on rolled-back rollouts no operator has acknowledged (a rollback
    is the platform saying a version was bad; somebody should look
    before the next update ships the same regression)."""
    from rafiki_tpu import config
    from rafiki_tpu.constants import RolloutPhase

    notes = []
    warn = False
    live_rollouts = 0
    target = str(config.DB_PATH)
    is_url = target.startswith(("postgresql://", "postgres://"))
    if is_url or os.path.exists(target):
        try:
            import time as _time

            from rafiki_tpu.db.database import Database

            timeout_s = float(config.SERVICE_DEPLOY_TIMEOUT_S)
            now = _time.time()
            db = Database(target)
            try:
                wedged = [
                    s for s in db.get_services(status="DEPLOYING")
                    if now - (s.get("datetime_started") or now) > timeout_s]
                if wedged:
                    warn = True
                    notes.append(
                        f"{len(wedged)} service row(s) stuck in DEPLOYING "
                        f"longer than SERVICE_DEPLOY_TIMEOUT_S="
                        f"{timeout_s:g}s: "
                        + ", ".join(s["id"][:8] for s in wedged[:5])
                        + (" …" if len(wedged) > 5 else "")
                        + " — a wedged deploy; restarting the admin "
                        "reconciles them")
                unacked = [
                    r for r in db.get_rollouts_by_phases(
                        [RolloutPhase.ROLLED_BACK])
                    if not r["operator_ack"]]
                if unacked:
                    warn = True
                    notes.append(
                        f"{len(unacked)} rolled-back rollout(s) with no "
                        "operator ack: "
                        + "; ".join(
                            f"job {r['inference_job_id'][:8]} "
                            f"({(r.get('reason') or 'no reason')[:60]})"
                            for r in unacked[:3])
                        + (" …" if len(unacked) > 3 else "")
                        + " — review, then POST .../rollout/ack "
                        "(Client.ack_rollout)")
                live_rollouts = len(db.get_rollouts_by_phases(
                    list(RolloutPhase.LIVE)))
            finally:
                db.close()
        # lint: absorb(doctor checks must never crash; the failure becomes the check detail)
        except Exception as e:
            return ("rollouts", WARN,
                    f"could not scan {target}: {type(e).__name__}: {e}")
    if warn:
        return ("rollouts", WARN, "; ".join(notes))
    detail = (f"no wedged deploys, no unacked rollbacks; "
              f"{live_rollouts} rollout(s) in flight, canary fraction "
              f"{float(config.ROLLOUT_CANARY_FRACTION):g}, judge window "
              f"{float(config.ROLLOUT_JUDGE_WINDOW_S):g}s")
    return ("rollouts", PASS, detail)


def check_drift() -> Check:
    """The drift closed loop (docs/failure-model.md "Model drift
    faults"): WARN when the loop is enabled but can't work — no digest
    stream will flow (metrics disabled hides the loop entirely; a
    WATCHING row that never froze a baseline means no samples reach the
    monitor), retrain budget 0 (monitor-only: verdicts fire, nothing is
    ever retrained), or a baseline window shorter than the monitor
    window (the reference population is a subset of every comparison
    window, so novelty can never clear the threshold) — and on loop
    rows that need an operator: a PARKED loop waiting for an ack, or
    ≥2 consecutive auto-retrained candidates rolled back (the loop is
    flapping — raise RAFIKI_DRIFT_COOLDOWN_S or fix the training
    signal)."""
    from rafiki_tpu import config
    from rafiki_tpu.constants import DriftPhase
    from rafiki_tpu.utils import metrics as _metrics

    enabled = bool(config.DRIFT)
    notes = []
    warn = False
    if enabled:
        if not _metrics.metrics_enabled():
            warn = True
            notes.append(
                "RAFIKI_DRIFT=1 with RAFIKI_METRICS=0: the loop runs "
                "but every rafiki_drift_* signal is a no-op — its "
                "verdicts and retrains are invisible to operators")
        if int(config.DRIFT_RETRAIN_BUDGET) <= 0:
            warn = True
            notes.append(
                "RAFIKI_DRIFT_RETRAIN_BUDGET<=0: monitor-only mode — "
                "drift events fire but nothing is ever retrained; set "
                "a positive trial budget to close the loop")
        if float(config.DRIFT_BASELINE_WINDOW_S) \
                < float(config.DRIFT_WINDOW_S):
            warn = True
            notes.append(
                f"RAFIKI_DRIFT_BASELINE_WINDOW_S="
                f"{float(config.DRIFT_BASELINE_WINDOW_S):g} < "
                f"RAFIKI_DRIFT_WINDOW_S={float(config.DRIFT_WINDOW_S):g}"
                ": the frozen baseline samples a shorter horizon than "
                "every window it judges — novelty verdicts will be "
                "noise; make the baseline window at least the monitor "
                "window")
    target = str(config.DB_PATH)
    is_url = target.startswith(("postgresql://", "postgres://"))
    stale_watch = 0
    if is_url or os.path.exists(target):
        try:
            import time as _time

            from rafiki_tpu.db.database import Database

            now = _time.time()
            db = Database(target)
            try:
                rows = db.get_drift_states()
                parked = [r for r in rows
                          if r["phase"] == DriftPhase.PARKED
                          and not r["operator_ack"]]
                if parked:
                    warn = True
                    notes.append(
                        f"{len(parked)} drift loop(s) PARKED with no "
                        "operator ack: "
                        + "; ".join(
                            f"job {r['inference_job_id'][:8]} "
                            f"({(r.get('reason') or 'no reason')[:60]})"
                            for r in parked[:3])
                        + (" …" if len(parked) > 3 else "")
                        + " — review, then POST .../drift/ack "
                        "(Client.ack_drift)")
                flapping = [r for r in rows
                            if int(r.get("consecutive_rollbacks") or 0)
                            >= 2]
                if flapping:
                    warn = True
                    notes.append(
                        f"{len(flapping)} drift loop(s) with >=2 "
                        "consecutive auto-retrained candidates rolled "
                        "back: "
                        + ", ".join(f"job {r['inference_job_id'][:8]} "
                                    f"(x{r['consecutive_rollbacks']})"
                                    for r in flapping[:3])
                        + " — the loop is flapping; raise "
                        "RAFIKI_DRIFT_COOLDOWN_S (backoff already "
                        "doubles per rollback) or fix the training "
                        "signal, then .../drift/ack to clear")
                if enabled:
                    # a WATCHING row much older than the baseline window
                    # that never froze a baseline: the monitor sees no
                    # digest stream from that job's serving plane
                    horizon = max(
                        float(config.DRIFT_BASELINE_WINDOW_S),
                        float(config.DRIFT_INTERVAL_S)) * 10
                    stale_watch = sum(
                        1 for r in rows
                        if r["phase"] == DriftPhase.WATCHING
                        and r.get("baseline") is None
                        and now - float(r.get("datetime_updated") or now)
                        > horizon)
                    if stale_watch:
                        warn = True
                        notes.append(
                            f"{stale_watch} WATCHING loop(s) never froze "
                            "a baseline: no digest stream is flowing "
                            "from the serving plane (job idle, or the "
                            "admin restarted without RAFIKI_DRIFT=1)")
            finally:
                db.close()
        # lint: absorb(doctor checks must never crash; the failure becomes the check detail)
        except Exception as e:
            return ("drift loop", WARN,
                    f"could not scan {target}: {type(e).__name__}: {e}")
    if warn:
        return ("drift loop", WARN, "; ".join(notes))
    if not enabled:
        return ("drift loop", PASS,
                "disabled (RAFIKI_DRIFT=0); no parked or flapping loop "
                "rows")
    return ("drift loop", PASS,
            f"enabled: window {float(config.DRIFT_WINDOW_S):g}s, budget "
            f"{int(config.DRIFT_RETRAIN_BUDGET)} trial(s), cooldown "
            f"{float(config.DRIFT_COOLDOWN_S):g}s")


def check_trial_faults() -> Check:
    """Training-plane fault tolerance (docs/failure-model.md,
    "Training-plane faults"): WARN when infra-retry is disabled
    (RAFIKI_TRIAL_RETRY_MAX=0 — every transient fault burns a budget
    slot), when a live job's recent trials are mostly ERRORED (the
    signature of a broken template or a sick host), and list poison-knob
    signatures with enough recorded user-class faults to be quarantined
    (grouped by exact knob JSON here — the store scan has no knob
    config, so this is the conservative subset of the worker's
    unit-cube quarantine)."""
    from rafiki_tpu import config

    notes = []
    retry_disabled = int(config.TRIAL_RETRY_MAX) <= 0
    if retry_disabled:
        notes.append("RAFIKI_TRIAL_RETRY_MAX=0: transient INFRA/MEM/"
                     "STALL faults will NOT be retried — each burns a "
                     "budget slot")
    target = str(config.DB_PATH)
    is_url = target.startswith(("postgresql://", "postgres://"))
    hot_jobs = 0
    quarantined = []
    if is_url or os.path.exists(target):
        try:
            import time as _time

            from rafiki_tpu.db.database import Database
            from rafiki_tpu.worker.faults import quarantined_signatures

            recent_s = 3600.0
            now = _time.time()
            db = Database(target)
            try:
                for j in db.get_train_jobs_by_statuses(
                        ["STARTED", "RUNNING"]):
                    trials = db.get_trials_of_train_job(j["id"])
                    recent = [t for t in trials
                              if now - (t.get("datetime_started") or now)
                              < recent_s]
                    errored = [t for t in recent
                               if t["status"] == "ERRORED"]
                    if len(recent) >= 3 and \
                            len(errored) / len(recent) > 0.5:
                        hot_jobs += 1
                        kinds = db.get_trial_fault_counts_of_train_job(
                            j["id"])
                        notes.append(
                            f"job {j['id'][:8]}: {len(errored)}/"
                            f"{len(recent)} recent trials ERRORED "
                            f"(fault kinds: {kinds or 'unrecorded'})")
                    q = quarantined_signatures(
                        trials, None,
                        int(config.TRIAL_QUARANTINE_K))
                    quarantined.extend(
                        f"job {j['id'][:8]}: {sig} x{n}"
                        for sig, n in q.items())
            finally:
                db.close()
        # lint: absorb(doctor checks must never crash; the failure becomes the check detail)
        except Exception as e:
            return ("trial faults", WARN,
                    f"could not scan {target}: {type(e).__name__}: {e}")
    if quarantined:
        notes.append("quarantined knob signatures: "
                     + "; ".join(quarantined[:5])
                     + (" …" if len(quarantined) > 5 else ""))
    if hot_jobs or retry_disabled:
        return ("trial faults", WARN, "; ".join(notes))
    detail = (f"retry up to {int(config.TRIAL_RETRY_MAX)} per trial, "
              f"quarantine at {int(config.TRIAL_QUARANTINE_K)} faults, "
              f"job fail-fast at {int(config.TRIAL_FAULT_LIMIT) or 'off'}")
    if quarantined:
        return ("trial faults", PASS, detail + "; " + notes[-1])
    return ("trial faults", PASS, detail)


def check_vectorized_trials() -> Check:
    """Vectorized trial execution (docs/performance.md, "Vectorized
    trial execution"): WARN when the operator explicitly enabled
    population mode (RAFIKI_TRIAL_VMAP=1) but a live train job's
    template advertises no population capability — the worker silently
    falls back to scalar trials, and "enabled but not engaging" is
    exactly the state an operator cannot see from throughput alone. Also
    WARN when K exceeds the per-chip memory heuristic (stacked params +
    optimizer state scale linearly with K) or is too small to ever
    vectorize. The capability probe is the static analyzer's verdict on
    the uploaded template bytes (analysis/template.py — AST passes, no
    untrusted code runs inside doctor; this replaced the r8 regex-grade
    ``b"population_spec" in bytes`` source sniff)."""
    from rafiki_tpu import config

    notes = []
    warn = False
    enabled = bool(config.TRIAL_VMAP)
    k = int(config.TRIAL_VMAP_K)
    explicit = os.environ.get("RAFIKI_TRIAL_VMAP") == "1"
    k_warn = int(os.environ.get("RAFIKI_TRIAL_VMAP_K_WARN", "16"))
    if enabled and k < 2:
        warn = True
        notes.append(
            f"RAFIKI_TRIAL_VMAP_K={k} < 2: the vectorized path can never "
            "engage — every 'batch' is one trial")
    if enabled and k > k_warn:
        warn = True
        notes.append(
            f"RAFIKI_TRIAL_VMAP_K={k} exceeds the per-chip memory "
            f"heuristic ({k_warn}): K stacked (params + opt state) copies "
            "must fit HBM next to the replicated dataset — expect OOM-"
            "classed faults (templates additionally cap via "
            "PopulationSpec.max_members)")
    if explicit:
        target = str(config.DB_PATH)
        is_url = target.startswith(("postgresql://", "postgres://"))
        if is_url or os.path.exists(target):
            try:
                from rafiki_tpu.db.database import Database

                db = Database(target)
                try:
                    from rafiki_tpu.analysis import (
                        static_population_capability)

                    incapable = []
                    for j in db.get_train_jobs_by_statuses(
                            ["STARTED", "RUNNING"]):
                        for sub in db.get_sub_train_jobs_of_train_job(
                                j["id"]):
                            m = db.get_model(sub["model_id"])
                            if m and static_population_capability(
                                    m.get("model_file_bytes") or b"",
                                    m.get("model_class")) is None:
                                incapable.append(
                                    f"job {j['id'][:8]}/"
                                    f"{m.get('name', '?')}")
                    if incapable:
                        warn = True
                        notes.append(
                            "RAFIKI_TRIAL_VMAP=1 but these live jobs' "
                            "templates advertise no population capability "
                            "(silent scalar fallback): "
                            + "; ".join(incapable[:5])
                            + (" …" if len(incapable) > 5 else ""))
                finally:
                    db.close()
            # lint: absorb(doctor checks must never crash; the failure becomes the check detail)
            except Exception as e:
                notes.append(f"could not scan {target}: "
                             f"{type(e).__name__}: {e}")
    detail = (f"{'on' if enabled else 'OFF (kill switch)'}, K={k} "
              "(population-capable templates train K proposals as one "
              "vmapped program)"
              + ("; " + "; ".join(notes) if notes else ""))
    return ("vectorized trials", WARN if warn else PASS, detail)


def check_static_analysis() -> Check:
    """Upload-time template verification (docs/static-analysis.md): WARN
    when RAFIKI_VERIFY_TEMPLATES=off while train/inference jobs are live
    — the platform is accepting templates nothing has looked at — and
    list models whose rows carry no verification report (uploaded before
    the verifier, or under =off): those are exactly the templates a
    fault at trial time would "discover" the expensive way. Also WARNs
    on models whose persisted report carries error findings (an upload
    that went through under =warn)."""
    from rafiki_tpu import config
    from rafiki_tpu.analysis import verify_mode

    mode = verify_mode()
    notes = []
    warn = False
    live_jobs = 0
    unverified = []
    flagged = []
    target = str(config.DB_PATH)
    is_url = target.startswith(("postgresql://", "postgres://"))
    if is_url or os.path.exists(target):
        try:
            from rafiki_tpu.db.database import Database

            db = Database(target)
            try:
                live_jobs = len(db.get_train_jobs_by_statuses(
                    ["STARTED", "RUNNING"]))
                for m in db.get_models():
                    blob = m.get("verification")
                    if not blob:
                        unverified.append(m.get("name", m["id"][:8]))
                        continue
                    try:
                        report = json.loads(blob)
                    # lint: absorb(an unreadable report reads as unverified)
                    except ValueError:
                        unverified.append(m.get("name", m["id"][:8]))
                        continue
                    if not report.get("ok", True):
                        flagged.append(m.get("name", m["id"][:8]))
            finally:
                db.close()
        # lint: absorb(doctor checks must never crash; the failure becomes the check detail)
        except Exception as e:
            return ("static analysis", WARN,
                    f"could not scan {target}: {type(e).__name__}: {e}")
    if mode == "off" and live_jobs:
        warn = True
        notes.append(
            f"RAFIKI_VERIFY_TEMPLATES=off with {live_jobs} live train "
            "job(s): uploads are going straight to trial time unchecked")
    if unverified:
        warn = warn or mode != "off"
        notes.append(
            f"{len(unverified)} model(s) have no verification report "
            "(pre-verifier uploads or =off): "
            + ", ".join(unverified[:5])
            + (" …" if len(unverified) > 5 else "")
            + " — re-upload or dry-run via Client.verify_model")
    if flagged:
        warn = True
        notes.append(
            f"{len(flagged)} model(s) carry ERROR findings (uploaded "
            "under =warn): " + ", ".join(flagged[:5])
            + (" …" if len(flagged) > 5 else ""))
    detail = (f"mode={mode} (AST template verifier at upload; "
              "framework self-lint rides tier-1)"
              + ("; " + "; ".join(notes) if notes else ""))
    return ("static analysis", WARN if warn else PASS, detail)


def check_concurrency_lint() -> Check:
    """The whole-package concurrency analyzer (docs/static-analysis.md,
    CONC1xx/2xx/3xx): tier-1 pins the shipped tree at zero findings, but
    an operator running a locally-edited tree never sees CI — WARN when
    the INSTALLED package lints dirty, so a race or lock-order inversion
    introduced by a local patch is caught at doctor time, not in
    production."""
    try:
        from rafiki_tpu.analysis.concurrency import analyze_package

        findings = analyze_package()
    # lint: absorb(doctor checks must never crash; the failure becomes the check detail)
    except Exception as e:
        return ("concurrency lint", WARN,
                f"analyzer failed on the installed tree: "
                f"{type(e).__name__}: {e}")
    if not findings:
        return ("concurrency lint", PASS,
                "installed tree lints clean (lockset inference, "
                "lock-order cycles, atomicity — zero unannotated "
                "findings)")
    by_code: Dict[str, int] = {}
    for f in findings:
        by_code[f.code] = by_code.get(f.code, 0) + 1
    head = "; ".join(str(f) for f in findings[:3])
    return ("concurrency lint", WARN,
            f"{len(findings)} finding(s) in the installed tree "
            f"({', '.join(f'{c}x{n}' for c, n in sorted(by_code.items()))})"
            f" — local edits regressed the race gate: {head}"
            + (" …" if len(findings) > 3 else "")
            + " (fix the race or annotate the true negative; "
            "python -m rafiki_tpu.analysis --self-lint lists all)")


def check_int8_serving() -> Check:
    """int8 weight-only serving (docs/performance.md): retired from the
    default record after measuring a 0.805x SLOWDOWN on the bench matmul
    shapes (VERDICT r5) — the weight-bandwidth win it targets did not
    materialize there, and the in-graph dequantize costs real time.
    WARN whenever an operator forces it on, so nobody serves slower
    without noticing."""
    if os.environ.get("RAFIKI_SERVE_INT8") != "1":
        return ("int8 serving", PASS,
                "off (default; measured 0.805x SLOWDOWN on the bench "
                "matmul shapes, VERDICT r5 — enable only after "
                "RAFIKI_BENCH_INT8=1 shows a win on YOUR shapes)")
    return ("int8 serving", WARN,
            "RAFIKI_SERVE_INT8=1: this path measured a 0.805x SLOWDOWN "
            "on the bench matmul shapes (VERDICT r5) — it also "
            "quantizes trial-time evaluate. Re-verify with "
            "RAFIKI_BENCH_INT8=1 (int8_unloaded_speedup > 1) or unset it; "
            "docs/performance.md explains when int8 can still win")


#: paged-KV pool capacity (block_tokens x pool_blocks, in tokens) past
#: which the doctor reads "this pool will not fit beside the model in
#: chip HBM" — the paged twin of the ~64-slot ring heuristic (64 slots of
#: a 4k context).
PAGED_POOL_TOKEN_HEURISTIC = 64 * 4096


def check_generative_serving() -> Check:
    """Generative serving (docs/serving-generation.md): WARN when the
    slot table is misconfigured against the chip-memory heuristic (every
    slot preallocates a max_context-long KV ring — slots x context is the
    cache's token capacity, and past ~64 slots a worker is trading HBM
    for queueing the door could do better), when the PAGED layout is
    degenerate (block size < 8 amplifies table/gather overhead, past the
    2048-token ceiling a "page" is bigger than most contexts and paging
    buys nothing) or its pool capacity exceeds the chip-memory heuristic,
    when the prefix cache is disabled while the shareable-prefix counter
    shows shared-prompt traffic, when the stall detector is disabled, and
    when live TEXT_GENERATION jobs have no reachable streaming door (the
    chunked /generate route only exists on the dedicated per-job
    predictor port)."""
    from rafiki_tpu import config

    notes = []
    warn = False
    slots = int(config.GEN_MAX_SLOTS)
    if slots < 1:
        warn = True
        notes.append(f"RAFIKI_GEN_MAX_SLOTS={slots}: generation workers "
                     "clamp to 1 slot — continuous batching is OFF")
    elif slots > 64:
        warn = True
        notes.append(
            f"RAFIKI_GEN_MAX_SLOTS={slots} is past the memory heuristic "
            "(~64): each slot preallocates a full max_context KV ring in "
            "HBM and decode advances EVERY slot each step — prefer more "
            "replicas over a wider table")
    block_tokens = int(config.GEN_KV_BLOCK_TOKENS)
    pool_blocks = int(config.GEN_KV_POOL_BLOCKS)
    if bool(config.GEN_KV_PAGED):
        if block_tokens < 8 or block_tokens > 2048:
            warn = True
            notes.append(
                f"RAFIKI_GEN_KV_BLOCK_TOKENS={block_tokens} is degenerate "
                "(sane range 8..2048): tiny pages spend the pool on block-"
                "table overhead, giant pages degrade to one-ring-per-slot")
        if pool_blocks and block_tokens * pool_blocks \
                > PAGED_POOL_TOKEN_HEURISTIC:
            warn = True
            notes.append(
                f"RAFIKI_GEN_KV_BLOCK_TOKENS={block_tokens} x "
                f"RAFIKI_GEN_KV_POOL_BLOCKS={pool_blocks} = "
                f"{block_tokens * pool_blocks} tokens of K/V exceeds the "
                f"chip-memory heuristic ({PAGED_POOL_TOKEN_HEURISTIC}): "
                "the pool competes with the model for HBM — prefer more "
                "replicas over a deeper pool")
        if not bool(config.GEN_PREFIX_CACHE):
            try:
                from rafiki_tpu.utils.metrics import REGISTRY

                shareable = REGISTRY.get(
                    "rafiki_gen_prefix_shareable_total")
                shared_n = shareable.value() if shareable else 0
            # lint: absorb(telemetry probe is best-effort inside a doctor check)
            except Exception:
                shared_n = 0
            if shared_n > 0:
                warn = True
                notes.append(
                    f"RAFIKI_GEN_PREFIX_CACHE=0 while "
                    f"{int(shared_n)} admissions shared a prompt prefix "
                    "(rafiki_gen_prefix_shareable_total): these streams "
                    "are re-paying prefill the cache would serve free")
    if float(config.GEN_STREAM_TIMEOUT_S) <= 0:
        warn = True
        notes.append("RAFIKI_GEN_STREAM_TIMEOUT_S<=0: the door clamps "
                     "the stall detector to 0.1s — streams may be cut "
                     "before slow decodes deliver")
    gen_jobs = 0
    doors = []
    target = str(config.DB_PATH)
    is_url = target.startswith(("postgresql://", "postgres://"))
    if is_url or os.path.exists(target):
        try:
            from rafiki_tpu.db.database import Database

            db = Database(target)
            try:
                for inf in db.get_inference_jobs_by_statuses(["RUNNING"]):
                    tj = db.get_train_job(inf["train_job_id"])
                    if not tj or tj["task"] != "TEXT_GENERATION":
                        continue
                    gen_jobs += 1
                    psvc = (db.get_service(inf["predictor_service_id"])
                            if inf.get("predictor_service_id") else None)
                    host = (psvc or {}).get("host")
                    port = (psvc or {}).get("port")
                    if not host or not port:
                        warn = True
                        notes.append(
                            f"gen job {inf['id'][:8]}: no dedicated "
                            "predictor door published — streaming "
                            "/generate needs RAFIKI_PREDICTOR_PORTS=1")
                        continue
                    try:
                        import urllib.request

                        with urllib.request.urlopen(
                                f"http://{host}:{port}/healthz",
                                timeout=2.0) as resp:
                            ok = resp.status == 200
                    # lint: absorb(an unreachable door is the WARN itself, not a crash)
                    except Exception:
                        ok = False
                    if ok:
                        doors.append(f"{host}:{port}")
                    else:
                        warn = True
                        notes.append(
                            f"gen job {inf['id'][:8]}: streaming door "
                            f"{host}:{port} UNREACHABLE")
            finally:
                db.close()
        # lint: absorb(doctor checks must never crash; the failure becomes the check detail)
        except Exception as e:
            return ("generative serving", WARN,
                    f"could not scan {target}: {type(e).__name__}: {e}")
    if warn:
        return ("generative serving", WARN, "; ".join(notes))
    detail = (f"{slots} slots/worker, max {int(config.GEN_MAX_TOKENS)} "
              f"tokens/request, stall cutoff "
              f"{float(config.GEN_STREAM_TIMEOUT_S):g}s")
    if bool(config.GEN_KV_PAGED):
        detail += (f"; paged KV: {block_tokens}-token blocks, pool "
                   + (f"{pool_blocks} blocks" if pool_blocks
                      else "auto-sized (ring parity)")
                   + (", prefix cache on"
                      if bool(config.GEN_PREFIX_CACHE)
                      else ", prefix cache OFF"))
    else:
        detail += "; paged KV OFF (legacy contiguous ring)"
    if gen_jobs:
        detail += (f"; {gen_jobs} live generation job(s), doors: "
                   + (", ".join(doors) or "none"))
    return ("generative serving", PASS, detail)


#: speculative lookahead past which the draft's k proposals rarely all
#: land — each extra position costs draft compute AND verify width, and
#: acceptance decays geometrically with depth
GEN_SPEC_K_HEURISTIC = 8


def check_speculative_decoding() -> Check:
    """Speculative decoding (docs/serving-generation.md "Speculative
    decoding & sampling"): WARN when RAFIKI_GEN_SPEC is on without the
    paged plane it lives on, when RAFIKI_GEN_SPEC_K is outside the sane
    1..8 window, when a RUNNING generation job budgets a GEN_DRAFT_TRIAL
    whose template is not generation-capable or whose max_context trails
    the target's (long streams silently drop out of speculation), when a
    worker reports speculation DEGRADED (gen_spec_degraded in its stats
    row names the fault), and when the measured acceptance rate sits
    under RAFIKI_GEN_SPEC_MIN_RATE — a draft that rarely earns its k
    proposals back is pure overhead."""
    from rafiki_tpu import config

    notes = []
    warn = False
    spec_on = bool(config.GEN_SPEC)
    k = int(config.GEN_SPEC_K)
    if spec_on and not bool(config.GEN_KV_PAGED):
        warn = True
        notes.append(
            "RAFIKI_GEN_SPEC=1 with RAFIKI_GEN_KV_PAGED=0: speculation "
            "verifies through paged_verify_step on the paged plane — "
            "workers will serve plain ring decode")
    if spec_on and not (1 <= k <= GEN_SPEC_K_HEURISTIC):
        warn = True
        notes.append(
            f"RAFIKI_GEN_SPEC_K={k} is outside 1..{GEN_SPEC_K_HEURISTIC}:"
            " acceptance decays geometrically with lookahead depth, so "
            "deep drafts burn proposal compute the verify step rejects")
    drafted = 0
    target = str(config.DB_PATH)
    is_url = target.startswith(("postgresql://", "postgres://"))
    if spec_on and (is_url or os.path.exists(target)):
        try:
            from rafiki_tpu import analysis
            from rafiki_tpu.constants import BudgetType
            from rafiki_tpu.db.database import Database

            db = Database(target)
            try:
                for inf in db.get_inference_jobs_by_statuses(["RUNNING"]):
                    tj = db.get_train_job(inf["train_job_id"])
                    if not tj or tj["task"] != "TEXT_GENERATION":
                        continue
                    draft_tid = (inf.get("budget") or {}).get(
                        BudgetType.GEN_DRAFT_TRIAL)
                    if not draft_tid:
                        continue
                    drafted += 1
                    trial = db.get_trial(str(draft_tid))
                    model = (db.get_model(trial["model_id"])
                             if trial else None)
                    if model is None:
                        warn = True
                        notes.append(
                            f"gen job {inf['id'][:8]}: GEN_DRAFT_TRIAL "
                            f"{str(draft_tid)[:8]} has no stored model")
                        continue
                    dspec = analysis.static_generation_capability(
                        model["model_file_bytes"],
                        model.get("model_class"))
                    if dspec is None:
                        warn = True
                        notes.append(
                            f"gen job {inf['id'][:8]}: draft trial "
                            f"{str(draft_tid)[:8]}'s template is not "
                            "generation-capable — its workers degrade "
                            "to plain decode at boot")
                        continue
                    # the TARGET's context: the job's best trial's model
                    best = db.get_best_trials_of_train_job(
                        tj["id"], max_count=1)
                    tmodel = (db.get_model(best[0]["model_id"])
                              if best else None)
                    tspec = (analysis.static_generation_capability(
                        tmodel["model_file_bytes"],
                        tmodel.get("model_class"))
                        if tmodel is not None else None)
                    if tspec and int(dspec.get("max_context", 0)) \
                            < int(tspec.get("max_context", 0)):
                        warn = True
                        notes.append(
                            f"gen job {inf['id'][:8]}: draft max_context "
                            f"{dspec.get('max_context')} < target "
                            f"{tspec.get('max_context')} — streams past "
                            "the draft's horizon drop out of speculation "
                            "and decode plain")
            finally:
                db.close()
        # lint: absorb(doctor checks must never crash; the failure becomes the check detail)
        except Exception as e:
            return ("speculative decoding", WARN,
                    f"could not scan {target}: {type(e).__name__}: {e}")
    # live worker verdicts: degradations + the measured acceptance rate
    try:
        from rafiki_tpu.utils.metrics import REGISTRY
        from rafiki_tpu.worker.inference import SERVING_STATS, _stats_lock

        with _stats_lock:
            degraded = sorted({
                str(row["gen_spec_degraded"])
                for row in SERVING_STATS.values()
                if row.get("gen_spec_degraded")})
        if degraded:
            warn = True
            notes.append("speculation DEGRADED on live worker(s): "
                         + "; ".join(degraded))
        prop = REGISTRY.get("rafiki_gen_spec_proposed_total")
        acc = REGISTRY.get("rafiki_gen_spec_accepted_total")
        proposed = prop.value() if prop else 0
        accepted = acc.value() if acc else 0
        min_rate = float(config.GEN_SPEC_MIN_RATE)
        if proposed >= 200 and accepted / proposed < min_rate:
            warn = True
            notes.append(
                f"acceptance rate {accepted / proposed:.2f} < "
                f"RAFIKI_GEN_SPEC_MIN_RATE={min_rate:g} over "
                f"{int(proposed)} proposals: the draft disagrees with "
                "the target too often to pay for itself — use a draft "
                "distilled from the target, or lower RAFIKI_GEN_SPEC_K")
    # lint: absorb(telemetry probe is best-effort inside a doctor check)
    except Exception:
        pass
    if warn:
        return ("speculative decoding", WARN, "; ".join(notes))
    if not spec_on:
        return ("speculative decoding", PASS,
                "RAFIKI_GEN_SPEC=0 (plain decode)")
    detail = f"on, k={k}"
    if drafted:
        detail += f"; {drafted} live job(s) budget a draft trial"
    return ("speculative decoding", PASS, detail)


def check_stream_continuity() -> Check:
    """Stream continuity (docs/failure-model.md "Stream continuity"):
    WARN when the door-side resume journal's byte cap cannot hold a
    max-length stream (~8 B per journaled token id, so a cap under
    GEN_MAX_TOKENS*8 means long streams overflow and silently lose
    resume eligibility before they finish), when resume is disabled
    (RAFIKI_GEN_RESUME_MAX=0) while the autoscaler is ON (every
    scale-down's MIGRATING handoff then surfaces as a client error
    instead of a sibling resume), when the journal TTL is shorter than
    the serving deadline (a stream can outlive its own resume
    eligibility), and when a rollout's drain window is zero (every
    rolling step force-migrates every resident stream instead of
    letting finishable ones run out)."""
    from rafiki_tpu import config

    notes = []
    warn = False
    cap_bytes = int(config.GEN_JOURNAL_MAX_KB) * 1024
    need = int(config.GEN_MAX_TOKENS) * 8
    if 0 < cap_bytes < need:
        warn = True
        notes.append(
            f"RAFIKI_GEN_JOURNAL_MAX_KB={int(config.GEN_JOURNAL_MAX_KB)} "
            f"({cap_bytes} B) < GEN_MAX_TOKENS*8 ({need} B): max-length "
            "streams overflow the journal and lose resume eligibility "
            "mid-stream")
    resume_max = int(config.GEN_RESUME_MAX)
    if resume_max <= 0 and bool(config.AUTOSCALE):
        warn = True
        notes.append(
            "RAFIKI_GEN_RESUME_MAX=0 with RAFIKI_AUTOSCALE=1: scale-down "
            "drain handoffs of generation streams cannot be resumed — "
            "every forced migration becomes a client-visible error")
    ttl = float(config.GEN_JOURNAL_TTL_S)
    if 0 < ttl < float(config.PREDICT_TIMEOUT_S):
        warn = True
        notes.append(
            f"RAFIKI_GEN_JOURNAL_TTL_S={ttl:g} < "
            f"PREDICT_TIMEOUT_S={float(config.PREDICT_TIMEOUT_S):g}: a "
            "stream can outlive its journal entry and die unresumable "
            "inside its own deadline")
    if resume_max > 0 and float(config.AUTOSCALE_DRAIN_S) <= 0:
        warn = True
        notes.append(
            f"RAFIKI_AUTOSCALE_DRAIN_S="
            f"{float(config.AUTOSCALE_DRAIN_S):g}: gen rollouts/scale-"
            "downs skip the run-out window and force-migrate EVERY "
            "resident stream — resumes work but burn sibling prefills "
            "for streams that could have finished in place")
    if warn:
        return ("stream continuity", WARN, "; ".join(notes))
    if resume_max <= 0:
        return ("stream continuity", PASS,
                "resume disabled (RAFIKI_GEN_RESUME_MAX=0)")
    return ("stream continuity", PASS,
            f"resume on: {resume_max} attempt(s), journal cap "
            f"{int(config.GEN_JOURNAL_MAX_KB)} KB, TTL {ttl:g}s")


#: prediction-cache byte cap past which the doctor reads "this cache
#: will contend with the models for host memory" — results live in the
#: admin process's RAM beside every Predictor, door, and broker ring
PREDICT_CACHE_BYTES_HEURISTIC = 1 << 30


def check_prediction_cache() -> Check:
    """Prediction result cache + single-flight (docs/performance.md
    "Prediction caching & single-flight"): WARN when the cache is ON
    with a zero TTL (every fill is dropped — pure digest overhead), when
    the byte cap is past the host-memory heuristic, when it is enabled
    alongside live TEXT_GENERATION jobs (generative serving is excluded
    by design, so the operator's knob is doing less than they think),
    and when it is OFF while the sampled duplicate-query counter shows
    sustained identical-query traffic being forwarded redundantly (the
    `shareable`-style signal, applied to classification)."""
    from rafiki_tpu import config

    enabled = bool(config.PREDICT_CACHE)
    notes = []
    warn = False
    if enabled:
        ttl = float(config.PREDICT_CACHE_TTL_S)
        if ttl <= 0:
            warn = True
            notes.append(
                f"RAFIKI_PREDICT_CACHE_TTL_S={ttl:g} with the cache ON: "
                "every fill is dropped, so requests pay the digest cost "
                "and never hit — set a positive TTL or disable the cache")
        cap = int(config.PREDICT_CACHE_MAX_BYTES)
        if cap > PREDICT_CACHE_BYTES_HEURISTIC:
            warn = True
            notes.append(
                f"RAFIKI_PREDICT_CACHE_MAX_BYTES={cap} is past the "
                f"host-memory heuristic ({PREDICT_CACHE_BYTES_HEURISTIC}): "
                "the cache shares the admin process's RAM with every "
                "serving head and broker ring — prefer a shorter TTL "
                "over a deeper cache")
        target = str(config.DB_PATH)
        is_url = target.startswith(("postgresql://", "postgres://"))
        if is_url or os.path.exists(target):
            try:
                from rafiki_tpu.db.database import Database

                db = Database(target)
                try:
                    gen_jobs = [
                        inf["id"][:8]
                        for inf in db.get_inference_jobs_by_statuses(
                            ["RUNNING"])
                        if (db.get_train_job(inf["train_job_id"]) or {}
                            ).get("task") == "TEXT_GENERATION"]
                finally:
                    db.close()
                if gen_jobs:
                    warn = True
                    notes.append(
                        "RAFIKI_PREDICT_CACHE=1 beside live "
                        f"TEXT_GENERATION job(s) {gen_jobs}: generative "
                        "serving is EXCLUDED from the prediction cache "
                        "by design (token streams answer from decode "
                        "state, not a one-shot forward) — the knob does "
                        "nothing for those jobs; the shared-prefix KV "
                        "cache (RAFIKI_GEN_PREFIX_CACHE) is their "
                        "equivalent lever")
            # lint: absorb(doctor checks must never crash; the failure becomes the check detail)
            except Exception as e:
                notes.append(f"could not scan {target} for generative "
                             f"jobs: {type(e).__name__}: {e}")
    else:
        try:
            from rafiki_tpu.utils.metrics import REGISTRY

            shareable = REGISTRY.get("rafiki_cache_shareable_total")
            # per-job labeled family: the signal is the fleet-wide sum
            shared_n = (sum(ch.value()
                            for ch in shareable.children().values())
                        if shareable else 0)
        # lint: absorb(telemetry probe is best-effort inside a doctor check)
        except Exception:
            shared_n = 0
        if shared_n > 0:
            warn = True
            notes.append(
                f"RAFIKI_PREDICT_CACHE=0 while the sampled duplicate-"
                f"query probe counted {int(shared_n)} repeat(s) "
                "(rafiki_cache_shareable_total, 1-in-16 sampling): "
                "identical queries are re-paying model forwards the "
                "cache would serve free — consider "
                "RAFIKI_PREDICT_CACHE=1 (results must be deterministic "
                "per model version; flushed automatically on deploy/"
                "rollback/adoption)")
    if warn:
        return ("prediction cache", WARN, "; ".join(notes))
    if not enabled:
        return ("prediction cache", PASS,
                "off (default; no duplicate-query traffic observed — "
                "RAFIKI_PREDICT_CACHE=1 adds a versioned result cache "
                "with single-flight coalescing)")
    detail = (f"on: TTL {float(config.PREDICT_CACHE_TTL_S):g}s, cap "
              f"{int(config.PREDICT_CACHE_MAX_BYTES)} bytes, "
              "single-flight "
              + ("on" if bool(config.PREDICT_SINGLEFLIGHT) else "OFF"))
    if notes:
        detail += "; " + "; ".join(notes)
    return ("prediction cache", PASS, detail)


def check_autoscaler(total_chips: int = None) -> Check:
    """Elastic serving autoscaler (docs/failure-model.md "Overload
    adaptation"): WARN when the serving plane is visibly shedding while
    the control loop that could fix it is disabled, when the replica
    bounds are inverted (the loop would be wedged between them), and when
    the chip-borrow training floor exceeds the fleet's capacity (no
    borrow could ever be granted — probably a typo'd knob).

    ``total_chips`` injects the fleet capacity when the caller knows it;
    otherwise it is summed from RAFIKI_AGENTS inventories when set."""
    from rafiki_tpu import config
    from rafiki_tpu.utils.metrics import REGISTRY, ring_window_s

    notes = []
    warn = False
    enabled = bool(config.AUTOSCALE)
    min_r = int(config.AUTOSCALE_MIN_REPLICAS)
    max_r = int(config.AUTOSCALE_MAX_REPLICAS)
    if min_r > max_r:
        warn = True
        notes.append(
            f"replica bounds INVERTED: RAFIKI_AUTOSCALE_MIN_REPLICAS="
            f"{min_r} > RAFIKI_AUTOSCALE_MAX_REPLICAS={max_r} — the loop "
            "can neither grow nor shrink any job")
    low, high = float(config.AUTOSCALE_DEPTH_LOW), float(
        config.AUTOSCALE_DEPTH_HIGH)
    if low >= high:
        warn = True
        notes.append(
            f"no hysteresis: RAFIKI_AUTOSCALE_DEPTH_LOW={low:g} >= "
            f"DEPTH_HIGH={high:g} — the loop will flap between up and "
            "down on the same signal")
    # sustained shed with the loop off: scan the shed-rate ring series
    # (in-process registry — embedded use — plus the admin door's JSON
    # snapshot when an admin is reachable)
    ring_snapshot = {
        name: series
        for name, series in REGISTRY.snapshot()["rings"].items()
        if name.startswith("shed_rate:")}
    try:
        import json as _json
        import urllib.request

        with urllib.request.urlopen(
                f"http://{config.ADMIN_HOST}:{config.ADMIN_PORT}"
                "/metrics?format=json", timeout=2) as resp:
            remote = _json.load(resp).get("rings", {})
        for name, series in remote.items():
            if name.startswith("shed_rate:"):
                ring_snapshot.setdefault(name, series)
    # lint: absorb(doctor checks must never crash; the failure becomes the check detail)
    except Exception:
        pass  # no admin on this host — in-process rings only
    shed_doors = sorted(
        name.split(":", 1)[1]
        for name, series in ring_snapshot.items()
        if sum(v for _, v in series) > 0)
    if shed_doors and not enabled:
        warn = True
        notes.append(
            f"sustained shed observed at {shed_doors} within the last "
            f"{ring_window_s()}s but RAFIKI_AUTOSCALE is OFF — the fleet "
            "is turning traffic away that a scale-up could absorb")
    # chip-borrow floor vs fleet capacity
    floor = int(config.AUTOSCALE_TRAIN_FLOOR)
    if total_chips is None:
        agents = [a.strip() for a in os.environ.get(
            "RAFIKI_AGENTS", "").split(",") if a.strip()]
        if agents:
            from rafiki_tpu.utils.agent_http import call_agent

            total_chips = 0
            for addr in agents:
                try:
                    inv = call_agent(
                        addr, "GET", "/inventory",
                        key=os.environ.get("RAFIKI_AGENT_KEY"),
                        timeout_s=5, use_breaker=False)
                    total_chips += int(inv.get("total_chips", 0))
                # lint: absorb(doctor checks must never crash; the failure becomes the check detail)
                except Exception:
                    total_chips = None
                    break
    if total_chips is not None and floor > total_chips > 0:
        warn = True
        notes.append(
            f"RAFIKI_AUTOSCALE_TRAIN_FLOOR={floor} exceeds the fleet's "
            f"{total_chips} chip(s): no serving borrow can ever be "
            "granted — probably a typo")
    state = "loop ON" if enabled else "loop off"
    fair = "fair admission ON" if config.AUTOSCALE_FAIR else \
        "fair admission off"
    detail = (f"{state}, {fair}, replicas [{min_r}, {max_r}] step "
              f"{int(config.AUTOSCALE_STEP)}, train floor {floor} chip(s)"
              + ("; " + "; ".join(notes) if notes else ""))
    return ("autoscaler", WARN if warn else PASS, detail)


def check_compile_cache(total_chips: Optional[int] = None) -> Check:
    """Cold-start resilience (docs/failure-model.md "Cold-start
    faults"): WARN when the persistent compile cache cannot actually
    serve worker boots — the dir missing/unwritable or on a different
    device than the workdir, the cache disabled while the autoscaler or
    warm pool is ON (their replacement replicas would recompile from
    scratch, defeating the point), recent boots compiling without a
    single cache hit (a silently-misconfigured key or dir), or a
    warm-pool floor no fleet capacity could ever hold."""
    from rafiki_tpu import config
    from rafiki_tpu.utils.metrics import REGISTRY

    notes = []
    warn = False
    enabled = bool(config.COMPILE_CACHE)
    root = (config.COMPILE_CACHE_DIR
            or os.path.join(config.WORKDIR, "xla_cache"))
    scaler_on = bool(config.AUTOSCALE) or int(config.AUTOSCALE_WARM_POOL) > 0
    if not enabled and scaler_on:
        warn = True
        notes.append(
            "RAFIKI_COMPILE_CACHE=0 while the autoscaler/warm pool is ON "
            "— every replacement replica pays a full cold compile, which "
            "is exactly the latency those loops exist to remove")
    if enabled:
        try:
            os.makedirs(root, exist_ok=True)
            probe = os.path.join(root, ".rafiki_doctor_probe")
            with open(probe, "w", encoding="utf-8") as f:
                f.write("ok")
            os.unlink(probe)
        # lint: absorb(doctor checks must never crash; the failure becomes the check detail)
        except Exception as e:
            warn = True
            notes.append(
                f"cache dir {root} is missing/unwritable "
                f"({type(e).__name__}: {e}) — workers degrade to fresh "
                "compiles every boot")
        else:
            try:
                if (os.stat(root).st_dev
                        != os.stat(config.WORKDIR).st_dev):
                    warn = True
                    notes.append(
                        f"cache dir {root} sits on a different device "
                        "than RAFIKI_WORKDIR — cache writes cross a "
                        "filesystem boundary (slow, and atomic-rename "
                        "guarantees differ)")
            # lint: absorb(doctor checks must never crash; an unstatable workdir just skips the device comparison)
            except OSError:
                pass
    # recent boots compiling without a single hit: the
    # silently-misconfigured-key case (this process's registry plus the
    # admin door's JSON snapshot when an admin is reachable)
    local = REGISTRY.snapshot().get("metrics", {})
    remote = {}
    try:
        import json as _json
        import urllib.request

        with urllib.request.urlopen(
                f"http://{config.ADMIN_HOST}:{config.ADMIN_PORT}"
                "/metrics?format=json", timeout=2) as resp:
            remote = _json.load(resp).get("metrics", {})
    # lint: absorb(doctor checks must never crash; no admin on this host means in-process counters only)
    except Exception:
        pass
    hits = (_sum_counter(local, "rafiki_compile_cache_hits_total")
            + _sum_counter(remote, "rafiki_compile_cache_hits_total"))
    misses = (_sum_counter(local, "rafiki_compile_cache_misses_total")
              + _sum_counter(remote, "rafiki_compile_cache_misses_total"))
    if enabled and hits == 0 and misses >= 2:
        warn = True
        notes.append(
            f"{misses} program(s) compiled fresh with ZERO persistent-"
            "cache hits — a misconfigured RAFIKI_COMPILE_CACHE_DIR or a "
            "topology/version key that never matches (every boot is "
            "cold)")
    # warm-pool floor vs fleet capacity
    pool = int(config.AUTOSCALE_WARM_POOL)
    if total_chips is None:
        agents = [a.strip() for a in os.environ.get(
            "RAFIKI_AGENTS", "").split(",") if a.strip()]
        if agents:
            from rafiki_tpu.utils.agent_http import call_agent

            total_chips = 0
            for addr in agents:
                try:
                    inv = call_agent(
                        addr, "GET", "/inventory",
                        key=os.environ.get("RAFIKI_AGENT_KEY"),
                        timeout_s=5, use_breaker=False)
                    total_chips += int(inv.get("total_chips", 0))
                # lint: absorb(doctor checks must never crash; the failure becomes the check detail)
                except Exception:
                    total_chips = None
                    break
    if total_chips is not None and pool > total_chips > 0:
        warn = True
        notes.append(
            f"RAFIKI_AUTOSCALE_WARM_POOL={pool} standbys/job exceeds the "
            f"fleet's {total_chips} chip(s) — the pool can never reach "
            "its floor, probably a typo")
    state = "cache ON" if enabled else "cache off"
    pool_s = f"warm pool {pool}/job" if pool > 0 else "warm pool off"
    detail = (f"{state} at {root}, {pool_s}, hits {hits} misses {misses}"
              + ("; " + "; ".join(notes) if notes else ""))
    return ("compile cache", WARN if warn else PASS, detail)


def _sum_counter(metrics: Dict[str, Any], name: str) -> int:
    """Sum a counter family out of a registry JSON snapshot's flat
    {``name{labels}``: value} metric map (all label sets folded)."""
    total = 0.0
    for key, val in metrics.items():
        if (key == name or key.startswith(name + "{")) \
                and isinstance(val, (int, float)):
            total += val
    return int(total)


def check_observability() -> Check:
    """Telemetry plane (docs/observability.md): the registry must render
    parseable exposition, RAFIKI_TRACE_SAMPLE must be a sane rate, and
    the slow-request exemplar log must not be growing past its rotation
    cap. When RAFIKI_AGENTS is set, each agent's GET /metrics is probed —
    the scrape endpoint an autoscaler/dashboard will sit on."""
    from rafiki_tpu import config
    from rafiki_tpu.utils import trace as rtrace
    from rafiki_tpu.utils.metrics import (
        REGISTRY, metrics_enabled, parse_prometheus)

    notes = []
    warn = False
    if not metrics_enabled():
        warn = True
        notes.append("RAFIKI_METRICS=0: registry writes are no-ops — "
                     "/metrics will expose zeros")
    raw_rate = os.environ.get("RAFIKI_TRACE_SAMPLE", "")
    if raw_rate:
        try:
            r = float(raw_rate)
            if not 0.0 <= r <= 1.0:
                warn = True
                notes.append(f"RAFIKI_TRACE_SAMPLE={raw_rate} outside "
                             "[0, 1] — clamped, probably a typo")
            elif r >= 0.5 and rtrace.slow_threshold_s() <= 0:
                warn = True
                notes.append(
                    f"RAFIKI_TRACE_SAMPLE={r:g} with RAFIKI_TRACE_SLOW_MS "
                    "unset dumps an exemplar for (nearly) EVERY request — "
                    "set a slow threshold for production traffic")
        except ValueError:
            warn = True
            notes.append(f"RAFIKI_TRACE_SAMPLE={raw_rate!r} unparseable — "
                         "tracing is OFF")
    try:
        parse_prometheus(REGISTRY.render())
        n_metrics = len(REGISTRY.names())
    # lint: absorb(doctor checks must never crash; the failure becomes the check detail)
    except Exception as e:
        return ("observability", FAIL,
                f"registry exposition does not parse: {e}")
    try:
        path = rtrace.exemplar_path()
        if os.path.exists(path):
            mb = os.path.getsize(path) / (1 << 20)
            cap = rtrace.exemplar_max_mb()
            if mb > cap * 1.5:
                warn = True
                notes.append(
                    f"exemplar log {path} at {mb:.0f} MB, past its "
                    f"{cap:g} MB rotation cap — rotation is not keeping "
                    "up (RAFIKI_TRACE_EXEMPLAR_MAX_MB)")
            else:
                notes.append(f"exemplar log {mb:.1f} MB / {cap:g} MB cap")
    except OSError:
        pass
    agents = [a.strip() for a in os.environ.get(
        "RAFIKI_AGENTS", "").split(",") if a.strip()]
    unreachable = []
    for addr in agents:
        try:
            import urllib.request

            with urllib.request.urlopen(
                    f"http://{addr}/metrics", timeout=5) as resp:
                parse_prometheus(resp.read().decode())
        # lint: absorb(doctor checks must never crash; the failure becomes the check detail)
        except Exception:
            unreachable.append(addr)
    if unreachable:
        warn = True
        notes.append(f"agent /metrics unreachable: {unreachable}")
    rate = rtrace.sample_rate()
    detail = (f"{n_metrics} metric families registered, trace sampling "
              f"{rate:g}" + ("; " + "; ".join(notes) if notes else ""))
    return ("observability", WARN if warn else PASS, detail)


def check_agents() -> Check:
    from rafiki_tpu.utils.agent_http import AgentHTTPError, call_agent

    agents = [a.strip() for a in os.environ.get("RAFIKI_AGENTS", "").split(",")
              if a.strip()]
    if not agents:
        return ("host agents", PASS, "single-host (RAFIKI_AGENTS unset)")
    key = os.environ.get("RAFIKI_AGENT_KEY")
    down, rejected, locked = [], [], []
    total = 0
    for addr in agents:
        try:
            # /healthz first (unauthenticated): separates "host process
            # dead" from "alive but misconfigured" — the same liveness
            # probe the admin's heartbeat monitor uses, so doctor and the
            # /fleet/health API agree on what DOWN means
            call_agent(addr, "GET", "/healthz", timeout_s=5,
                       use_breaker=False)
        except AgentHTTPError:
            pass  # the host ANSWERED: alive (any config problem shows below)
        # lint: absorb(doctor checks must never crash; the failure becomes the check detail)
        except Exception:
            down.append(addr)
            continue
        try:
            inv = call_agent(addr, "GET", "/inventory", key=key, timeout_s=5,
                             use_breaker=False)
            total += int(inv.get("total_chips", 0))
        except AgentHTTPError as e:
            # a live agent refusing the request is a CONFIG problem, not
            # an outage — agents are keyed by default since r5. 401 =
            # key mismatch (fix on the admin side); 403 = the AGENT has
            # no key and no insecure opt-in (fix on the agent side)
            if e.code == 401:
                rejected.append(addr)
            elif e.code == 403:
                locked.append(addr)
            else:
                down.append(addr)
        # lint: absorb(doctor checks must never crash; the failure becomes the check detail)
        except Exception:
            down.append(addr)
    if locked:
        return ("host agents", FAIL,
                f"locked (keyless, no RAFIKI_AGENT_INSECURE): {locked} — "
                "configure RAFIKI_AGENT_KEY on those agents")
    if rejected:
        why = ("RAFIKI_AGENT_KEY unset on this admin" if not key
               else "this admin's RAFIKI_AGENT_KEY does not match")
        return ("host agents", FAIL,
                f"key rejected by: {rejected} ({why}; copy the agents' "
                "agent.key here)")
    if down:
        return ("host agents", FAIL if len(down) == len(agents) else WARN,
                f"DOWN (no /healthz answer): {down} (fleet chips visible: "
                f"{total}) — a hosts-mode admin evicts their serving "
                "queues and fails their train executors over; see "
                "GET /fleet/health and docs/failure-model.md")
    if not key:
        return ("host agents", WARN,
                f"{len(agents)} agent(s), {total} fleet chips — keyless "
                "admin talking to RAFIKI_AGENT_INSECURE agents; set a "
                "fleet key")
    return ("host agents", PASS,
            f"{len(agents)} agent(s), {total} fleet chips")


def check_control_plane_ha() -> Check:
    """Control-plane HA (docs/failure-model.md "Control-plane HA"): lease
    timing sanity, standby reachability, leader-epoch agreement between
    the store and the agent fleet, and the HA-off-but-controllers-on
    single-point-of-failure shape."""
    from rafiki_tpu import config

    notes = []
    warn = False
    ha_on = bool(config.ADMIN_HA)
    ttl = float(config.ADMIN_LEASE_TTL_S)
    renew = float(config.ADMIN_LEASE_RENEW_S) or ttl / 3.0
    if ha_on and ttl <= 2.0 * renew:
        warn = True
        notes.append(
            f"lease TTL {ttl:g}s <= 2x renewal period {renew:g}s: one "
            "missed renewal forfeits leadership (set "
            "RAFIKI_ADMIN_LEASE_TTL_S >= 3x RAFIKI_ADMIN_LEASE_RENEW_S)")
    if not ha_on and (config.AUTOSCALE or config.DRIFT):
        warn = True
        notes.append(
            "closed-loop controllers on (RAFIKI_AUTOSCALE/RAFIKI_DRIFT) "
            "with RAFIKI_ADMIN_HA=0: the deciding admin is a single "
            "point of failure — run a hot standby")
    addrs = [a.strip() for a in str(config.ADMIN_ADDRS).split(",")
             if a.strip()]
    if len(addrs) > 1:
        import urllib.request as _ur

        dead = []
        for addr in addrs:
            try:
                with _ur.urlopen(f"http://{addr}/", timeout=3):
                    pass
            # lint: absorb(doctor checks must never crash; the failure becomes the check detail)
            except Exception:
                dead.append(addr)
        if dead:
            warn = True
            notes.append(
                f"RAFIKI_ADMIN_ADDRS lists unreachable admin(s): {dead} "
                "— clients will burn the failover window walking them")
    # leader-epoch agreement: the lease row is the truth; an agent
    # remembering a HIGHER epoch than the store means a stale/forked
    # store (or an admin writing to a different one)
    lease_epoch = None
    target = str(config.DB_PATH)
    is_url = target.startswith(("postgresql://", "postgres://"))
    if ha_on and (is_url or os.path.exists(target)):
        try:
            from rafiki_tpu.db.database import Database

            db = Database(target)
            row = db.read_lease()
            if row is not None:
                lease_epoch = int(row["epoch"])
                import time as _time

                live = row["expires_at"] > _time.time()
                notes.append(
                    f"lease: epoch {lease_epoch} held by "
                    f"{row.get('holder')}"
                    + ("" if live else " (EXPIRED — no leader)"))
                if not live:
                    warn = True
        # lint: absorb(doctor checks must never crash; the failure becomes the check detail)
        except Exception as e:
            notes.append(f"lease row unreadable: {type(e).__name__}")
    agents = [a.strip() for a in os.environ.get("RAFIKI_AGENTS", "").split(",")
              if a.strip()]
    if lease_epoch is not None and agents:
        from rafiki_tpu.utils.agent_http import call_agent

        skewed = []
        for addr in agents:
            try:
                hz = call_agent(addr, "GET", "/healthz", timeout_s=5,
                                use_breaker=False)
                seen = int(hz.get("admin_epoch", 0))
            # lint: absorb(doctor checks must never crash; the failure becomes the check detail)
            except Exception:
                continue  # reachability is check_agents' job, not ours
            if seen > lease_epoch:
                skewed.append(f"{addr}=e{seen}")
        if skewed:
            warn = True
            notes.append(
                f"agents remember a HIGHER epoch than the lease row "
                f"({skewed} vs store e{lease_epoch}): this admin is "
                "reading a stale or forked store")
    if not ha_on and not notes:
        return ("control-plane HA", PASS,
                "off (RAFIKI_ADMIN_HA=0, no controllers demanding it)")
    detail = "; ".join(notes) if notes else (
        f"on: TTL {ttl:g}s, renew {renew:g}s, "
        f"{len(addrs) or 1} admin addr(s)")
    return ("control-plane HA", WARN if warn else PASS, detail)


CHECKS: List[Callable[[], Check]] = [
    check_workdir, check_store, check_shm_broker, check_sandbox,
    check_chaos, check_overload_knobs, check_autoscaler,
    check_compile_cache, check_recovery,
    check_rollouts, check_drift, check_trial_faults,
    check_vectorized_trials,
    check_static_analysis, check_concurrency_lint,
    check_int8_serving, check_generative_serving,
    check_speculative_decoding, check_stream_continuity,
    check_prediction_cache,
    check_observability, check_agents, check_control_plane_ha,
    check_backend,
]


def run(json_out: bool = False) -> int:
    results = []
    for check in CHECKS:
        try:
            results.append(check())
        # lint: absorb(doctor checks must never crash; the failure becomes the check detail)
        except Exception as e:  # a doctor must never crash mid-diagnosis
            results.append((check.__name__, FAIL,
                            f"check crashed: {type(e).__name__}: {e}"))
    worst = PASS
    for name, status, detail in results:
        if not json_out:
            print(f"[{status}] {name}: {detail}")
        if status == FAIL or (status == WARN and worst == PASS):
            worst = status
    if json_out:
        print(json.dumps([
            {"check": n, "status": s, "detail": d} for n, s, d in results]))
    return 1 if worst == FAIL else 0


if __name__ == "__main__":
    sys.exit(run(json_out="--json" in sys.argv))
