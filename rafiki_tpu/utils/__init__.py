"""Cross-cutting utilities: auth tokens, password hashing, logging setup
(reference rafiki/utils/)."""
