"""Deterministic fault injection for the fleet control/data planes.

Every failover path in the fleet health subsystem (placement/hosts.py
heartbeats, utils/agent_http.py circuit breaker, cache/fleet.py eviction)
must be exercisable by fast CPU-only tier-1 tests without real hosts
dying. This module is the single switchboard: the two wire-protocol
chokepoints — ``call_agent`` (client side) and the agent HTTP server
(placement/agent.py) — ask it before each request, and it answers with an
injected fault (or nothing) on a **deterministic schedule** driven by
per-rule hit counters, never randomness.

Rules come from the ``RAFIKI_CHAOS`` environment variable (off by
default — empty/unset means every hook is a no-op) or programmatically
via :func:`install` in tests. Env format: ``|``-separated rules of
``;``-separated ``key=value`` fields, e.g. ::

    RAFIKI_CHAOS='site=agent;action=error;code=503;match=/predict_relay;times=2'
    RAFIKI_CHAOS='site=call_agent;action=drop;match=9001|site=agent;action=delay;delay_s=0.2'

Fields:

    site     where to inject: ``call_agent`` (admin-side transport),
             ``agent`` (host agent server), ``worker`` (inference
             serve loop — overload drills: slow/stalled replicas),
             ``wire`` (shm frames popped off the serving rings, before
             decode — corruption drills), ``db`` (metadata-store
             statements — transient store-failure drills for
             control-plane recovery), ``trial`` (the trial-run
             chokepoint in the train worker — fault-taxonomy drills),
             ``cache`` (the prediction result cache's lookup/fill/join
             operations — degraded-cache drills: a broken cache must
             degrade to miss-path serving, never fail a request),
             ``generate`` (the generation decode loop — mid-stream
             fault / stalled-decode drills, one ask per active slot per
             round), ``deploy`` (the inference-replica placement
             chokepoint — canary-failure / deploy-timeout rollback
             drills for live rollouts), or ``drift`` (the drift loop's
             monitor-tick and retrain-launch chokepoints — degraded-
             monitor / parked-launch drills), or ``compile`` (the worker
             warm-up / compile chokepoint — cold-start drills: slow
             compiles, corrupt cache entries, failed standby warm-ups),
             or ``lease`` (the control-plane leadership-lease
             acquire/renew chokepoint — false-lease-loss, slow-renewal
             and self-fence drills for admin HA).
             Required.
    action   ``drop`` (connection-level failure; at site=worker the batch
             is silently swallowed — a stalled replica), ``delay`` (sleep
             ``delay_s`` then proceed — a slow replica), ``error``
             (HTTP ``code``; at site=worker the batch fails; at
             site=trial a typed transient INFRA fault), ``corrupt``
             (site=wire: truncate/garble the raw frame bytes;
             site=compile: garble the persistent compile-cache entries),
             or ``oom`` (site=trial only: raise MemoryError — the
             MEM-class drill). Required.
    match    substring filter on the target ("addr path" client-side,
             request path server-side). Empty matches everything.
    after    skip the first N matching requests (default 0).
    times    inject into at most N matching requests (default: no cap) —
             ``after``/``times`` windows let a test kill a host "mid-
             serving" at an exact request ordinal.
    every    of the post-``after`` matches, inject into every k-th
             (default 1 = all).
    delay_s  sleep for ``delay`` (default 0.05).
    code     HTTP status for ``error`` (default 503).

The controller re-parses ``RAFIKI_CHAOS`` whenever the env value changes
(counters reset with it), so monkeypatched tests and spawned agent
subprocesses both pick rules up without plumbing.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from dataclasses import dataclass, field
from typing import List, Optional

logger = logging.getLogger(__name__)

ENV_VAR = "RAFIKI_CHAOS"

SITE_CALL_AGENT = "call_agent"
SITE_AGENT = "agent"
# inference worker serve loop (worker/inference.py): the overload-drill
# site. `delay` makes a worker slow (queues back up behind a live model —
# the condition that triggers admission shed + hedge suppression), `drop`
# makes it silently swallow a batch (futures never resolve; the
# predictor's SLO machinery takes over), `error` fails the batch.
# GENERATION replicas (worker/generation.py) ask this site once per
# serve-loop round with target "{job_id}/{service_id}" — the kill-replica
# chaos target: `drop` is the SIGKILL drill (the loop exits ABRUPTLY,
# resident streams abandoned un-handed-back; the door's journal resumes
# them on siblings when the dead replica's queue vanishes), `error` is a
# clean kill (typed MIGRATING handoff of every resident stream first),
# `delay` stalls the whole replica for a round.
SITE_WORKER = "worker"
# serving wire chokepoint (cache/shm_broker.py): frames popped off the
# shm rings, BEFORE decode. `corrupt` garbles/truncates the raw bytes on
# a deterministic schedule — the drill that proves a corrupt frame
# yields a typed per-request error (WireFormatError -> skip -> the
# request's SLO timeout), never a worker-loop crash. Target string is
# the shm queue name, so `match` can pick the query vs response ring.
SITE_WIRE = "wire"
# metadata store (db/database.py): every statement the DAL issues asks
# this site first; target string is the SQL text, so `match` can pick a
# table ("FROM service") or verb ("UPDATE"). `error` raises a typed
# transient store failure, `delay` models a slow/contended store — the
# drill that proves control-plane recovery retries with bounded jittered
# backoff instead of aborting reconciliation (docs/failure-model.md).
SITE_DB = "db"
# generation decode loop (worker/generation.py): one ask per ACTIVE SLOT
# per decode round, target "{job_id}/{service_id}/slot{i}/{seq_id}" so
# `match` can injure one co-resident sequence mid-stream. `error` fails
# exactly that sequence (typed terminal error frame on its stream;
# siblings keep decoding), `drop` mutes the slot's deltas — the stalled-
# decode drill the door's inter-token timeout must convert into a typed
# error frame, never a silent hang — and `delay` slows the whole step
# (a slow decode) — docs/serving-generation.md "Chaos drills".
# A second target shape lives at this site: "draft/{job_id}/{service_id}"
# is asked once per speculative round BEFORE the draft proposes. `delay`
# slows the round, `drop` skips speculation for that round (plain
# decode), `error` permanently degrades the worker to plain decode with
# a typed reason (gen_spec_degraded) — the crashing/stalling-draft drill:
# a broken draft model must cost throughput, never correctness.
SITE_GENERATE = "generate"
# inference-replica placement chokepoint (admin/services.py — the
# shared _chaos_deploy ask inside create_inference_services,
# _scale_up_one, and the rollout controller's deploy_version_replica):
# one ask per replica placement, target "{inference_job_id}/{trial_id}".
# `error` (or `drop`) fails the placement with a typed
# ServiceDeploymentError — the deterministic canary-failure drill —
# and `delay` models a slow deploy (stacked against the rollout's
# deploy deadline, it becomes the deploy-timeout rollback drill) —
# docs/failure-model.md "Rollout faults".
SITE_DEPLOY = "deploy"
# prediction result cache (predictor/result_cache.py): one ask per
# lookup / fill / single-flight join, target "{inference_job_id}/{op}"
# (op in lookup|fill|join) so `match` can injure one operation class.
# `error` raises inside the cache call — the drill that proves a broken
# cache DEGRADES to miss-path serving (the predictor absorbs it, the
# request is answered by a real forward, never failed); `delay` models
# a slow cache. docs/failure-model.md "Cache faults".
SITE_CACHE = "cache"
# drift closed loop (admin/drift.py): two chokepoints, target
# "tick/{inference_job_id}" (the monitor's per-job evaluation) and
# "launch/{inference_job_id}" (the bounded-retrain launch). `error` at
# tick proves the degradation contract — a broken monitor is absorbed
# and never touches serving; `error` at launch drives the bounded
# launch retries and the PARKED terminal state; `delay` models a slow
# monitor/launch — docs/failure-model.md "Model drift faults".
SITE_DRIFT = "drift"
# trial-run chokepoint (worker/train.py _execute_trial): one ask per
# trial ATTEMPT, target "{sub_train_job_id} {trial_id}". `error` raises
# a typed transient fault the taxonomy classifies INFRA (the
# bounded-retry drill: the trial re-runs under the same id without
# burning a budget slot), `oom` raises MemoryError (classified MEM),
# `delay` models a slow trial start — docs/failure-model.md
# "Training-plane faults".
SITE_TRIAL = "trial"
# worker warm-up / compile chokepoint (worker/warmup.py run_warmup):
# one ask per warm-up program, target
# "{inference_job_id}/{service_id}/{program}". `delay` models a slow
# compile (the still-warming replica stays DEPLOYING — the drill that
# proves the predictor never routes to it), `error` raises the typed
# WarmupError that fails the worker's startup (the bounded standby-
# retry drill), and `corrupt` garbles the persistent compile-cache
# entries on disk first (the bit-rot drill: JAX's reader absorbs the
# damage and the boot degrades to a fresh compile, never a crash) —
# docs/failure-model.md "Cold-start faults".
SITE_COMPILE = "compile"
# control-plane leadership lease (db/database.py acquire_lease /
# renew_lease): one ask per lease operation, target "acquire" or
# "renew". `error` is the false-lease-loss drill (a renewal that errors
# must NOT drop leadership — the TTL clock decides; a leader that cannot
# renew within the TTL self-fences its writes BEFORE the standby can
# acquire), `delay` models a slow/contended store near the TTL edge
# (renewal landing late, promotion racing expiry) —
# docs/failure-model.md "Control-plane HA".
SITE_LEASE = "lease"

ACTION_DROP = "drop"
ACTION_DELAY = "delay"
ACTION_ERROR = "error"
ACTION_CORRUPT = "corrupt"
ACTION_OOM = "oom"


class ChaosSpecError(ValueError):
    """RAFIKI_CHAOS could not be parsed; raised at install, logged (once
    per bad value) when coming from the environment."""


@dataclass
class ChaosRule:
    site: str
    action: str
    match: str = ""
    after: int = 0
    times: Optional[int] = None
    every: int = 1
    delay_s: float = 0.05
    code: int = 503
    hits: int = field(default=0, compare=False)  # matching requests seen

    def __post_init__(self) -> None:
        if self.site not in (SITE_CALL_AGENT, SITE_AGENT, SITE_WORKER,
                             SITE_WIRE, SITE_DB, SITE_TRIAL,
                             SITE_GENERATE, SITE_DEPLOY, SITE_CACHE,
                             SITE_DRIFT, SITE_COMPILE, SITE_LEASE):
            raise ChaosSpecError(f"unknown chaos site {self.site!r}")
        if self.action not in (ACTION_DROP, ACTION_DELAY, ACTION_ERROR,
                               ACTION_CORRUPT, ACTION_OOM):
            raise ChaosSpecError(f"unknown chaos action {self.action!r}")
        if self.action == ACTION_CORRUPT and self.site not in (SITE_WIRE,
                                                               SITE_COMPILE):
            raise ChaosSpecError(
                "chaos action 'corrupt' only applies at site=wire (raw "
                "frame bytes) or site=compile (cache entries on disk)")
        if self.action == ACTION_OOM and self.site != SITE_TRIAL:
            raise ChaosSpecError(
                "chaos action 'oom' only applies at site=trial "
                "(trial-run chokepoint)")
        if self.every < 1:
            raise ChaosSpecError("chaos 'every' must be >= 1")

    def fires(self, site: str, target: str) -> bool:
        """Count a request against this rule; True when the fault applies.
        Deterministic: depends only on the request order seen so far."""
        if site != self.site or self.match not in target:
            return False
        self.hits += 1
        n = self.hits - self.after  # 1-based index past the warm-up window
        if n <= 0:
            return False
        if self.times is not None and n > self.times * self.every:
            return False
        return (n - 1) % self.every == 0


def parse_rules(spec: str) -> List[ChaosRule]:
    rules: List[ChaosRule] = []
    for chunk in spec.split("|"):
        chunk = chunk.strip()
        if not chunk:
            continue
        fields = {}
        for kv in chunk.split(";"):
            kv = kv.strip()
            if not kv:
                continue
            if "=" not in kv:
                raise ChaosSpecError(f"chaos field {kv!r} is not key=value")
            k, v = kv.split("=", 1)
            fields[k.strip()] = v.strip()
        unknown = set(fields) - {"site", "action", "match", "after",
                                 "times", "every", "delay_s", "code"}
        if unknown:
            raise ChaosSpecError(f"unknown chaos fields {sorted(unknown)}")
        try:
            rules.append(ChaosRule(
                site=fields.get("site", ""),
                action=fields.get("action", ""),
                match=fields.get("match", ""),
                after=int(fields.get("after", 0)),
                times=(int(fields["times"]) if "times" in fields else None),
                every=int(fields.get("every", 1)),
                delay_s=float(fields.get("delay_s", 0.05)),
                code=int(fields.get("code", 503)),
            ))
        except (TypeError, ValueError) as e:
            if isinstance(e, ChaosSpecError):
                raise
            raise ChaosSpecError(f"bad chaos rule {chunk!r}: {e}") from e
    return rules


class ChaosController:
    """Holds the active rule set; thread-safe (agent server handlers and
    the admin's sender/heartbeat threads all consult it concurrently)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._rules: List[ChaosRule] = []
        self._installed = False      # programmatic rules win over env
        self._env_value: Optional[str] = None
        self._env_bad: Optional[str] = None

    def install(self, rules: List[ChaosRule]) -> None:
        with self._lock:
            self._rules = list(rules)
            self._installed = True

    def clear(self) -> None:
        with self._lock:
            self._rules = []
            self._installed = False
            self._env_value = None
            self._env_bad = None

    def enabled(self) -> bool:
        with self._lock:
            self._refresh_env_locked()
            return bool(self._rules)

    def hit(self, site: str, target: str) -> Optional[ChaosRule]:
        """Record one request at ``site`` against every rule; return the
        first rule whose schedule fires, else None.

        Fast path without the lock when chaos is provably inactive (no
        installed rules, no rules loaded, env unset): every metadata-store
        statement and every popped shm frame asks this function — they
        must not all contend on one process-global mutex to learn that
        nothing is injected. The unlocked reads are benign: a racing
        install/env-set is picked up by the next call."""
        # lint: unguarded(documented lock-free fast path; a racing install/env-set is picked up by the next call)
        if (not self._installed and not self._rules and not self._env_value
                and not os.environ.get(ENV_VAR)):
            # (a truthy cached _env_value means the env was JUST unset:
            # fall through once so the locked refresh resets the cache)
            return None
        with self._lock:
            self._refresh_env_locked()
            for rule in self._rules:
                if rule.fires(site, target):
                    logger.warning("chaos %s@%s -> %s", site, target,
                                   rule.action)
                    return rule
        return None

    def _refresh_env_locked(self) -> None:  # guarded-by: _lock
        if self._installed:
            return
        value = os.environ.get(ENV_VAR, "")
        if value == self._env_value:
            return
        self._env_value = value
        try:
            self._rules = parse_rules(value)
            self._env_bad = None
        except ChaosSpecError as e:
            self._rules = []
            if value != self._env_bad:
                self._env_bad = value
                logger.error("ignoring unparseable %s: %s", ENV_VAR, e)


_controller = ChaosController()

install = _controller.install
clear = _controller.clear
enabled = _controller.enabled
hit = _controller.hit


def sleep_for(rule: ChaosRule) -> None:
    """Apply a delay rule (kept here so call sites stay one-liners)."""
    time.sleep(rule.delay_s)


def corrupt_bytes(raw: bytes, rule: ChaosRule) -> bytes:
    """Apply a site=wire `corrupt` rule to popped frame bytes.
    Deterministic in the rule's hit count: odd hits truncate (a partial
    write), even hits garble bytes in place (bit rot) — both classes of
    damage a decoder must survive."""
    if not raw:
        return raw
    if rule.hits % 2:
        return raw[: max(len(raw) // 2, 1)]
    buf = bytearray(raw)
    for i in range(0, len(buf), max(len(buf) // 8, 1)):
        buf[i] ^= 0xA5
    return bytes(buf)
