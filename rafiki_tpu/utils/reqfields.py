"""Shared route-boundary field validation.

The dedicated predictor port (predictor/server.py) and the agent predict
relay (placement/agent.py) both accept a client-supplied ``timeout_s``;
this is the single copy of its validate+clamp rule so the two doors
cannot drift (review r5: the copies had already diverged on the 0 case).
Reference analogue: none — the reference's predictor app read no client
timeout at all (/root/reference/rafiki/predictor/app.py).
"""

from __future__ import annotations

import math
from typing import Optional, Tuple


def parse_timeout_s(
    value: object,
    default: float,
    cap: Optional[float] = 300.0,
    label: str = "timeout_s",
) -> Tuple[Optional[float], Optional[str]]:
    """Validate a client-supplied timeout. Returns ``(timeout_s, None)``
    on success or ``(None, error)`` for a 400: malformed input is the
    CLIENT's error, and an unbounded (or NaN) value could pin a handler
    thread past any deadline. The cap bounds CLIENT values only — the
    default is the operator's PREDICT_TIMEOUT_S, trusted config (a
    long-predict deployment may legitimately set it above the cap).
    ``cap=None`` skips the clamp for doors whose callers are themselves
    authenticated infrastructure (the agent predict relay: its senders
    hold the fleet key, and forwarding the admin's resolved timeout must
    not time remote replicas out earlier than local ones)."""
    if value is None:
        return float(default), None
    try:
        t = float(value)  # bools are numbers here; fine
    except (TypeError, ValueError):
        return None, f"{label} must be a number"
    if not math.isfinite(t) or t <= 0:
        return None, f"{label} must be a positive finite number"
    return (t if cap is None else min(t, cap)), None
