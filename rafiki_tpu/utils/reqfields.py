"""Shared route-boundary field validation.

The dedicated predictor port (predictor/server.py) and the agent predict
relay (placement/agent.py) both accept a client-supplied ``timeout_s``;
this is the single copy of its validate+clamp rule so the two doors
cannot drift (review r5: the copies had already diverged on the 0 case).
Reference analogue: none — the reference's predictor app read no client
timeout at all (/root/reference/rafiki/predictor/app.py).
"""

from __future__ import annotations

import math
import socket
import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple


class LowLatencyHandler(BaseHTTPRequestHandler):
    """Base handler for every HTTP door (admin, predictor, agent).

    The stock handler writes a response as (at least) two TCP segments —
    one for the batched header lines, one for the body — and with Nagle
    on, the body segment sits behind the peer's delayed ACK of the header
    segment: ~+40 ms on EVERY response, even over loopback (measured:
    a 13 ms in-process ensemble predict answered in 60 ms over HTTP).
    Buffering ``wfile`` coalesces the whole response into one segment
    (``handle_one_request`` flushes it per request), and TCP_NODELAY
    covers any path that still writes more than once (streamed/oversized
    bodies).
    """

    wbufsize = 1 << 16
    disable_nagle_algorithm = True

    def log_message(self, fmt, *args):  # doors log through `logging`
        pass


class SeveringHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer whose ``stop`` path can sever LIVE connections.

    ``shutdown() + server_close()`` only closes the LISTENER; handler
    threads serving established HTTP/1.1 keep-alive connections keep
    answering until the peer closes or the idle timeout reaps them — so
    an in-process "killed" door (control-plane HA drills, restart tests)
    keeps serving its old clients for up to ``Handler.timeout`` seconds,
    which a real SIGKILL'd process never would. ``sever()`` resets every
    open connection so a stopped door goes dark the way a dead process
    does; clients see a connection reset, which the failover walk
    (client/client.py) absorbs exactly like a refusal."""

    daemon_threads = True

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._conns: set = set()
        self._conns_lock = threading.Lock()

    def process_request_thread(self, request, client_address):
        with self._conns_lock:
            self._conns.add(request)
        try:
            super().process_request_thread(request, client_address)
        finally:
            with self._conns_lock:
                self._conns.discard(request)

    def handle_error(self, request, client_address):
        # severed sockets raise in their handler threads; that teardown
        # is expected — only non-transport errors deserve a traceback
        exc = sys.exc_info()[1]
        if isinstance(exc, OSError):
            return
        super().handle_error(request, client_address)

    def sever(self) -> None:
        with self._conns_lock:
            conns = list(self._conns)
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass


def parse_timeout_s(
    value: object,
    default: float,
    cap: Optional[float] = 300.0,
    label: str = "timeout_s",
) -> Tuple[Optional[float], Optional[str]]:
    """Validate a client-supplied timeout. Returns ``(timeout_s, None)``
    on success or ``(None, error)`` for a 400: malformed input is the
    CLIENT's error, and an unbounded (or NaN) value could pin a handler
    thread past any deadline. The cap bounds CLIENT values only — the
    default is the operator's PREDICT_TIMEOUT_S, trusted config (a
    long-predict deployment may legitimately set it above the cap).
    ``cap=None`` skips the clamp for doors whose callers are themselves
    authenticated infrastructure (the agent predict relay: its senders
    hold the fleet key, and forwarding the admin's resolved timeout must
    not time remote replicas out earlier than local ones)."""
    if value is None:
        return float(default), None
    try:
        t = float(value)  # bools are numbers here; fine
    except (TypeError, ValueError):
        return None, f"{label} must be a number"
    if not math.isfinite(t) or t <= 0:
        return None, f"{label} must be a positive finite number"
    return (t if cap is None else min(t, cap)), None


def read_bounded_body(handler, max_mb: float, fallback_mb: float = 64.0):
    """THE Content-Length guard for every HTTP door (admin, predictor,
    agent — copy-pasted variants drifted, review r5). Returns
    ``(raw_bytes, None)`` or ``(None, (status, error))``:

    - malformed / negative Content-Length -> 400 (reading ``-1`` would
      block until EOF, pinning the handler thread to the socket timeout),
    - oversized -> 413 before a single byte is read or allocated,
    - a broken ``max_mb`` knob (NaN/<=0) falls back instead of rejecting
      everything (``0 <= length <= nan`` is False even for GETs).

    Refusals set ``close_connection`` — the unread body would desync
    HTTP/1.1 keep-alive framing. Callers map the status onto their own
    error channel (the admin door answers 400 via InvalidRequestError;
    the predictor answers the status directly)."""
    if not math.isfinite(max_mb) or max_mb <= 0:
        max_mb = fallback_mb
    try:
        length = int(handler.headers.get("Content-Length") or 0)
    except ValueError:
        handler.close_connection = True
        return None, (400, "bad Content-Length")
    if length < 0:
        handler.close_connection = True
        return None, (400, "bad Content-Length")
    if length > max_mb * (1 << 20):
        handler.close_connection = True
        return None, (413, f"request body exceeds {max_mb:g} MB")
    return (handler.rfile.read(length) if length else b""), None
