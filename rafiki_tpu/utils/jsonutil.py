"""One JSON wire convention for everything that crosses a process
boundary (shm broker, sandbox pipes, agent relays): numpy arrays/scalars
serialize via tolist()/item() at ANY nesting depth; everything else
non-JSON raises TypeError so silent corruption can't pass."""

from __future__ import annotations

import json
from typing import Any


def json_default(o: Any):
    if hasattr(o, "tolist"):
        return o.tolist()
    if hasattr(o, "item"):
        return o.item()
    raise TypeError(
        f"{type(o).__name__} is not JSON-serializable on the wire")


def dumps(obj: Any) -> str:
    return json.dumps(obj, default=json_default)
