"""Bounded, subprocess-isolated probing of the JAX accelerator backend.

The live TPU backend in this deployment is reached through a tunnel that
can wedge: ``jax.devices()`` may then block indefinitely inside PJRT
plugin init, and a signal delivered during first backend init can wedge
the tunnel for every later process (round-3 postmortem, commit 88ab848).
Anything that must stay responsive no matter what — the driver-facing
``bench.py``, ``__graft_entry__.dryrun_multichip`` — therefore must never
initialize the live backend in its own process. This module gives them:

- :func:`probe_device_count` — device count read in a child interpreter
  under a hard timeout; the caller never imports jax.
- :func:`cpu_env` — an environment for child interpreters that cannot
  touch the tunnel (``JAX_PLATFORMS=cpu`` plus the tunnel-hook trigger
  vars stripped, so ``sitecustomize`` never registers the TPU plugin),
  with an ``n``-device virtual CPU mesh.
- :func:`defer_term_signals` — context manager that holds SIGTERM/SIGINT
  delivery across a critical section (first backend init) and re-raises
  afterwards, so this process cannot be the one that wedges the tunnel
  by dying mid-init.

Reference analogue: none — the reference assumed always-healthy local
CUDA devices; a tunnelled accelerator needs an explicit health seam.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import tempfile
import time
from contextlib import contextmanager

# Env vars whose presence makes the baked sitecustomize register the
# remote-TPU PJRT plugin at *interpreter start* of every child process.
# Stripping them is the only reliable way to keep a child off the tunnel:
# JAX_PLATFORMS=cpu alone does not stop the hook from running (it imports
# jax and dials the tunnel before user code executes).
TUNNEL_HOOK_VARS = ("PALLAS_AXON_POOL_IPS",)

PROBE_TIMEOUT_S = float(os.environ.get("RAFIKI_BACKEND_PROBE_TIMEOUT_S", 75))

_PROBE_CODE = (
    "import jax; print('DEVICE_COUNT=%d' % len(jax.devices()))"
)


def _probe_lock_path() -> str:
    """One lock file per machine (not per process): concurrent probes —
    bench retry loops, doctor, several agents booting — would otherwise
    STACK child interpreters onto an already-wedged tunnel (VERDICT r5:
    the driver bench fell back to CPU twice with 'backend probe still
    hung')."""
    return os.environ.get(
        "RAFIKI_BACKEND_PROBE_LOCK",
        os.path.join(tempfile.gettempdir(), "rafiki_backend_probe.lock"))


def _probe_stale_s() -> float:
    """Age past which an abandoned probe child is definitively WEDGED —
    far beyond any legitimate backend init, so killing it can no longer
    be the mid-init signal that wedges the tunnel (round-3 postmortem)."""
    return float(os.environ.get("RAFIKI_BACKEND_PROBE_STALE_S", 600))


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    except OSError:
        return False
    return True


def _read_lock(path: str):
    """(pid, age_seconds) recorded in a lock file, or None if unreadable
    (a corrupt/foreign lock is treated as stale once old enough)."""
    try:
        with open(path) as f:
            pid_s, _, ts_s = f.read().strip().partition(" ")
        return int(pid_s), max(time.time() - float(ts_s), 0.0)
    except (OSError, ValueError):
        return None


def _lock_is_stale(path: str) -> bool:
    """A lock is stale when its recorded holder died, or — when the
    content is unreadable (O_EXCL-create and the pid+ts write are two
    steps, so a racing reader can catch a live holder's lock still
    EMPTY) — when the FILE is older than the stale window. A fresh lock
    is never broken on sight."""
    info = _read_lock(path)
    if info is not None:
        return (not _pid_alive(info[0])) or info[1] > _probe_stale_s()
    try:
        return time.time() - os.path.getmtime(path) > _probe_stale_s()
    except OSError:
        return False  # vanished: nothing left to break


def _break_stale_lock(path: str) -> None:
    """Unlink a lock judged stale — serialized on a flock guard and
    RE-judged under it, so two waiters who both saw the same dead holder
    can't have the second unlink the first one's freshly taken lock."""
    import fcntl

    guard = path + ".guard"
    try:
        g = open(guard, "a")
    except OSError:
        return  # no guard possible: leave the lock to time out
    try:
        fcntl.flock(g, fcntl.LOCK_EX)
        if _lock_is_stale(path):
            try:
                os.unlink(path)
            except OSError:
                pass
    finally:
        try:
            fcntl.flock(g, fcntl.LOCK_UN)
        except OSError:
            pass
        g.close()


def _acquire_probe_lock(timeout_s: float):
    """Take the machine-wide probe lock, breaking locks whose holder died
    or that outlived the stale window. Returns the lock path on success,
    None when a LIVE probe still holds it at timeout — the caller reports
    that instead of stacking another child onto the tunnel."""
    path = _probe_lock_path()
    deadline = time.monotonic() + max(timeout_s, 0.0)
    while True:
        try:
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY, 0o644)
            with os.fdopen(fd, "w") as f:
                f.write(f"{os.getpid()} {time.time()}")
            return path
        except FileExistsError:
            if _lock_is_stale(path):
                _break_stale_lock(path)
                if not os.path.exists(path):
                    continue  # broken: retry the O_EXCL create (fair race)
                # still there — another waiter re-took it, or a foreign
                # owner we can't unlink: wait it out instead of spinning
            if time.monotonic() >= deadline:
                return None
            time.sleep(0.25)
        except OSError:
            # unwritable tmpdir: probing unlocked beats not probing
            return path


def _release_probe_lock(path: str) -> None:
    info = _read_lock(path)
    if info is None or info[0] != os.getpid():
        # not provably ours: someone broke our stale lock and took over
        # (an unreadable lock may be the new holder caught mid-write —
        # the same rule the acquire path lives by)
        return
    try:
        os.unlink(path)
    except OSError:
        pass


def _orphan_ledger_path() -> str:
    return _probe_lock_path() + ".pids"


def _record_orphan(pid: int) -> None:
    """Remember an abandoned probe child so a LATER probe can clean it up
    once it is stale (we never signal it young — that is the tunnel-wedge
    trigger)."""
    try:
        with open(_orphan_ledger_path(), "a") as f:
            f.write(f"{pid} {time.time()}\n")
    except OSError:
        pass


def _pid_is_probe(pid: int) -> bool:
    """True when the pid's cmdline still carries the probe marker — the
    ledger outlives its children, so a recycled pid must never get an
    unrelated process SIGKILLed (same identity-pin idea as the worker
    kill path in placement/process.py)."""
    try:
        with open(f"/proc/{pid}/cmdline", "rb") as f:
            return b"DEVICE_COUNT" in f.read()
    except OSError:
        return False


def cleanup_stale_probes() -> int:
    """Reap probe children abandoned by EARLIER probes: entries older
    than the stale window whose process still exists AND is still a
    probe interpreter get SIGKILLed (they are wedged, long past any
    init), dead or recycled-pid entries are forgotten, young live ones
    are left alone. Returns the number killed. Called before every new
    probe so retry loops (bench.py runs the probe twice) never
    accumulate wedged interpreters."""
    path = _orphan_ledger_path()
    try:
        with open(path) as f:
            lines = f.read().splitlines()
    except OSError:
        return 0
    now = time.time()
    killed, keep = 0, []
    for line in lines:
        try:
            pid_s, _, ts_s = line.strip().partition(" ")
            pid, ts = int(pid_s), float(ts_s)
        except ValueError:
            continue
        if not _pid_alive(pid) or not _pid_is_probe(pid):
            continue
        if now - ts > _probe_stale_s():
            try:
                os.kill(pid, 9)
                killed += 1
            except OSError:
                keep.append(line)
        else:
            keep.append(line)
    try:
        if keep:
            with open(path, "w") as f:
                f.write("\n".join(keep) + "\n")
        else:
            os.unlink(path)
    except OSError:
        pass
    return killed


def cpu_env(n_devices: int | None = None, base: dict | None = None) -> dict:
    """Child-process environment guaranteed to stay off the TPU tunnel,
    optionally with an ``n_devices``-wide virtual CPU mesh."""
    env = dict(os.environ if base is None else base)
    for var in TUNNEL_HOOK_VARS:
        env.pop(var, None)
    env["JAX_PLATFORMS"] = "cpu"
    flags = [
        f for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    ]
    if n_devices:
        flags.append(f"--xla_force_host_platform_device_count={n_devices}")
    env["XLA_FLAGS"] = " ".join(flags).strip()
    return env


def strip_tunnel_hook() -> None:
    """Drop the tunnel-hook trigger vars from *this* process's environ so
    every subsequently spawned child interpreter starts clean (the hook
    adds ~10 s per interpreter on a slow tunnel and hangs on a wedged
    one). Call only after this process has finished its own backend init
    — jax reads these at init time, not after."""
    for var in TUNNEL_HOOK_VARS:
        os.environ.pop(var, None)


def probe_device_count(
    timeout_s: float = PROBE_TIMEOUT_S,
) -> tuple[int, str | None]:
    """(device_count, error) for the live backend, measured in a child
    interpreter so a wedged tunnel costs at most ``timeout_s`` and never
    blocks the caller. ``device_count`` is 0 on any failure; ``error``
    carries the reason (None on success).

    A timed-out probe child is ABANDONED, not killed: a signal delivered
    during first backend init is exactly what wedges the tunnel for every
    later process (round-3 postmortem), so the orphan is left to finish or
    fail on its own — it holds no resources beyond one idle interpreter.
    Abandoned pids land in a ledger; the NEXT probe reaps any that are
    still alive past the stale window (they are wedged, not initializing).

    Concurrent probes serialize on a machine-wide lock file: a wedged
    tunnel must cost bounded probes one at a time, never a stack of hung
    interpreters dialing it at once. A probe that cannot get the lock
    from a live holder within ``timeout_s`` reports that instead of
    running."""
    lock = _acquire_probe_lock(timeout_s)
    if lock is None:
        info = _read_lock(_probe_lock_path())
        holder = f" (pid {info[0]})" if info else ""
        return 0, (
            "another backend probe%s still holds the probe lock after "
            "%.0fs — tunnel likely wedged; not stacking another probe"
            % (holder, timeout_s))
    try:
        # reap earlier probes' wedged orphans BEFORE adding our own
        # child — under the lock, so the ledger's read-modify-write can
        # never race another probe's _record_orphan append
        cleanup_stale_probes()
        out = tempfile.NamedTemporaryFile(
            mode="w+", suffix=".probe", delete=False)
        try:
            proc = subprocess.Popen(
                [sys.executable, "-c", _PROBE_CODE],
                stdout=out, stderr=subprocess.STDOUT,
                env=dict(os.environ), start_new_session=True,
            )
        except OSError as e:
            out.close()
            os.unlink(out.name)
            return 0, f"backend probe failed to launch: {e!r}"
        deadline = time.monotonic() + timeout_s
        while proc.poll() is None and time.monotonic() < deadline:
            time.sleep(0.25)
        if proc.poll() is None:
            out.close()  # leave the file for the orphan; tiny, in tmpdir
            _record_orphan(proc.pid)
            return 0, (
                f"backend probe still hung after {timeout_s:.0f}s "
                f"(abandoned, pid {proc.pid})"
            )
        out.seek(0)
        text = out.read()
        out.close()
        os.unlink(out.name)
        for line in text.splitlines():
            if line.startswith("DEVICE_COUNT="):
                try:
                    return int(line.split("=", 1)[1]), None
                except ValueError:
                    break
        tail = text.strip().splitlines()
        return 0, (
            f"backend probe rc={proc.returncode}: "
            + (tail[-1] if tail else "no output")
        )
    finally:
        _release_probe_lock(lock)


@contextmanager
def defer_term_signals():
    """Hold SIGTERM/SIGINT across a critical section (e.g. first TPU
    backend init) and re-deliver on exit. A process killed mid-init can
    wedge the tunnel for every later process; deferring lets init finish
    (or fail) cleanly first. Signals arriving while blocked in a C call
    are queued by CPython until the call returns, so this also covers the
    init path itself. No-op off the main thread (signal() would raise)."""
    import threading

    if threading.current_thread() is not threading.main_thread():
        yield
        return
    received: list[int] = []
    previous = {}
    for sig in (signal.SIGTERM, signal.SIGINT):
        previous[sig] = signal.signal(
            sig, lambda signum, frame: received.append(signum))
    try:
        yield
    finally:
        for sig, handler in previous.items():
            signal.signal(sig, handler)
        for sig in dict.fromkeys(received):  # each unique signal, in order
            os.kill(os.getpid(), sig)
