"""Bounded, subprocess-isolated probing of the JAX accelerator backend.

The live TPU backend in this deployment is reached through a tunnel that
can wedge: ``jax.devices()`` may then block indefinitely inside PJRT
plugin init, and a signal delivered during first backend init can wedge
the tunnel for every later process (round-3 postmortem, commit 88ab848).
Anything that must stay responsive no matter what — the driver-facing
``bench.py``, ``__graft_entry__.dryrun_multichip`` — therefore must never
initialize the live backend in its own process. This module gives them:

- :func:`probe_device_count` — device count read in a child interpreter
  under a hard timeout; the caller never imports jax.
- :func:`cpu_env` — an environment for child interpreters that cannot
  touch the tunnel (``JAX_PLATFORMS=cpu`` plus the tunnel-hook trigger
  vars stripped, so ``sitecustomize`` never registers the TPU plugin),
  with an ``n``-device virtual CPU mesh.
- :func:`defer_term_signals` — context manager that holds SIGTERM/SIGINT
  delivery across a critical section (first backend init) and re-raises
  afterwards, so this process cannot be the one that wedges the tunnel
  by dying mid-init.

Reference analogue: none — the reference assumed always-healthy local
CUDA devices; a tunnelled accelerator needs an explicit health seam.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import tempfile
import time
from contextlib import contextmanager

# Env vars whose presence makes the baked sitecustomize register the
# remote-TPU PJRT plugin at *interpreter start* of every child process.
# Stripping them is the only reliable way to keep a child off the tunnel:
# JAX_PLATFORMS=cpu alone does not stop the hook from running (it imports
# jax and dials the tunnel before user code executes).
TUNNEL_HOOK_VARS = ("PALLAS_AXON_POOL_IPS",)

PROBE_TIMEOUT_S = float(os.environ.get("RAFIKI_BACKEND_PROBE_TIMEOUT_S", 75))

_PROBE_CODE = (
    "import jax; print('DEVICE_COUNT=%d' % len(jax.devices()))"
)


def cpu_env(n_devices: int | None = None, base: dict | None = None) -> dict:
    """Child-process environment guaranteed to stay off the TPU tunnel,
    optionally with an ``n_devices``-wide virtual CPU mesh."""
    env = dict(os.environ if base is None else base)
    for var in TUNNEL_HOOK_VARS:
        env.pop(var, None)
    env["JAX_PLATFORMS"] = "cpu"
    flags = [
        f for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    ]
    if n_devices:
        flags.append(f"--xla_force_host_platform_device_count={n_devices}")
    env["XLA_FLAGS"] = " ".join(flags).strip()
    return env


def strip_tunnel_hook() -> None:
    """Drop the tunnel-hook trigger vars from *this* process's environ so
    every subsequently spawned child interpreter starts clean (the hook
    adds ~10 s per interpreter on a slow tunnel and hangs on a wedged
    one). Call only after this process has finished its own backend init
    — jax reads these at init time, not after."""
    for var in TUNNEL_HOOK_VARS:
        os.environ.pop(var, None)


def probe_device_count(
    timeout_s: float = PROBE_TIMEOUT_S,
) -> tuple[int, str | None]:
    """(device_count, error) for the live backend, measured in a child
    interpreter so a wedged tunnel costs at most ``timeout_s`` and never
    blocks the caller. ``device_count`` is 0 on any failure; ``error``
    carries the reason (None on success).

    A timed-out probe child is ABANDONED, not killed: a signal delivered
    during first backend init is exactly what wedges the tunnel for every
    later process (round-3 postmortem), so the orphan is left to finish or
    fail on its own — it holds no resources beyond one idle interpreter."""
    out = tempfile.NamedTemporaryFile(
        mode="w+", suffix=".probe", delete=False)
    try:
        proc = subprocess.Popen(
            [sys.executable, "-c", _PROBE_CODE],
            stdout=out, stderr=subprocess.STDOUT,
            env=dict(os.environ), start_new_session=True,
        )
    except OSError as e:
        out.close()
        os.unlink(out.name)
        return 0, f"backend probe failed to launch: {e!r}"
    deadline = time.monotonic() + timeout_s
    while proc.poll() is None and time.monotonic() < deadline:
        time.sleep(0.25)
    if proc.poll() is None:
        out.close()  # leave the file for the orphan; tiny, in tmpdir
        return 0, (
            f"backend probe still hung after {timeout_s:.0f}s "
            f"(abandoned, pid {proc.pid})"
        )
    out.seek(0)
    text = out.read()
    out.close()
    os.unlink(out.name)
    for line in text.splitlines():
        if line.startswith("DEVICE_COUNT="):
            try:
                return int(line.split("=", 1)[1]), None
            except ValueError:
                break
    tail = text.strip().splitlines()
    return 0, (
        f"backend probe rc={proc.returncode}: "
        + (tail[-1] if tail else "no output")
    )


@contextmanager
def defer_term_signals():
    """Hold SIGTERM/SIGINT across a critical section (e.g. first TPU
    backend init) and re-deliver on exit. A process killed mid-init can
    wedge the tunnel for every later process; deferring lets init finish
    (or fail) cleanly first. Signals arriving while blocked in a C call
    are queued by CPython until the call returns, so this also covers the
    init path itself. No-op off the main thread (signal() would raise)."""
    import threading

    if threading.current_thread() is not threading.main_thread():
        yield
        return
    received: list[int] = []
    previous = {}
    for sig in (signal.SIGTERM, signal.SIGINT):
        previous[sig] = signal.signal(
            sig, lambda signum, frame: received.append(signum))
    try:
        yield
    finally:
        for sig, handler in previous.items():
            signal.signal(sig, handler)
        for sig in dict.fromkeys(received):  # each unique signal, in order
            os.kill(os.getpid(), sig)
