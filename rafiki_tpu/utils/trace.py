"""Tracing and profiling — spans for the control plane, XLA profiles for
the compute plane.

The reference has no tracing subsystem at all (SURVEY.md §5.1: no timers,
spans, or profiler hooks anywhere); its nearest artifact is the per-trial
metric stream. This module is the first-class upgrade:

- **Spans**: lightweight wall-clock spans with nesting (thread-local
  stack), collected per trial/service by a `Tracer` and persisted as JSON
  lines under LOGS_DIR. The train worker wraps each trial phase (propose /
  train / evaluate / persist) so every trial ships a breakdown of where its
  time went; the REST layer serves it back (`GET /trials/<id>/trace`).
- **XLA profiles**: `jax_profile(dir)` wraps `jax.profiler.trace` to
  capture a TensorBoard-loadable xplane trace of the device — opt-in via
  the RAFIKI_PROFILE env var because capture is not free. This is the
  TPU-side story the reference could never have (its compute was opaque
  inside user TF1 graphs).
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional

from rafiki_tpu import config

logger = logging.getLogger(__name__)


@dataclass
class Span:
    name: str
    start: float
    end: float = 0.0
    depth: int = 0
    attrs: Dict[str, Any] = field(default_factory=dict)

    @property
    def duration_s(self) -> float:
        return max(self.end - self.start, 0.0)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "start": self.start,
            "end": self.end,
            "duration_s": round(self.duration_s, 6),
            "depth": self.depth,
            **({"attrs": self.attrs} if self.attrs else {}),
        }


class Tracer:
    """Collects spans for one unit of work (a trial, a predict call...).

    Thread-safe for concurrent span entry from worker threads; nesting depth
    is tracked per thread.
    """

    def __init__(self, trace_id: str):
        self.trace_id = trace_id
        self.spans: List[Span] = []
        self._lock = threading.Lock()
        # depth per (tracer, thread) — a module-global thread-local would
        # interleave depths of two tracers active on one thread (e.g. a
        # predict-call tracer inside a trial tracer)
        self._depth: Dict[int, int] = {}

    @contextlib.contextmanager
    def span(self, name: str, **attrs: Any) -> Iterator[Span]:
        tid = threading.get_ident()
        with self._lock:
            depth = self._depth.get(tid, 0)
            self._depth[tid] = depth + 1
        s = Span(name=name, start=time.time(), depth=depth, attrs=attrs)
        try:
            yield s
        finally:
            s.end = time.time()
            with self._lock:
                if depth == 0:
                    self._depth.pop(tid, None)
                else:
                    self._depth[tid] = depth
                self.spans.append(s)

    def summary(self) -> Dict[str, float]:
        """name -> total seconds (top-level occurrences summed)."""
        out: Dict[str, float] = {}
        with self._lock:
            for s in self.spans:
                out[s.name] = out.get(s.name, 0.0) + s.duration_s
        return out

    def save(self, path: Optional[str] = None) -> str:
        path = path or trace_path(self.trace_id)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with self._lock:
            ordered = sorted(self.spans, key=lambda s: s.start)
            with open(path, "w") as f:
                for s in ordered:
                    f.write(json.dumps(s.to_dict()) + "\n")
        return path


def trace_path(trace_id: str) -> str:
    return os.path.join(config.LOGS_DIR, f"trace-{trace_id}.jsonl")


def load_trace(trace_id: str) -> List[Dict[str, Any]]:
    """Read back a saved trace; [] if none was recorded."""
    path = trace_path(trace_id)
    if not os.path.exists(path):
        return []
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


# ---------------------------------------------------------------------------
# XLA / device profiling

def profiling_enabled() -> bool:
    return os.environ.get("RAFIKI_PROFILE", "") not in ("", "0", "false")


@contextlib.contextmanager
def jax_profile(out_dir: Optional[str] = None,
                force: bool = False) -> Iterator[Optional[str]]:
    """Capture an XLA device profile (xplane, TensorBoard-loadable) around
    the body. No-op unless RAFIKI_PROFILE is set (or force=True) — capture
    adds overhead and output is large."""
    if not (force or profiling_enabled()):
        yield None
        return
    out_dir = out_dir or os.path.join(config.LOGS_DIR, "profiles")
    os.makedirs(out_dir, exist_ok=True)
    import jax

    try:
        jax.profiler.start_trace(out_dir)
        started = True
    except Exception:  # already tracing, or backend without profiler support
        logger.exception("jax profiler failed to start")
        started = False
    try:
        yield out_dir if started else None
    finally:
        if started:
            try:
                jax.profiler.stop_trace()
            except Exception:
                logger.exception("jax profiler failed to stop")
