"""Tracing and profiling — spans for the control plane, XLA profiles for
the compute plane.

The reference has no tracing subsystem at all (SURVEY.md §5.1: no timers,
spans, or profiler hooks anywhere); its nearest artifact is the per-trial
metric stream. This module is the first-class upgrade:

- **Spans**: lightweight wall-clock spans with nesting (thread-local
  stack), collected per trial/service by a `Tracer` and persisted as JSON
  lines under LOGS_DIR. The train worker wraps each trial phase (propose /
  train / evaluate / persist) so every trial ships a breakdown of where its
  time went; the REST layer serves it back (`GET /trials/<id>/trace`).
- **XLA profiles**: `jax_profile(dir)` wraps `jax.profiler.trace` to
  capture a TensorBoard-loadable xplane trace of the device — opt-in via
  the RAFIKI_PROFILE env var because capture is not free. This is the
  TPU-side story the reference could never have (its compute was opaque
  inside user TF1 graphs).
- **Request traces** (the serving-plane half): a :class:`TraceContext`
  (trace id + sampling bit, rate ``RAFIKI_TRACE_SAMPLE``) enters at the
  predictor door as the ``X-Rafiki-Trace`` header, rides queue entries,
  the binary wire frame metadata (cache/wire.py, v2), and the fleet
  relay into the inference worker and back — so one sampled predict
  yields ONE span tree covering admission wait → queue wait → codec
  decode → batch assembly → model forward → codec encode → response.
  :class:`RequestTrace` extends :class:`Tracer` with direct span
  recording (monotonic clock; workers on the same host share it) and
  wire import/export; sampled requests slower than
  ``RAFIKI_TRACE_SLOW_MS`` are appended as JSON-lines exemplars to a
  size-rotated file under LOGS_DIR (:func:`record_exemplar`).
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional

from rafiki_tpu import config

logger = logging.getLogger(__name__)


@dataclass
class Span:
    name: str
    start: float
    end: float = 0.0
    depth: int = 0
    attrs: Dict[str, Any] = field(default_factory=dict)

    @property
    def duration_s(self) -> float:
        return max(self.end - self.start, 0.0)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "start": self.start,
            "end": self.end,
            "duration_s": round(self.duration_s, 6),
            "depth": self.depth,
            **({"attrs": self.attrs} if self.attrs else {}),
        }


class Tracer:
    """Collects spans for one unit of work (a trial, a predict call...).

    Thread-safe for concurrent span entry from worker threads; nesting depth
    is tracked per thread.
    """

    def __init__(self, trace_id: str):
        self.trace_id = trace_id
        self.spans: List[Span] = []
        self._lock = threading.Lock()
        # depth per (tracer, thread) — a module-global thread-local would
        # interleave depths of two tracers active on one thread (e.g. a
        # predict-call tracer inside a trial tracer)
        self._depth: Dict[int, int] = {}

    @contextlib.contextmanager
    def span(self, name: str, **attrs: Any) -> Iterator[Span]:
        tid = threading.get_ident()
        with self._lock:
            depth = self._depth.get(tid, 0)
            self._depth[tid] = depth + 1
        s = Span(name=name, start=time.time(), depth=depth, attrs=attrs)
        try:
            yield s
        finally:
            s.end = time.time()
            with self._lock:
                if depth == 0:
                    self._depth.pop(tid, None)
                else:
                    self._depth[tid] = depth
                self.spans.append(s)

    def summary(self) -> Dict[str, float]:
        """name -> total seconds (top-level occurrences summed)."""
        out: Dict[str, float] = {}
        with self._lock:
            for s in self.spans:
                out[s.name] = out.get(s.name, 0.0) + s.duration_s
        return out

    def save(self, path: Optional[str] = None) -> str:
        path = path or trace_path(self.trace_id)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with self._lock:
            ordered = sorted(self.spans, key=lambda s: s.start)
            with open(path, "w") as f:
                for s in ordered:
                    f.write(json.dumps(s.to_dict()) + "\n")
        return path


def trace_path(trace_id: str) -> str:
    return os.path.join(config.LOGS_DIR, f"trace-{trace_id}.jsonl")


def load_trace(trace_id: str) -> List[Dict[str, Any]]:
    """Read back a saved trace; [] if none was recorded."""
    path = trace_path(trace_id)
    if not os.path.exists(path):
        return []
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


# ---------------------------------------------------------------------------
# Request tracing (serving plane)

#: HTTP header carrying the trace context across doors/hops:
#: ``<hex trace id>;s=<0|1>`` (s is the sampling bit — a front door that
#: already decided to sample forces every hop behind it to record)
TRACE_HEADER = "X-Rafiki-Trace"


def sample_rate() -> float:
    """RAFIKI_TRACE_SAMPLE in [0, 1]; 0 (default) disables door-side
    sampling entirely. Malformed values read as 0 — doctor WARNs."""
    raw = os.environ.get("RAFIKI_TRACE_SAMPLE", "")
    if not raw:
        return 0.0
    try:
        return min(max(float(raw), 0.0), 1.0)
    except ValueError:
        return 0.0


def slow_threshold_s() -> float:
    """RAFIKI_TRACE_SLOW_MS: sampled requests at least this slow are
    dumped as JSON-lines exemplars (0 = every sampled request)."""
    try:
        return max(
            float(os.environ.get("RAFIKI_TRACE_SLOW_MS", "0")), 0.0) / 1000.0
    except ValueError:
        return 0.0


def exemplar_max_mb() -> float:
    try:
        return max(
            float(os.environ.get("RAFIKI_TRACE_EXEMPLAR_MAX_MB", "64")), 1.0)
    except ValueError:
        return 64.0


def exemplar_path() -> str:
    return os.path.join(config.LOGS_DIR, "predict_exemplars.jsonl")


class TraceContext:
    """The propagated part of a trace: id + sampling decision. Small and
    serializable — this is what crosses HTTP headers, queue entries, and
    wire frame metadata; the span collection stays in :class:`RequestTrace`
    at whichever hop records."""

    __slots__ = ("trace_id", "sampled")

    def __init__(self, trace_id: str, sampled: bool = True) -> None:
        self.trace_id = trace_id
        self.sampled = bool(sampled)

    def to_header(self) -> str:
        return f"{self.trace_id};s={1 if self.sampled else 0}"

    @classmethod
    def from_header(cls, value: Optional[str]) -> Optional["TraceContext"]:
        """Parse the X-Rafiki-Trace header; None for absent/garbled input
        (a malformed header from an untrusted client must never 500 a
        predict)."""
        if not value:
            return None
        parts = value.strip().split(";")
        tid = parts[0].strip()
        if not tid or len(tid) > 64 or not tid.isalnum():
            return None
        sampled = True
        for p in parts[1:]:
            k, _, v = p.strip().partition("=")
            if k == "s":
                sampled = v.strip() == "1"
        return cls(tid, sampled)

    def to_wire(self) -> Dict[str, Any]:
        return {"id": self.trace_id, "s": 1 if self.sampled else 0}

    @classmethod
    def from_wire(cls, meta: Any) -> Optional["TraceContext"]:
        if not isinstance(meta, dict) or not isinstance(meta.get("id"), str):
            return None
        return cls(meta["id"], bool(meta.get("s", 1)))


class RequestTrace(Tracer):
    """Span collector for ONE predict request, rooted at the serving
    door. Extends :class:`Tracer` (same Span/save machinery the trial
    path uses) with direct interval recording on the MONOTONIC clock —
    worker processes on the same host share CLOCK_MONOTONIC, so spans
    recorded worker-side line up with the door's without clock math —
    and with wire import/export for spans that crossed a hop as
    ``[name, offset_s, duration_s]`` triples."""

    def __init__(self, ctx: TraceContext) -> None:
        super().__init__(ctx.trace_id)
        self.ctx = ctx
        self.t0 = time.monotonic()
        #: set by the queue layer at submit time; the anchor worker-side
        #: queue_wait spans and returned wire spans are measured against
        self.t_submit: Optional[float] = None
        self._dequeued = False

    def add_span(self, name: str, start: float, end: float,
                 depth: int = 0, **attrs: Any) -> None:
        s = Span(name=name, start=start, end=max(end, start), depth=depth,
                 attrs=attrs)
        with self._lock:
            self.spans.append(s)

    def mark_submitted(self) -> None:
        if self.t_submit is None:
            self.t_submit = time.monotonic()

    def mark_dequeued(self, now: Optional[float] = None) -> None:
        """Record the queue_wait span once (a request's entries share one
        trace; the first dequeued entry closes the wait)."""
        with self._lock:
            if self._dequeued:
                return
            self._dequeued = True
        start = self.t_submit if self.t_submit is not None else self.t0
        self.add_span("queue_wait", start, now or time.monotonic(), depth=1)

    def add_wire_spans(self, spans: Any,
                       anchor: Optional[float] = None) -> None:
        """Import spans that crossed a hop as [name, offset_s, duration_s]
        triples, re-anchored at this trace's submit time. Garbled input is
        dropped silently — trace metadata is best-effort decoration, never
        worth failing a served request over."""
        if anchor is None:
            anchor = self.t_submit if self.t_submit is not None else self.t0
        if not isinstance(spans, list):
            return
        for entry in spans:
            try:
                name, off, dur = entry
                self.add_span(str(name)[:64], anchor + float(off),
                              anchor + float(off) + float(dur), depth=1)
            except (TypeError, ValueError):
                continue

    def wire_spans(self, anchor: float) -> List[List[Any]]:
        """Export spans as [name, offset_s, duration_s] relative to
        ``anchor`` — the hop-crossing format of :meth:`add_wire_spans`."""
        with self._lock:
            return [[s.name, round(s.start - anchor, 6),
                     round(s.duration_s, 6)] for s in self.spans]

    def phase_durations(self) -> Dict[str, float]:
        """name -> seconds for the latency histograms. Per name this is
        the MAX single span, not the sum: a multi-trial ensemble records
        one same-named span set per trial and the trials run in
        PARALLEL — summing would report a 3-trial 10 ms forward as one
        30 ms sample, exceeding the request's own wall time. Max is the
        per-phase critical path; for single-trial requests max == sum.
        The exemplar keeps every span, so per-trial detail is not lost."""
        out: Dict[str, float] = {}
        with self._lock:
            for s in self.spans:
                out[s.name] = max(out.get(s.name, 0.0), s.duration_s)
        return out


def start_trace(header_value: Optional[str] = None
                ) -> Optional[RequestTrace]:
    """Door-side entry point: honor an incoming header's sampling bit, or
    make the sampling decision locally at RAFIKI_TRACE_SAMPLE. Returns a
    RequestTrace only when this request is sampled — the unsampled path
    costs one header read and (without a header) one random draw."""
    ctx = TraceContext.from_header(header_value)
    if ctx is None:
        rate = sample_rate()
        if rate <= 0.0:
            return None
        import random
        import uuid

        if random.random() >= rate:
            return None
        ctx = TraceContext(uuid.uuid4().hex, True)
    if not ctx.sampled:
        return None
    return RequestTrace(ctx)


_exemplar_lock = threading.Lock()


def record_exemplar(trace: RequestTrace, e2e_s: float, door: str) -> None:
    """Append one request's span tree as a JSON line to the exemplar
    file, size-rotating at RAFIKI_TRACE_EXEMPLAR_MAX_MB (one ``.1``
    generation — bounded growth, doctor checks it). Best-effort: disk
    trouble must never fail a served request."""
    try:
        path = exemplar_path()
        os.makedirs(os.path.dirname(path), exist_ok=True)
        anchor = trace.t0
        line = json.dumps({
            "trace_id": trace.trace_id,
            "ts": round(time.time(), 3),
            "door": door,
            "e2e_s": round(e2e_s, 6),
            "spans": [
                {"name": s.name, "offset_s": round(s.start - anchor, 6),
                 "duration_s": round(s.duration_s, 6),
                 **({"attrs": s.attrs} if s.attrs else {})}
                for s in sorted(trace.spans, key=lambda s: s.start)
            ],
        })
        cap_bytes = int(exemplar_max_mb() * (1 << 20))
        with _exemplar_lock:
            try:
                if os.path.getsize(path) >= cap_bytes:
                    os.replace(path, path + ".1")
            except OSError:
                pass
            with open(path, "a") as f:
                f.write(line + "\n")
    except Exception:
        logger.debug("exemplar write failed", exc_info=True)


# ---------------------------------------------------------------------------
# XLA / device profiling

def profiling_enabled() -> bool:
    return os.environ.get("RAFIKI_PROFILE", "") not in ("", "0", "false")


@contextlib.contextmanager
def jax_profile(out_dir: Optional[str] = None,
                force: bool = False) -> Iterator[Optional[str]]:
    """Capture an XLA device profile (xplane, TensorBoard-loadable) around
    the body. No-op unless RAFIKI_PROFILE is set (or force=True) — capture
    adds overhead and output is large."""
    if not (force or profiling_enabled()):
        yield None
        return
    out_dir = out_dir or os.path.join(config.LOGS_DIR, "profiles")
    os.makedirs(out_dir, exist_ok=True)
    import jax

    try:
        jax.profiler.start_trace(out_dir)
        started = True
    except Exception:  # already tracing, or backend without profiler support
        logger.exception("jax profiler failed to start")
        started = False
    try:
        yield out_dir if started else None
    finally:
        if started:
            try:
                jax.profiler.stop_trace()
            except Exception:
                logger.exception("jax profiler failed to stop")
