"""Authentication: password hashing and signed API tokens.

Parity with the reference's JWT + bcrypt auth (reference rafiki/utils/auth.py,
admin/admin.py:635-640) using only the stdlib: scrypt for password hashing and
HMAC-SHA256-signed tokens (JWT-shaped payload: user id, type, expiry).
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import os
import time
from typing import Any, Dict, Optional

from rafiki_tpu import config


class UnauthorizedError(Exception):
    pass


# -- passwords -------------------------------------------------------------


def hash_password(password: str) -> str:
    salt = os.urandom(16)
    digest = hashlib.scrypt(
        password.encode(), salt=salt, n=2**14, r=8, p=1, dklen=32
    )
    return base64.b64encode(salt + digest).decode()


def verify_password(password: str, password_hash: str) -> bool:
    try:
        raw = base64.b64decode(password_hash.encode())
        salt, digest = raw[:16], raw[16:]
        check = hashlib.scrypt(
            password.encode(), salt=salt, n=2**14, r=8, p=1, dklen=32
        )
        return hmac.compare_digest(digest, check)
    except (ValueError, TypeError):
        return False


# -- tokens ----------------------------------------------------------------


def _b64(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def _unb64(s: str) -> bytes:
    return base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))


def generate_token(payload: Dict[str, Any], secret: Optional[str] = None) -> str:
    secret = secret or config.APP_SECRET
    body = dict(payload)
    body.setdefault("exp", time.time() + config.TOKEN_TTL_HOURS * 3600)
    encoded = _b64(json.dumps(body).encode())
    sig = hmac.new(secret.encode(), encoded.encode(), hashlib.sha256).digest()
    return f"{encoded}.{_b64(sig)}"


def decode_token(token: str, secret: Optional[str] = None) -> Dict[str, Any]:
    secret = secret or config.APP_SECRET
    try:
        encoded, sig = token.split(".")
        expect = hmac.new(secret.encode(), encoded.encode(), hashlib.sha256).digest()
        if not hmac.compare_digest(_unb64(sig), expect):
            raise UnauthorizedError("Invalid token signature")
        payload = json.loads(_unb64(encoded))
    except (ValueError, json.JSONDecodeError):
        raise UnauthorizedError("Malformed token")
    if payload.get("exp", 0) < time.time():
        raise UnauthorizedError("Token expired")
    return payload


def auth_check(payload: Dict[str, Any], allowed_types: Optional[list] = None) -> None:
    """Raise unless the token's user type is in `allowed_types`
    (per-route RBAC, reference rafiki/utils/auth.py:28-45)."""
    if allowed_types is not None and payload.get("user_type") not in allowed_types:
        raise UnauthorizedError(
            f"User type {payload.get('user_type')!r} not allowed"
        )
