"""Process-local metrics registry with Prometheus text exposition.

PRs 1-5 grew rich *local* signals — queue depth/expired/shed counters,
admission EWMA wait, hedge counters, ``TRAINING_STATS`` — but they lived
in ad-hoc dicts scattered across ``/healthz`` payloads: no common naming,
no histograms, no way to scrape them with standard tooling. This module
is the unified store those signals migrate into:

- **Counter / Gauge / Histogram** with labels, each child guarded by its
  own tiny lock (an ``inc`` is one lock + one add — the hot serving path
  must not convoy on a registry-global lock);
- **fixed-log-bucket histograms** so latency percentiles (p50/p95/p99)
  come from the serving door itself, not from client-side sampling;
- **Prometheus text exposition** (format 0.0.4) served at ``GET
  /metrics`` on all three HTTP doors (admin, agent, dedicated
  predictor port);
- a **bounded ring-buffer time series** per named series at ~1 s
  resolution (``RAFIKI_METRICS_RING_S`` seconds of history) for the
  handful of autoscaler-grade signals — queue depth, shed rate, EWMA
  wait — that a control loop wants as a short series, not a scalar.

The registry is process-local by design: in-process/thread placements
surface everything through the admin door; separate worker processes
keep their own registries (their counters still reach the admin through
the existing SERVING_STATS event relay). ``RAFIKI_METRICS=0`` turns every
write into a no-op — the kill switch the bench overhead guard measures
against.

Metric names are a STABLE contract (docs/observability.md carries the
catalog; tests/test_metrics.py snapshots them — renames fail the test).
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

# default histogram buckets: log ladder from 100 us to ~200 s (factor 2)
# — wide enough for a sub-ms codec phase and a 30 s SLO miss in one
# histogram, coarse enough that a snapshot stays small
_DEFAULT_BUCKETS = tuple(1e-4 * (2.0 ** i) for i in range(22))


def metrics_enabled() -> bool:
    """RAFIKI_METRICS=0 turns every registry write into a no-op (the
    overhead kill switch; resolved per call like the other lazy knobs so
    tests and the bench guard phase can flip it at runtime)."""
    return os.environ.get("RAFIKI_METRICS", "1") not in ("0", "false")


def ring_window_s() -> int:
    try:
        return max(int(os.environ.get("RAFIKI_METRICS_RING_S", "300")), 10)
    except ValueError:
        return 300


def _escape_label(value: str) -> str:
    return (str(value).replace("\\", r"\\").replace('"', r"\"")
            .replace("\n", r"\n"))


def _fmt(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


class _Child:
    """One (metric, label-values) cell. Own lock: hot-path increments
    from different label sets never contend."""

    __slots__ = ("_lock", "_value")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0.0

    def value(self) -> float:
        with self._lock:
            return self._value


class _CounterChild(_Child):
    __slots__ = ()

    def inc(self, amount: float = 1.0) -> None:
        if not metrics_enabled():
            return
        with self._lock:
            self._value += amount


class _GaugeChild(_Child):
    __slots__ = ()

    def set(self, value: float) -> None:
        if not metrics_enabled():
            return
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        if not metrics_enabled():
            return
        with self._lock:
            self._value += amount


class _HistogramChild:
    __slots__ = ("_lock", "_buckets", "_counts", "_sum", "_count")

    def __init__(self, buckets: Tuple[float, ...]) -> None:
        self._lock = threading.Lock()
        self._buckets = buckets
        self._counts = [0] * (len(buckets) + 1)  # +1: the +Inf bucket
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float) -> None:
        if not metrics_enabled():
            return
        v = float(value)
        if not math.isfinite(v):
            return
        # linear scan beats bisect at this bucket count for small values
        # (latencies land in the first few buckets); fall through to +Inf
        idx = len(self._buckets)
        for i, b in enumerate(self._buckets):
            if v <= b:
                idx = i
                break
        with self._lock:
            self._counts[idx] += 1
            self._sum += v
            self._count += 1

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            counts = list(self._counts)
            total, s = self._count, self._sum
        cum, cum_counts = 0, []
        for c in counts:
            cum += c
            cum_counts.append(cum)
        return {
            "count": total,
            "sum": round(s, 9),
            "buckets": [[_fmt(b), cum_counts[i]]
                        for i, b in enumerate(self._buckets)]
                       + [["+Inf", cum_counts[-1]]],
        }

    def quantile(self, q: float) -> Optional[float]:
        """Bucket-resolution quantile estimate (the bucket's upper bound
        whose cumulative count first reaches rank q) — what the bench
        reports as door-histogram p50/p95/p99."""
        with self._lock:
            counts = list(self._counts)
            total = self._count
        if total <= 0:
            return None
        rank = q * total
        cum = 0.0
        for i, c in enumerate(counts):
            cum += c
            if cum >= rank:
                return (self._buckets[i] if i < len(self._buckets)
                        else self._buckets[-1] * 2)
        return self._buckets[-1] * 2

    def value(self) -> float:  # uniform snapshot interface
        with self._lock:
            return float(self._count)


class _Metric:
    """Base: a named family of children keyed by label values."""

    kind = "untyped"

    def __init__(self, name: str, help_text: str,
                 label_names: Sequence[str] = ()) -> None:
        self.name = name
        self.help = help_text
        self.label_names = tuple(label_names)
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], Any] = {}

    def _make_child(self):
        raise NotImplementedError

    def labels(self, *values: Any):
        key = tuple(str(v) for v in values)
        if len(key) != len(self.label_names):
            raise ValueError(
                f"{self.name}: expected {len(self.label_names)} label "
                f"value(s) {self.label_names}, got {len(key)}")
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.setdefault(key, self._make_child())
        return child

    def children(self) -> Dict[Tuple[str, ...], Any]:
        with self._lock:
            return dict(self._children)

    def _label_str(self, key: Tuple[str, ...]) -> str:
        if not key:
            return ""
        pairs = ",".join(
            f'{n}="{_escape_label(v)}"'
            for n, v in zip(self.label_names, key))
        return "{" + pairs + "}"

    def render(self) -> List[str]:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} {self.kind}"]
        for key, child in sorted(self.children().items()):
            lines.append(f"{self.name}{self._label_str(key)} "
                         f"{_fmt(child.value())}")
        return lines


class Counter(_Metric):
    kind = "counter"

    def _make_child(self) -> _CounterChild:
        return _CounterChild()

    def inc(self, amount: float = 1.0) -> None:
        self.labels().inc(amount)

    def value(self, *label_values: Any) -> float:
        return self.labels(*label_values).value()


class Gauge(_Metric):
    kind = "gauge"

    def _make_child(self) -> _GaugeChild:
        return _GaugeChild()

    def set(self, value: float) -> None:
        self.labels().set(value)

    def value(self, *label_values: Any) -> float:
        return self.labels(*label_values).value()


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name: str, help_text: str,
                 label_names: Sequence[str] = (),
                 buckets: Optional[Sequence[float]] = None) -> None:
        super().__init__(name, help_text, label_names)
        self.buckets = tuple(sorted(buckets)) if buckets else _DEFAULT_BUCKETS

    def _make_child(self) -> _HistogramChild:
        return _HistogramChild(self.buckets)

    def observe(self, value: float) -> None:
        self.labels().observe(value)

    def render(self) -> List[str]:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} histogram"]
        for key, child in sorted(self.children().items()):
            snap = child.snapshot()
            base = self._label_str(key)
            for le, cum in snap["buckets"]:
                if base:
                    lbl = base[:-1] + f',le="{le}"' + "}"
                else:
                    lbl = '{le="' + le + '"}'
                lines.append(f"{self.name}_bucket{lbl} {cum}")
            lines.append(f"{self.name}_sum{base} {_fmt(snap['sum'])}")
            lines.append(f"{self.name}_count{base} {snap['count']}")
        return lines


class Ring:
    """Bounded ~1 s-resolution time series: one slot per wall-clock
    second over a ``ring_window_s()`` window, last-write-wins within a
    second (``record``) or summed within a second (``add`` — shed *rates*
    want per-second sums, depth *levels* want the latest sample).
    O(window) memory, O(1) writes — safe to feed from the serving path."""

    __slots__ = ("_lock", "_slots", "_t", "_v")

    def __init__(self, slots: Optional[int] = None) -> None:
        self._lock = threading.Lock()
        self._slots = slots or ring_window_s()
        self._t = [0] * self._slots
        self._v = [0.0] * self._slots

    def record(self, value: float) -> None:
        if not metrics_enabled():
            return
        s = int(time.time())
        i = s % self._slots
        with self._lock:
            self._t[i] = s
            self._v[i] = float(value)

    def add(self, value: float = 1.0) -> None:
        if not metrics_enabled():
            return
        s = int(time.time())
        i = s % self._slots
        with self._lock:
            if self._t[i] != s:
                self._t[i] = s
                self._v[i] = 0.0
            self._v[i] += float(value)

    def series(self) -> List[List[float]]:
        """Valid (epoch_second, value) samples within the window, oldest
        first — the autoscaler-facing view."""
        now = int(time.time())
        with self._lock:
            pairs = [(t, v) for t, v in zip(self._t, self._v)
                     if t and now - t < self._slots]
        return [[t, v] for t, v in sorted(pairs)]


class Registry:
    """Get-or-create metric store. Creation is idempotent by name so
    module-level callers can't race; re-declaring a name with a different
    type or label set raises — names are a stable contract."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}  # guarded-by: _lock
        self._rings: Dict[str, Ring] = {}  # guarded-by: _lock

    def _get_or_create(self, cls, name: str, help_text: str,
                       label_names: Sequence[str], **kw) -> _Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls) \
                        or m.label_names != tuple(label_names):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{type(m).__name__}{m.label_names}")
                return m
            m = cls(name, help_text, label_names, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help_text: str = "",
                label_names: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help_text, label_names)

    def gauge(self, name: str, help_text: str = "",
              label_names: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help_text, label_names)

    def histogram(self, name: str, help_text: str = "",
                  label_names: Sequence[str] = (),
                  buckets: Optional[Sequence[float]] = None) -> Histogram:
        return self._get_or_create(
            Histogram, name, help_text, label_names, buckets=buckets)

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def ring(self, name: str) -> Ring:
        with self._lock:
            r = self._rings.get(name)
            if r is None:
                r = self._rings[name] = Ring()
            return r

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._metrics)

    def render(self) -> str:
        """Prometheus text exposition (format 0.0.4) of every metric."""
        with self._lock:
            metrics = [self._metrics[n] for n in sorted(self._metrics)]
        lines: List[str] = []
        for m in metrics:
            lines.extend(m.render())
        return "\n".join(lines) + "\n"

    def snapshot(self) -> Dict[str, Any]:
        """JSON view: scalar metrics flattened to {name{labels}: value},
        histograms to their bucket snapshots, plus every ring series —
        the machine-friendly twin of the Prometheus text (``GET
        /metrics?format=json``)."""
        out: Dict[str, Any] = {"metrics": {}, "rings": {}}
        with self._lock:
            metrics = dict(self._metrics)
            rings = dict(self._rings)
        for name, m in sorted(metrics.items()):
            for key, child in sorted(m.children().items()):
                label = name + m._label_str(key)
                if isinstance(m, Histogram):
                    out["metrics"][label] = child.snapshot()
                else:
                    out["metrics"][label] = child.value()
        for name, r in sorted(rings.items()):
            out["rings"][name] = r.series()
        return out

    def reset(self) -> None:
        """Drop every metric and ring (test isolation only — live callers
        hold child references that survive a reset but stop rendering)."""
        with self._lock:
            self._metrics.clear()
            self._rings.clear()


#: THE process registry — every subsystem registers here so all three
#: HTTP doors expose one coherent catalog.
REGISTRY = Registry()


def http_payload(fmt: str = "text") -> Tuple[bytes, str]:
    """Body + Content-Type for a GET /metrics response — the ONE copy of
    the exposition logic shared by the admin, agent, and predictor doors.
    ``fmt="json"`` returns the snapshot (including ring series) instead
    of Prometheus text."""
    if fmt == "json":
        return (json.dumps(REGISTRY.snapshot()).encode(),
                "application/json")
    return REGISTRY.render().encode(), PROMETHEUS_CONTENT_TYPE


def serve_http(handler, query: str = "") -> None:
    """Answer one GET /metrics on a BaseHTTPRequestHandler — the single
    response path all three doors share (``?format=json`` selects the
    snapshot + ring series)."""
    data, ctype = http_payload(
        "json" if "format=json" in (query or "") else "text")
    handler.send_response(200)
    handler.send_header("Content-Type", ctype)
    handler.send_header("Content-Length", str(len(data)))
    handler.end_headers()
    handler.wfile.write(data)


def parse_prometheus(text: str) -> Dict[str, float]:
    """Tiny exposition parser (tests + doctor): {'name{labels}': value}.
    Not a full PromQL client — just enough to verify the text is
    well-formed and read sample values back."""
    out: Dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            key, value = line.rsplit(" ", 1)
            out[key] = float(value)
        except ValueError as e:
            raise ValueError(f"unparseable exposition line {line!r}") from e
    return out
