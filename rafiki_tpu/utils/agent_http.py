"""One wire protocol for talking to host agents (placement/agent.py).

Both the control plane (placement/hosts.py `_AgentHandle`) and the serving
data plane (cache/fleet.py `HttpWorkerQueue`) speak to agents; this is the
single copy of the request/auth/error-decode logic so the two cannot
drift. Callers map the two error types onto their own domains.

Fleet health hardening lives here too, shared by both planes:

- **Bounded retry** with exponential backoff + jitter for *idempotent*
  calls (GETs by default; callers assert idempotency for POSTs like
  ``/services/<id>/stop``). Non-idempotent calls never retry — the caller
  owns the ambiguous-create problem (placement/hosts.py).
- **Per-agent circuit breaker**: consecutive transport failures open the
  circuit; while open every call fails fast (<1 ms, vs the 10 s transport
  timeout) with :class:`AgentCircuitOpenError`; after a cooldown one
  half-open probe is let through — success closes the circuit, failure
  re-opens it. An HTTP-level error is a breaker *success* (the host
  answered); only transport failures count against it.
- **Fault injection**: the ``RAFIKI_CHAOS`` hook (utils/chaos.py) fires
  inside the attempt loop, so injected faults exercise the retry and
  breaker machinery exactly like real ones.
"""

from __future__ import annotations

import http.client
import json
import logging
import random
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Dict, Optional

from rafiki_tpu import config
from rafiki_tpu.utils import chaos

logger = logging.getLogger(__name__)

AGENT_KEY_HEADER = "X-Rafiki-Agent-Key"
# control-plane HA (docs/failure-model.md "Control-plane HA"): the
# admin's leadership epoch rides every control call; agents remember the
# highest epoch seen and answer STALE_EPOCH_STATUS to any mutating call
# carrying a lower one — the agent-side half of epoch fencing.
ADMIN_EPOCH_HEADER = "X-Rafiki-Admin-Epoch"
STALE_EPOCH_STATUS = 412  # Precondition Failed: typed, never retried

# breaker states (surfaced by placement/hosts.py agent_health and doctor)
BREAKER_CLOSED = "CLOSED"
BREAKER_OPEN = "OPEN"
BREAKER_HALF_OPEN = "HALF_OPEN"


class AgentHTTPError(Exception):
    """The agent answered with an error status; ``code``/``message``
    carry the decoded payload."""

    def __init__(self, code: int, message: str):
        super().__init__(message)
        self.code = code
        self.message = message


class AgentTransportError(Exception):
    """The agent could not be reached (connect/timeout/socket error)."""


class AgentCircuitOpenError(AgentTransportError):
    """Fail-fast refusal: this agent's circuit breaker is open. Subclasses
    AgentTransportError so existing callers treat it as unreachable."""


class CircuitBreaker:
    """Per-agent breaker: CLOSED -> (threshold consecutive transport
    failures) -> OPEN -> (cooldown elapses) -> HALF_OPEN, where exactly one
    probe call is admitted; its outcome closes or re-opens the circuit."""

    def __init__(self, threshold: int, cooldown_s: float):
        self.threshold = max(int(threshold), 1)
        self.cooldown_s = float(cooldown_s)
        self._lock = threading.Lock()
        self._failures = 0
        self._state = BREAKER_CLOSED
        self._opened_at = 0.0
        self._probing = False

    @property
    def state(self) -> str:
        with self._lock:
            if (self._state == BREAKER_OPEN
                    and time.monotonic() - self._opened_at >= self.cooldown_s):
                return BREAKER_HALF_OPEN
            return self._state

    def allow(self) -> bool:
        """May a call proceed right now? In the half-open window only one
        in-flight probe is admitted; siblings keep failing fast until its
        verdict lands."""
        with self._lock:
            if self._state == BREAKER_CLOSED:
                return True
            if self._state == BREAKER_HALF_OPEN:
                if self._probing:
                    return False
                self._probing = True
                return True
            if time.monotonic() - self._opened_at >= self.cooldown_s:
                self._state = BREAKER_HALF_OPEN
                self._probing = True
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._state = BREAKER_CLOSED
            self._probing = False

    def record_failure(self) -> None:
        with self._lock:
            self._failures += 1
            if (self._state != BREAKER_CLOSED
                    or self._failures >= self.threshold):
                self._state = BREAKER_OPEN
                self._opened_at = time.monotonic()
                self._probing = False


_breakers: Dict[str, CircuitBreaker] = {}
_breakers_lock = threading.Lock()


def get_breaker(addr: str) -> CircuitBreaker:
    with _breakers_lock:
        br = _breakers.get(addr)
        if br is None:
            br = _breakers[addr] = CircuitBreaker(
                config.AGENT_BREAKER_THRESHOLD,
                config.AGENT_BREAKER_COOLDOWN_S)
        return br


def reset_breaker(addr: Optional[str] = None) -> None:
    """Close one agent's breaker (heartbeat recovery) or, with no addr,
    drop the whole registry (test isolation)."""
    with _breakers_lock:
        if addr is None:
            _breakers.clear()
        elif addr in _breakers:
            _breakers[addr].record_success()


def breaker_states() -> Dict[str, str]:
    with _breakers_lock:
        return {addr: br.state for addr, br in _breakers.items()}


def _raw_call(
    addr: str,
    method: str,
    path: str,
    body: Optional[Dict[str, Any]],
    key: Optional[str],
    timeout_s: float,
    wire_frames: bool = False,
    epoch: Optional[int] = None,
) -> Dict[str, Any]:
    rule = chaos.hit(chaos.SITE_CALL_AGENT, f"{addr} {path}")
    if rule is not None:
        if rule.action == chaos.ACTION_DELAY:
            chaos.sleep_for(rule)
        elif rule.action == chaos.ACTION_DROP:
            raise AgentTransportError(f"{addr}: chaos-injected drop")
        elif rule.action == chaos.ACTION_ERROR:
            raise AgentHTTPError(rule.code, "chaos-injected error")
    url = f"http://{addr}{path}"
    # the serving data plane (cache/fleet.py) negotiates the binary wire
    # codec: ndarrays in `body` ride as raw bytes instead of JSON float
    # text. Control-plane calls stay plain JSON. Responses are sniffed
    # either way, so a binary-answering peer never needs a second flag.
    from rafiki_tpu.cache import wire as _wire

    data = None
    ctype = "application/json"
    if body is not None:
        if wire_frames:
            data = _wire.dumps(body)  # JSON framing if RAFIKI_WIRE_BINARY=0
            if _wire.is_frame(data):
                ctype = _wire.CONTENT_TYPE
        else:
            # jsonutil convention: ndarrays as float text — the shape
            # data-plane bodies take when the peer can't decode frames
            from rafiki_tpu.utils.jsonutil import json_default

            data = json.dumps(body, default=json_default).encode()
    req = urllib.request.Request(url, data=data, method=method)
    req.add_header("Content-Type", ctype)
    if key:
        req.add_header(AGENT_KEY_HEADER, key)
    if epoch is not None:
        req.add_header(ADMIN_EPOCH_HEADER, str(int(epoch)))
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            raw = resp.read() or b"{}"
            if _wire.is_frame(raw):
                try:
                    return _wire.decode(raw)
                except _wire.WireFormatError as e:
                    raise AgentTransportError(
                        f"{addr}: garbled wire response: {e}") from e
            return json.loads(raw)
    except urllib.error.HTTPError as e:
        try:
            message = json.loads(e.read() or b"{}").get("error", str(e))
        except (ValueError, TypeError):
            message = str(e)
        raise AgentHTTPError(e.code, message) from e
    except (urllib.error.URLError, OSError, TimeoutError,
            http.client.HTTPException) as e:
        # HTTPException covers garbled/truncated responses (BadStatusLine,
        # IncompleteRead) that urllib does not wrap — a half-dead host
        raise AgentTransportError(f"{addr}: {e}") from e


def call_agent(
    addr: str,
    method: str,
    path: str,
    body: Optional[Dict[str, Any]] = None,
    key: Optional[str] = None,
    timeout_s: float = 10.0,
    idempotent: Optional[bool] = None,
    use_breaker: bool = True,
    wire_frames: bool = False,
    epoch: Optional[int] = None,
) -> Dict[str, Any]:
    """One request to a host agent, with retry + circuit breaking.

    ``idempotent`` (default: GETs only) enables bounded retry with
    exponential backoff + jitter on transport failures. ``use_breaker``
    is disabled only by the heartbeat monitor, whose probes must reach
    the wire regardless of breaker state — they ARE the recovery signal.
    ``wire_frames`` ships the body as one binary wire frame
    (cache/wire.py) — data-plane callers only, after negotiating support
    via the agent's /healthz ``wire_versions`` advertisement.
    ``epoch`` stamps the admin's leadership epoch on the request
    (control-plane HA): the agent refuses mutating calls from a lower
    epoch with STALE_EPOCH_STATUS — an AgentHTTPError here, which never
    retries (the host answered; the refusal is the answer).
    """
    if idempotent is None:
        idempotent = method.upper() == "GET"
    breaker = get_breaker(addr) if use_breaker else None
    if breaker is not None and not breaker.allow():
        raise AgentCircuitOpenError(
            f"{addr}: circuit open (agent recently unreachable; next probe "
            f"within {breaker.cooldown_s:.1f}s)")
    attempts = 1 + (config.AGENT_RETRY_MAX if idempotent else 0)
    backoff = config.AGENT_RETRY_BACKOFF_S
    last: Optional[AgentTransportError] = None
    for attempt in range(attempts):
        if attempt:
            # full jitter on an exponential base: decorrelates the retry
            # storms of many callers hitting one recovering agent
            time.sleep(backoff * (2 ** (attempt - 1)) * random.uniform(0.5, 1.5))
        try:
            out = _raw_call(addr, method, path, body, key, timeout_s,
                            wire_frames=wire_frames, epoch=epoch)
        except AgentHTTPError:
            # the host answered — alive, whatever the status code says
            if breaker is not None:
                breaker.record_success()
            raise
        except AgentTransportError as e:
            last = e
            from rafiki_tpu.utils.metrics import REGISTRY

            REGISTRY.counter(
                "rafiki_agent_transport_failures_total",
                "agent calls that failed at the transport layer").inc()
            if breaker is not None:
                breaker.record_failure()
                if attempt + 1 < attempts and not breaker.allow():
                    break  # retries must not tunnel through an open circuit
            if attempt + 1 < attempts:
                logger.info("agent %s transport failure (%s); retry %d/%d",
                            addr, e, attempt + 1, attempts - 1)
            continue
        except BaseException:
            # anything unexpected must still release a half-open probe
            # slot, or the breaker would fence this agent forever
            if breaker is not None:
                breaker.record_failure()
            raise
        if breaker is not None:
            breaker.record_success()
        return out
    assert last is not None
    raise last
