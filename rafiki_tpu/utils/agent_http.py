"""One wire protocol for talking to host agents (placement/agent.py).

Both the control plane (placement/hosts.py `_AgentHandle`) and the serving
data plane (cache/fleet.py `HttpWorkerQueue`) speak to agents; this is the
single copy of the request/auth/error-decode logic so the two cannot
drift. Callers map the two error types onto their own domains.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Any, Dict, Optional

AGENT_KEY_HEADER = "X-Rafiki-Agent-Key"


class AgentHTTPError(Exception):
    """The agent answered with an error status; ``code``/``message``
    carry the decoded payload."""

    def __init__(self, code: int, message: str):
        super().__init__(message)
        self.code = code
        self.message = message


class AgentTransportError(Exception):
    """The agent could not be reached (connect/timeout/socket error)."""


def call_agent(
    addr: str,
    method: str,
    path: str,
    body: Optional[Dict[str, Any]] = None,
    key: Optional[str] = None,
    timeout_s: float = 10.0,
) -> Dict[str, Any]:
    url = f"http://{addr}{path}"
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    req.add_header("Content-Type", "application/json")
    if key:
        req.add_header(AGENT_KEY_HEADER, key)
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            return json.loads(resp.read() or b"{}")
    except urllib.error.HTTPError as e:
        try:
            message = json.loads(e.read() or b"{}").get("error", str(e))
        except (ValueError, TypeError):
            message = str(e)
        raise AgentHTTPError(e.code, message) from None
    except (urllib.error.URLError, OSError, TimeoutError) as e:
        raise AgentTransportError(f"{addr}: {e}") from None
