"""Hot-path compute ops: pallas TPU kernels with XLA fallbacks.

Every op here has (a) a pure-XLA reference implementation that works on any
backend and defines the semantics + gradients, and (b) where it pays off, a
pallas kernel for TPU (flash attention, fused softmax-cross-entropy). Kernels
run in interpreter mode off-TPU so the unit-test mesh (8 fake CPU devices)
exercises the same code path.
"""

from rafiki_tpu.ops.attention import multi_head_attention, mha_reference  # noqa: F401
from rafiki_tpu.ops.flash_attention import flash_attention  # noqa: F401
