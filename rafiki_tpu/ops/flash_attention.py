"""Blockwise (flash) attention as a pallas TPU kernel.

Computes softmax(q k^T * scale [+ causal mask]) v without materializing the
(S, S) score matrix in HBM: the kv sequence is streamed through VMEM in
blocks while running max/sum statistics keep the softmax numerically exact
(online softmax). This is the memory-bound op where HBM traffic — not FLOPs
— sets the ceiling, hence a hand kernel rather than trusting XLA fusion.

The backward pass is defined by recomputation: the custom VJP re-runs the
reference attention under ``jax.vjp``. That trades one extra forward of
FLOPs for never storing the attention matrix — the same rematerialisation
flash-attention backward does, without a second hand kernel to maintain.

The reference system has no analogue (its deepest compute is a TF1 GAN,
reference pg_gans.py); this exists for the transformer model zoo (ViT/BERT)
and the long-context path (parallel/ring.py reuses it per-block).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
NEG_INF = -1e30


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, sm_scale: float, causal: bool,
                  q_len: int, kv_len: int, block_k: int):
    """One (batch*head, q-block) program: stream kv blocks, online softmax."""
    q = q_ref[0].astype(jnp.float32) * sm_scale          # (Bq, Dh)
    block_q, dh = q.shape
    n_kv = k_ref.shape[1] // block_k
    q_start = pl.program_id(1) * block_q
    # End-aligned causal offset, matching mha_reference's tril(k=skv-sq):
    # query i attends keys j <= i + (kv_len - q_len). With sq == skv this is
    # the usual triangle; in decode shapes (sq=1) the query sees all keys.
    causal_off = kv_len - q_len

    def body(j, carry):
        acc, m, l = carry
        k = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)  # (Bq, Bk)
        k_idx = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = k_idx < kv_len
        if causal:
            q_idx = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            mask = jnp.logical_and(mask, q_idx + causal_off >= k_idx)
        s = jnp.where(mask, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jnp.dot(p, v, preferred_element_type=jnp.float32)
        return acc, m_new, l_new

    if causal:
        # only blocks intersecting the causal band contribute
        n_kv_eff = jnp.clip(
            pl.cdiv(q_start + block_q + causal_off, block_k), 0, n_kv
        ).astype(jnp.int32)
    else:
        n_kv_eff = n_kv
    acc0 = jnp.zeros((block_q, dh), jnp.float32)
    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, n_kv_eff, body, (acc0, m0, l0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _pad_to(x: jax.Array, axis: int, multiple: int) -> jax.Array:
    size = x.shape[axis]
    pad = (-size) % multiple
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _flash_forward(q: jax.Array, k: jax.Array, v: jax.Array, causal: bool,
                   sm_scale: Optional[float], block_q: int, block_k: int
                   ) -> jax.Array:
    """q,k,v: (B, H, S, Dh) -> (B, H, Sq, Dh)."""
    b, h, sq, dh = q.shape
    skv = k.shape[2]
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(dh)
    qf = _pad_to(q.reshape(b * h, sq, dh), 1, block_q)
    kf = _pad_to(k.reshape(b * h, skv, dh), 1, block_k)
    vf = _pad_to(v.reshape(b * h, skv, dh), 1, block_k)
    n_q = qf.shape[1] // block_q

    kernel = functools.partial(
        _flash_kernel, sm_scale=scale, causal=causal, q_len=sq, kv_len=skv,
        block_k=block_k)
    out = pl.pallas_call(
        kernel,
        grid=(b * h, n_q),
        in_specs=[
            pl.BlockSpec((1, block_q, dh), lambda bh, i: (bh, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, kf.shape[1], dh), lambda bh, i: (bh, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, vf.shape[1], dh), lambda bh, i: (bh, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, block_q, dh), lambda bh, i: (bh, i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b * h, qf.shape[1], dh), q.dtype),
        interpret=_use_interpret(),
    )(qf, kf, vf)
    return out[:, :sq, :].reshape(b, h, sq, dh)


def _reference(q, k, v, causal, sm_scale):
    from rafiki_tpu.ops.attention import mha_reference
    return mha_reference(q, k, v, causal=causal, sm_scale=sm_scale)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = False, sm_scale: Optional[float] = None,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K) -> jax.Array:
    """Flash attention over (B, H, S, Dh) tensors."""
    return _flash_forward(q, k, v, causal, sm_scale, block_q, block_k)


def _fwd(q, k, v, causal, sm_scale, block_q, block_k):
    return _flash_forward(q, k, v, causal, sm_scale, block_q, block_k), (q, k, v)


def _bwd(causal, sm_scale, block_q, block_k, res, g):
    q, k, v = res
    _, vjp = jax.vjp(lambda q_, k_, v_: _reference(q_, k_, v_, causal, sm_scale),
                     q, k, v)
    return vjp(g)


flash_attention.defvjp(_fwd, _bwd)
