"""Blockwise (flash) attention as pallas TPU kernels — forward and backward.

Computes softmax(q k^T * scale [+ causal mask]) v without ever materializing
an (S, S) score matrix in HBM or holding more than one kv block in VMEM:

- **forward**: grid (batch*heads, q-blocks, kv-blocks); the kv axis is the
  innermost (sequential on TPU) grid dimension, so each program sees one
  (block_q, dh) q tile and one (block_k, dh) k/v tile while online-softmax
  statistics (acc, row-max m, row-sum l) live in VMEM scratch that persists
  across the kv iteration. Per-row logsumexp is saved for the backward.
- **backward**: the standard two-kernel flash backward. With
  delta = rowsum(dO * O) precomputed, dQ streams kv blocks
  (dq += scale * [p * (dO v^T - delta)] k) and dK/dV streams q blocks
  (dv += p^T dO; dk += scale * [p * (dO v^T - delta)]^T q), where
  p = exp(s - lse) is recomputed from the saved logsumexp — O(S) residuals,
  O(S^2) flops, never an (S, S) tensor in memory.

This is the memory-bound op where HBM traffic — not FLOPs — sets the
ceiling, hence hand kernels rather than trusting XLA fusion. The reference
system has no analogue (its deepest compute is a TF1 GAN, reference
pg_gans.py); this exists for the transformer model zoo (ViT/BERT) and the
long-context path (parallel/ring.py composes blockwise attention across
chips; this kernel is the within-chip block).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
NEG_INF = -1e30
LANES = 128  # TPU lane width: minor dim of any Mosaic-lowered block tile


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _band_mask(q_start, j, block_q, block_k, kv_len, causal, causal_off):
    """(block_q, block_k) validity mask for kv block j against q block at
    q_start. Causal is end-aligned, matching mha_reference's
    tril(k=skv-sq): query i attends keys j <= i + (kv_len - q_len)."""
    k_idx = j * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    mask = k_idx < kv_len
    if causal:
        q_idx = q_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        mask = jnp.logical_and(mask, q_idx + causal_off >= k_idx)
    return mask


def _when_live(causal, cond_fn):
    """Run the decorated body only when the block intersects the causal band
    (unconditionally for non-causal attention — a static python branch)."""
    def deco(fn):
        if causal:
            pl.when(cond_fn())(fn)
        else:
            fn()
    return deco


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, *rest, sm_scale: float,
                causal: bool, q_len: int, kv_len: int, block_q: int,
                block_k: int, n_kv: int, save_lse: bool):
    # the lse output exists only when the forward runs under the VJP — the
    # primal-only path never writes row statistics to HBM
    if save_lse:
        lse_ref, acc_ref, m_ref, l_ref = rest
    else:
        lse_ref, (acc_ref, m_ref, l_ref) = None, rest
    j = pl.program_id(2)
    q_start = pl.program_id(1) * block_q
    causal_off = kv_len - q_len

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    @_when_live(causal, lambda: j * block_k <= q_start + block_q - 1 + causal_off)
    def _update():
        q = q_ref[0].astype(jnp.float32) * sm_scale           # (Bq, Dh)
        k = k_ref[0].astype(jnp.float32)                      # (Bk, Dh)
        v = v_ref[0].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)
        mask = _band_mask(q_start, j, block_q, block_k, kv_len, causal,
                          causal_off)
        s = jnp.where(mask, s, NEG_INF)
        m_prev = m_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        # explicit re-mask: rows with no visible keys have m_new == NEG_INF
        # and would otherwise get p = exp(0) = 1 on every masked column
        # (possible when causal and q block only partially intersects the
        # band), polluting l, o, and the backward's dk/dv
        p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[...] = alpha * l_ref[...] + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jnp.dot(
            p, v, preferred_element_type=jnp.float32)
        m_ref[...] = m_new

    @pl.when(j == n_kv - 1)
    def _finalize():
        l = l_ref[...]
        o_ref[0] = (acc_ref[...] / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
        if save_lse:
            # rows that saw no keys (causal with kv_len < q_len) get
            # lse=+inf so the backward's exp(s - lse) underflows to 0
            lse = jnp.where(l > 0,
                            m_ref[...] + jnp.log(jnp.maximum(l, 1e-30)),
                            jnp.inf)
            # broadcast across the 128 lanes: row statistics live in a
            # (block_q, 128) tile because Mosaic requires the minor block
            # dim to be a lane multiple — (1, block_q) is not lowerable
            lse_ref[0] = jnp.broadcast_to(lse, lse_ref.shape[1:])


def _pad_to(x: jax.Array, axis: int, multiple: int) -> jax.Array:
    size = x.shape[axis]
    pad = (-size) % multiple
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _flash_forward(q: jax.Array, k: jax.Array, v: jax.Array, causal: bool,
                   sm_scale: Optional[float], block_q: int, block_k: int,
                   save_lse: bool):
    """q,k,v: (B, H, S, Dh) -> out (B, H, Sq, Dh), lse (B*H, Sq_padded) or
    None. lse is only computed (and written to HBM) under the VJP."""
    b, h, sq, dh = q.shape
    skv = k.shape[2]
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(dh)
    qf = _pad_to(q.reshape(b * h, sq, dh), 1, block_q)
    kf = _pad_to(k.reshape(b * h, skv, dh), 1, block_k)
    vf = _pad_to(v.reshape(b * h, skv, dh), 1, block_k)
    n_q = qf.shape[1] // block_q
    n_kv = kf.shape[1] // block_k

    kernel = functools.partial(
        _fwd_kernel, sm_scale=scale, causal=causal, q_len=sq, kv_len=skv,
        block_q=block_q, block_k=block_k, n_kv=n_kv, save_lse=save_lse)
    out_specs = [
        pl.BlockSpec((1, block_q, dh), lambda bh, i, j: (bh, i, 0),
                     memory_space=pltpu.VMEM),
    ]
    out_shape = [jax.ShapeDtypeStruct((b * h, qf.shape[1], dh), q.dtype)]
    if save_lse:
        # (bh, S, 128): row statistics broadcast across lanes so every
        # block tile is (block_q, 128) — the minimum Mosaic f32 tile
        out_specs.append(
            pl.BlockSpec((1, block_q, LANES), lambda bh, i, j: (bh, i, 0),
                         memory_space=pltpu.VMEM))
        out_shape.append(
            jax.ShapeDtypeStruct((b * h, qf.shape[1], LANES), jnp.float32))
    res = pl.pallas_call(
        kernel,
        grid=(b * h, n_q, n_kv),
        in_specs=[
            pl.BlockSpec((1, block_q, dh), lambda bh, i, j: (bh, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, dh), lambda bh, i, j: (bh, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, dh), lambda bh, i, j: (bh, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((block_q, dh), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        interpret=_use_interpret(),
    )(qf, kf, vf)
    if save_lse:
        out, lse = res
        # keep only lane 0 as the residual — the broadcast costs 128x the
        # O(S) statistics memory flash attention exists to save
        lse = lse[:, :, 0]
    else:
        (out,), lse = res, None
    return out[:, :sq, :].reshape(b, h, sq, dh), lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _dq_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref, dq_ref,
               acc_ref, *, sm_scale: float, causal: bool, q_len: int,
               kv_len: int, block_q: int, block_k: int, n_kv: int):
    j = pl.program_id(2)
    q_start = pl.program_id(1) * block_q
    causal_off = kv_len - q_len

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    @_when_live(causal, lambda: j * block_k <= q_start + block_q - 1 + causal_off)
    def _update():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        g = g_ref[0].astype(jnp.float32)
        s = sm_scale * jnp.dot(q, k.T, preferred_element_type=jnp.float32)
        mask = _band_mask(q_start, j, block_q, block_k, kv_len, causal,
                          causal_off)
        s = jnp.where(mask, s, NEG_INF)
        p = jnp.exp(s - lse_ref[0][:, :1])                       # (Bq, Bk)
        dp = jnp.dot(g, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0][:, :1])
        acc_ref[...] += sm_scale * jnp.dot(
            ds, k, preferred_element_type=jnp.float32)

    @pl.when(j == n_kv - 1)
    def _finalize():
        dq_ref[0] = acc_ref[...].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref, dk_ref,
                dv_ref, dk_acc, dv_acc, *, sm_scale: float, causal: bool,
                q_len: int, kv_len: int, block_q: int, block_k: int,
                n_q: int):
    i = pl.program_id(2)
    jblk = pl.program_id(1)  # hoisted: program_id inside pl.when bodies is
    k_start = jblk * block_k  # not rewritten by the interpret-mode lowering
    q_start = i * block_q
    causal_off = kv_len - q_len

    @pl.when(i == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    # the q block contributes iff the causal band reaches this kv block
    @_when_live(causal, lambda: q_start + block_q - 1 + causal_off >= k_start)
    def _update():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        g = g_ref[0].astype(jnp.float32)
        s = sm_scale * jnp.dot(q, k.T, preferred_element_type=jnp.float32)
        mask = _band_mask(q_start, jblk, block_q, block_k,
                          kv_len, causal, causal_off)
        s = jnp.where(mask, s, NEG_INF)
        p = jnp.exp(s - lse_ref[0][:, :1])                       # (Bq, Bk)
        dv_acc[...] += jnp.dot(p.T, g, preferred_element_type=jnp.float32)
        dp = jnp.dot(g, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0][:, :1])
        dk_acc[...] += sm_scale * jnp.dot(
            ds.T, q, preferred_element_type=jnp.float32)

    @pl.when(i == n_q - 1)
    def _finalize():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _flash_backward(q, k, v, out, lse, g, causal, sm_scale, block_q, block_k):
    b, h, sq, dh = q.shape
    skv = k.shape[2]
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(dh)
    qf = _pad_to(q.reshape(b * h, sq, dh), 1, block_q)
    kf = _pad_to(k.reshape(b * h, skv, dh), 1, block_k)
    vf = _pad_to(v.reshape(b * h, skv, dh), 1, block_k)
    gf = _pad_to(g.reshape(b * h, sq, dh), 1, block_q)   # zero-padded: padded
    of = _pad_to(out.reshape(b * h, sq, dh), 1, block_q)  # rows contribute 0
    n_q = qf.shape[1] // block_q
    n_kv = kf.shape[1] // block_k
    # delta_i = sum_d dO_i O_i — the rowwise correction term of the flash
    # backward (d(softmax) along its normalization); both row statistics
    # are lanes-broadcast to (bh, S, 128) here, transiently (the saved
    # residual is the compact (bh, S) lse)
    delta = jnp.broadcast_to(
        jnp.sum(gf.astype(jnp.float32) * of.astype(jnp.float32),
                axis=-1)[:, :, None],
        (qf.shape[0], qf.shape[1], LANES))
    lse = jnp.broadcast_to(lse[:, :, None],
                           (qf.shape[0], qf.shape[1], LANES))

    common = dict(sm_scale=scale, causal=causal, q_len=sq, kv_len=skv,
                  block_q=block_q, block_k=block_k)
    q_spec = pl.BlockSpec((1, block_q, dh), lambda bh, i, j: (bh, i, 0),
                          memory_space=pltpu.VMEM)
    kv_spec = pl.BlockSpec((1, block_k, dh), lambda bh, i, j: (bh, j, 0),
                           memory_space=pltpu.VMEM)
    row_spec = pl.BlockSpec((1, block_q, LANES), lambda bh, i, j: (bh, i, 0),
                            memory_space=pltpu.VMEM)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, n_kv=n_kv, **common),
        grid=(b * h, n_q, n_kv),
        in_specs=[q_spec, kv_spec, kv_spec, q_spec, row_spec, row_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct(qf.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, dh), jnp.float32)],
        interpret=_use_interpret(),
    )(qf, kf, vf, gf, lse, delta)

    # dk/dv: kv blocks are the parallel axis, q blocks stream innermost
    q_spec2 = pl.BlockSpec((1, block_q, dh), lambda bh, j, i: (bh, i, 0),
                           memory_space=pltpu.VMEM)
    kv_spec2 = pl.BlockSpec((1, block_k, dh), lambda bh, j, i: (bh, j, 0),
                            memory_space=pltpu.VMEM)
    row_spec2 = pl.BlockSpec((1, block_q, LANES), lambda bh, j, i: (bh, i, 0),
                             memory_space=pltpu.VMEM)
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, n_q=n_q, **common),
        grid=(b * h, n_kv, n_q),
        in_specs=[q_spec2, kv_spec2, kv_spec2, q_spec2, row_spec2, row_spec2],
        out_specs=[kv_spec2, kv_spec2],
        out_shape=[jax.ShapeDtypeStruct(kf.shape, k.dtype),
                   jax.ShapeDtypeStruct(vf.shape, v.dtype)],
        scratch_shapes=[pltpu.VMEM((block_k, dh), jnp.float32),
                        pltpu.VMEM((block_k, dh), jnp.float32)],
        interpret=_use_interpret(),
    )(qf, kf, vf, gf, lse, delta)

    dq = dq[:, :sq, :].reshape(b, h, sq, dh)
    dk = dk[:, :skv, :].reshape(b, h, skv, dh)
    dv = dv[:, :skv, :].reshape(b, h, skv, dh)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public op
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = False, sm_scale: Optional[float] = None,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K) -> jax.Array:
    """Flash attention over (B, H, S, Dh) tensors."""
    out, _ = _flash_forward(q, k, v, causal, sm_scale, block_q, block_k,
                            save_lse=False)
    return out


def _fwd(q, k, v, causal, sm_scale, block_q, block_k):
    out, lse = _flash_forward(q, k, v, causal, sm_scale, block_q, block_k,
                              save_lse=True)
    return out, (q, k, v, out, lse)


def _bwd(causal, sm_scale, block_q, block_k, res, g):
    q, k, v, out, lse = res
    return _flash_backward(q, k, v, out, lse, g, causal, sm_scale,
                           block_q, block_k)


flash_attention.defvjp(_fwd, _bwd)
