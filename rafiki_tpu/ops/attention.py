"""Multi-head attention: XLA reference semantics + flash-kernel dispatch.

``mha_reference`` is the ground truth (used for gradients and for unit-test
comparison); ``multi_head_attention`` is the layer the model zoo calls —
projections + attention + output projection over a plain param dict, routing
the inner attention to the pallas flash kernel when profitable.
"""

from __future__ import annotations

import math
import os
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from rafiki_tpu.models import core

Params = Dict[str, Any]

# Auto-dispatch threshold: route to the flash kernel once the f32 (S, S)
# score tensor (4*B*H*S^2 bytes) would crowd HBM. Below it XLA's fused
# attention is FASTER on TPU (measured fwd+bwd at B4/H12: 14 vs 22 ms at
# seq 2048, 50 vs 65 ms at 4096) — flash's win is memory, not speed: at
# seq 8192 the same shape needs ~13 GB of scores and fails to compile,
# while flash runs it in 242 ms. 1 GB default leaves room for the scores
# XLA saves for backward alongside params/activations.
def _flash_threshold_bytes() -> int:
    raw = os.environ.get("RAFIKI_FLASH_THRESHOLD_BYTES", str(1 << 30))
    try:
        return int(raw)
    except ValueError:
        raise ValueError(
            f"RAFIKI_FLASH_THRESHOLD_BYTES={raw!r} must be a plain integer "
            "byte count (e.g. 1073741824)") from None


FLASH_SCORES_BYTES = _flash_threshold_bytes()


def mha_reference(q: jax.Array, k: jax.Array, v: jax.Array,
                  causal: bool = False,
                  sm_scale: Optional[float] = None) -> jax.Array:
    """Plain attention over (B, H, S, Dh); softmax statistics in f32."""
    dh = q.shape[-1]
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(dh)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        sq, skv = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((sq, skv), bool), k=skv - sq)
        s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def attention_init(rng: jax.Array, dim: int, heads: int) -> Params:
    """QKV + output projection params. Head axis kept explicit so tensor
    parallelism can shard it (heads over the ``model`` mesh axis)."""
    dh = dim // heads
    kq, kk, kv, ko = jax.random.split(rng, 4)
    # fans of the *logical* dim -> heads*dh projection, not the per-head
    # slice — matches the standard init of the fused (dim, dim) matmul
    shape = (dim, heads, dh)
    return {
        "wq": core.xavier_uniform(kq, shape, fan_in=dim, fan_out=heads * dh),
        "wk": core.xavier_uniform(kk, shape, fan_in=dim, fan_out=heads * dh),
        "wv": core.xavier_uniform(kv, shape, fan_in=dim, fan_out=heads * dh),
        "wo": core.xavier_uniform(ko, (heads, dh, dim), fan_in=heads * dh,
                                  fan_out=dim),
        "bo": jnp.zeros((dim,), jnp.float32),
    }


def multi_head_attention(params: Params, x: jax.Array,
                         causal: bool = False,
                         use_flash: Optional[bool] = None,
                         attn_fn=None,
                         fused_qkv: bool = False) -> jax.Array:
    """Self-attention over (B, S, D). ``use_flash=None`` auto-selects the
    pallas kernel once the (S, S) score tensors would crowd HBM (see
    FLASH_SCORES_BYTES — below that, XLA's fused attention is faster).
    ``attn_fn(q, k, v, causal)`` overrides the inner attention entirely
    (the seam ring attention plugs into — see models/transformer.py
    seq_parallel). ``fused_qkv`` computes all three projections as ONE
    (BS, D) x (D, 3HDh) matmul over runtime-stacked weights — x streams
    from HBM once instead of three times per layer and the MXU sees one
    wide gemm; param layout (and thus checkpoints/TP specs) is
    unchanged. Whether XLA's dot-merger already gets this is
    hardware-measured, not assumed — it is a sweep lever
    (bench_models.py RAFIKI_SWEEP_QKV)."""
    from rafiki_tpu.ops.flash_attention import flash_attention

    b, s, d = x.shape
    dt = x.dtype
    if fused_qkv:
        wqkv = jnp.stack(
            [params["wq"], params["wk"], params["wv"]], axis=0).astype(dt)
        q, k, v = jnp.einsum("bsd,tdhk->tbhsk", x, wqkv)
    else:
        q = jnp.einsum("bsd,dhk->bhsk", x, params["wq"].astype(dt))
        k = jnp.einsum("bsd,dhk->bhsk", x, params["wk"].astype(dt))
        v = jnp.einsum("bsd,dhk->bhsk", x, params["wv"].astype(dt))
    n_heads = params["wq"].shape[1]
    scores_bytes = 4 * b * n_heads * s * s
    if attn_fn is not None:
        o = attn_fn(q, k, v, causal)
    elif use_flash or (use_flash is None
                       and jax.default_backend() == "tpu"
                       and scores_bytes > FLASH_SCORES_BYTES):
        o = flash_attention(q, k, v, causal=causal)
    else:
        o = mha_reference(q, k, v, causal=causal)
    out = jnp.einsum("bhsk,hkd->bsd", o, params["wo"].astype(dt))
    return out + params["bo"].astype(dt)
