"""Serving layer: continuous-batching predictor + ensembling
(reference rafiki/predictor/)."""

from rafiki_tpu.predictor.ensemble import ensemble_predictions  # noqa: F401
