"""Deadline-aware admission control for the serving doors.

Both serving doors — the per-job ``PredictorServer`` and the admin's
``/predict/<app>`` route — sit on ``ThreadingHTTPServer``, which happily
spawns one handler thread per connection forever. Under overload that is
the metastable failure of "The Tail at Scale": every queued request is
eventually served (long after its client gave up), each one slower than
the last. This module is the shared front gate:

- a **bounded in-flight semaphore** (``RAFIKI_PREDICT_MAX_INFLIGHT``):
  requests beyond the cap are shed instantly with ``503`` — capacity is
  the model fleet, not the thread scheduler;
- an **estimated-wait check**: if the backlog already implies a wait
  longer than the request's own deadline, admitting it only burns model
  time on a doomed request — shed with ``429`` + ``Retry-After`` so
  well-behaved clients back off;
- **counters** (admitted/shed/in-flight + an EWMA of per-query service
  time) surfaced through ``/healthz`` and ``GET /fleet/health``.

Shed-code contract (docs/failure-model.md "Overload faults"): ``429``
means *retryable later* — the queue is full or the wait exceeds your
deadline, and ``Retry-After`` says when to come back; ``503`` means *no
capacity right now* — in-flight slots are gone, retry is the client's
call. Neither code is ever sent after work started; a shed request costs
the server microseconds.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Dict, Optional, Tuple

# parsed RAFIKI_AUTOSCALE_FAIR_WEIGHTS cache: (raw_value, {tenant: w})
_weights_cache: Tuple[Optional[str], Dict[str, float]] = (None, {})
_weights_lock = threading.Lock()


def _fair_weights() -> Dict[str, float]:
    """{tenant: weight} from RAFIKI_AUTOSCALE_FAIR_WEIGHTS
    ("appA=3,appB=1"); unlisted tenants weigh 1. Parsed once per distinct
    env value — this sits on the admission hot path."""
    from rafiki_tpu import config

    global _weights_cache
    raw = str(config.AUTOSCALE_FAIR_WEIGHTS)
    cached_raw, cached = _weights_cache
    if raw == cached_raw:
        return cached
    weights: Dict[str, float] = {}
    for part in raw.split(","):
        part = part.strip()
        if not part or "=" not in part:
            continue
        name, _, val = part.partition("=")
        try:
            w = float(val)
        except ValueError:
            continue
        if w > 0:
            weights[name.strip()] = w
    with _weights_lock:
        _weights_cache = (raw, weights)
    return weights


class ServerOverloadedError(RuntimeError):
    """The door's in-flight cap is exhausted (HTTP 503)."""

    def __init__(self, message: str, retry_after_s: float = 1.0) -> None:
        super().__init__(message)
        self.retry_after_s = max(float(retry_after_s), 0.0)


class DeadlineUnmeetableError(RuntimeError):
    """The estimated queue wait already exceeds the request's deadline
    (HTTP 429 + Retry-After): admitting it would spend model time on an
    answer nobody will read."""

    def __init__(self, message: str, retry_after_s: float = 1.0) -> None:
        super().__init__(message)
        self.retry_after_s = max(float(retry_after_s), 0.0)


class TenantOverShareError(DeadlineUnmeetableError):
    """The tenant is past its weighted fair share of admitted queries
    while the door is under pressure (HTTP 429 + Retry-After). Subclasses
    :class:`DeadlineUnmeetableError` so every door's shed mapping answers
    it retryable without new handler wiring — but the shed is PER-TENANT:
    the hot job backing off is exactly what keeps the cold jobs' latency
    (docs/failure-model.md "Overload adaptation")."""


def retry_after_headers(e: Exception) -> Dict[str, str]:
    """The Retry-After header (whole seconds, >= 1) from a shed error's
    estimate — THE one copy of the contract, used by every door."""
    return {"Retry-After": str(max(
        1, math.ceil(getattr(e, "retry_after_s", 1.0))))}


class AdmissionController:
    """One per serving door. Thread-safe; all operations are O(1) and
    lock-held for nanoseconds — this gate must stay cheap precisely when
    the server is busiest.

    ``door`` labels this controller's registry metrics (utils/metrics.py):
    per-door admitted/shed counters, an in-flight gauge, the EWMA-wait
    gauge, and the ``rafiki_request_seconds`` latency histogram fed by
    :meth:`observe` — the source of the bench's door-side p50/p95/p99.
    The JSON ``stats()`` shape is unchanged (per-controller ints,
    incremented at the same sites as the registry mirrors)."""

    def __init__(self, max_inflight: Optional[int] = None,
                 door: str = "predictor",
                 shared_tenants: bool = False) -> None:
        #: None defers to RAFIKI_PREDICT_MAX_INFLIGHT lazily per admit
        self._max_inflight = max_inflight
        #: True for doors several tenants enter (the admin /predict/<app>
        #: route); gates the per-tenant in-flight ceiling — a dedicated
        #: per-job door has ONE tenant by construction and may use every
        #: slot itself
        self._shared_tenants = shared_tenants
        self._lock = threading.Lock()
        self._inflight = 0
        self._admitted = 0
        self._shed_capacity = 0   # 503s
        self._shed_deadline = 0   # 429s
        # EWMA of per-query service seconds, admission's unit of wait
        # estimation; 0.0 until the first observation (estimate disabled —
        # never shed on a guess)
        self._ewma_query_s = 0.0
        self.door = door
        from rafiki_tpu.utils.metrics import REGISTRY

        self._m_admitted = REGISTRY.counter(
            "rafiki_admission_admitted_total",
            "requests admitted through a serving door", ("door",)
        ).labels(door)
        shed = REGISTRY.counter(
            "rafiki_admission_shed_total",
            "requests shed at a serving door (reason: capacity=503, "
            "deadline=429)", ("door", "reason"))
        self._m_shed_capacity = shed.labels(door, "capacity")
        self._m_shed_deadline = shed.labels(door, "deadline")
        self._m_shed_fairness = shed.labels(door, "fairness")
        # -- multi-tenant weighted fair admission (RAFIKI_AUTOSCALE_FAIR).
        # Deficit-style accounting on ADMITTED QUERIES: each tenant
        # carries a decaying charge of what it was actually granted; when
        # the door is under pressure, a tenant whose charge is past its
        # weighted fair share of the total is shed with 429 while tenants
        # under their share keep being admitted — degradation becomes
        # per-tenant, not global. {tenant: [charge, last_decay_monotonic]}
        self._fair: Dict[str, list] = {}
        # {tenant: slots currently held} for the in-flight ceiling —
        # release(tenant=) is the decrement
        self._fair_inflight: Dict[str, int] = {}
        self._shed_fairness = 0
        self._last_shed_mono = 0.0
        self._g_inflight = REGISTRY.gauge(
            "rafiki_admission_inflight",
            "requests currently in flight behind a serving door",
            ("door",)).labels(door)
        self._g_ewma = REGISTRY.gauge(
            "rafiki_admission_ewma_query_seconds",
            "EWMA of per-query service seconds (the wait-estimation "
            "unit)", ("door",)).labels(door)
        self._h_request = REGISTRY.histogram(
            "rafiki_request_seconds",
            "end-to-end served-request latency at a serving door",
            ("door",)).labels(door)
        # autoscaler-grade ring series (~1 s resolution, bounded window).
        # One ring per door: the admin door and every per-app predictor
        # door live in one process, and a shared ring would clobber their
        # samples into one interleaved series no control loop could read.
        self._ring_shed = REGISTRY.ring(f"shed_rate:{door}")
        self._ring_wait = REGISTRY.ring(f"ewma_wait_s:{door}")
        # EWMA cold start: a FRESH controller (rebound door after crash
        # recovery, a door for a just-scaled job) has no latency history,
        # so the estimated-wait check is disabled for its first requests —
        # under a flood at cold start that admits a pile of doomed work.
        # The process registry outlives any one controller: seed from the
        # door's running request-latency histogram when it has history.
        # Median REQUEST latency over-estimates per-QUERY time, which is
        # the conservative direction (shed slightly early, never admit
        # blind); the first real observe() blends it toward truth.
        seed = self._h_request.quantile(0.5)
        if seed is not None and seed > 0:
            self._ewma_query_s = float(seed)

    def _cap(self) -> int:
        if self._max_inflight is not None:
            return self._max_inflight
        from rafiki_tpu import config

        return int(config.PREDICT_MAX_INFLIGHT)

    # -- admission ---------------------------------------------------------

    def admit(self, timeout_s: float,
              backlog_depth: Optional[int] = None,
              tenant: Optional[str] = None, cost: int = 1) -> None:
        """Claim one in-flight slot or raise a shed error. The caller MUST
        pair a successful admit with :meth:`release` (try/finally).

        ``backlog_depth`` is the least-loaded replica path's queue depth
        (``Predictor.min_backlog_depth``); with a service-time EWMA it
        yields the estimated wait this request would face.

        ``tenant`` names the requesting job/app for the weighted-fair
        gate (``RAFIKI_AUTOSCALE_FAIR``); ``cost`` is the query count the
        tenant is charged on admission. ``None`` (every pre-existing call
        site) skips fairness entirely. ``cost=0`` is legal — a request
        the prediction cache will answer entirely still claims an
        in-flight slot (the handler thread is real) but charges nothing
        to the fairness book (it sheds no load onto the worker fleet)."""
        with self._lock:
            cap = self._cap()
            if tenant is not None:
                # in-flight ceiling BEFORE the capacity shed: the hot
                # tenant is turned away while slots remain, so the
                # capacity check below still has room for everyone else
                self._fair_ceiling_locked(tenant, cap)
            if cap > 0 and self._inflight >= cap:
                self._shed_capacity += 1
                self._m_shed_capacity.inc()
                self._ring_shed.add()
                self._last_shed_mono = time.monotonic()
                raise ServerOverloadedError(
                    f"serving door at capacity ({self._inflight}/{cap} "
                    f"in flight)",
                    retry_after_s=max(self._ewma_query_s, 1.0))
            if tenant is not None:
                self._fair_gate_locked(tenant, max(int(cost), 0), cap)
            est_wait = (backlog_depth * self._ewma_query_s
                        if backlog_depth and self._ewma_query_s > 0 else 0.0)
            if est_wait > timeout_s > 0:
                self._shed_deadline += 1
                self._m_shed_deadline.inc()
                self._ring_shed.add()
                self._last_shed_mono = time.monotonic()
                raise DeadlineUnmeetableError(
                    f"estimated queue wait {est_wait:.2f}s exceeds the "
                    f"request deadline {timeout_s:.2f}s",
                    retry_after_s=math.ceil(est_wait))
            self._inflight += 1
            self._admitted += 1
            if tenant is not None:
                self._fair_inflight[tenant] = (
                    self._fair_inflight.get(tenant, 0) + 1)
                # charge only what was actually ADMITTED — a request shed
                # at the capacity/deadline/fairness checks above must not
                # inflate the tenant's "admitted queries" book (cost 0:
                # a fully-cache-served request charges nothing)
                self._fair_charge_locked(tenant, max(int(cost), 0))
            self._m_admitted.inc()
            self._g_inflight.inc()

    # -- multi-tenant weighted fairness -------------------------------------

    def _fair_ceiling_locked(self, tenant: str,  # guarded-by: _lock
                             cap: int) -> None:
        """No single tenant may occupy EVERY in-flight slot of a shared
        door (caller holds ``self._lock``). The charge gate below can
        only defend a tenant it has admitted at least once — but a flood
        of SLOW requests from one hot job can hold all ``cap`` slots, so
        a cold tenant's first request would die at the capacity shed
        before any fairness accounting ever saw it. Under
        ``RAFIKI_AUTOSCALE_FAIR`` a tenant already holding ``cap - 1``
        slots is shed 429 instead: one slot always stays winnable by
        someone else."""
        from rafiki_tpu import config

        if cap < 2 or not self._shared_tenants or not config.AUTOSCALE_FAIR:
            return
        held = self._fair_inflight.get(tenant, 0)
        if held >= cap - 1:
            # fairness sheds deliberately do NOT refresh _last_shed_mono:
            # they are a CONSEQUENCE of pressure, and letting them renew
            # the pressure window would self-sustain shedding on a door
            # that has already gone quiet
            self._shed_fairness += 1
            self._m_shed_fairness.inc()
            self._ring_shed.add()
            raise TenantOverShareError(
                f"tenant {tenant!r} already holds {held} of the door's "
                f"{cap} in-flight slots",
                retry_after_s=max(self._ewma_query_s, 1.0))

    def _fair_gate_locked(self, tenant: str, cost: int,  # guarded-by: _lock
                          cap: int) -> None:
        """Deficit-style fair-share check (caller holds ``self._lock``).
        Check only — the charge lands in :meth:`_fair_charge_locked` once
        the request is actually admitted.

        Charges decay with a half-life of ``RAFIKI_AUTOSCALE_FAIR_WINDOW_S``
        so the accounting is a sliding picture of recent admissions, not
        all-time totals. The gate only sheds **under pressure** — the door
        near its in-flight cap, or sheds within the last few seconds;
        an uncontended door admits everyone (fairness is about dividing
        scarcity, not rationing plenty). A dedicated per-job door
        (``shared_tenants=False``) has ONE tenant by construction: its
        charges still accrue (``fair_shares`` observability) but it is
        never rationed against itself."""
        from rafiki_tpu import config

        if not self._shared_tenants or not config.AUTOSCALE_FAIR:
            return
        now = time.monotonic()
        half_life = max(float(config.AUTOSCALE_FAIR_WINDOW_S), 0.5)
        total = 0.0
        for state in self._fair.values():
            dt = now - state[1]
            if dt > 0:
                state[0] *= 0.5 ** (dt / half_life)
                state[1] = now
            total += state[0]
        charge = self._fair.get(tenant, (0.0, now))[0]
        # fairness needs someone to be fair TO: with no OTHER tenant
        # recently active, shedding the only customer serves nobody —
        # and for the sole tenant the share test degenerates to
        # cost > burst, rationing plenty
        others_active = any(
            t != tenant and s[0] > 0.5 for t, s in self._fair.items())
        pressure = ((cap > 0 and self._inflight >= max(cap // 2, 1))
                    or now - self._last_shed_mono < 2.0)
        if pressure and others_active:
            weights = _fair_weights()
            w = weights.get(tenant, 1.0)
            sum_w = sum(
                weights.get(t, 1.0) for t, s in self._fair.items()
                if s[0] > 0.5 or t == tenant)
            if tenant not in self._fair:
                sum_w += w
            fair_share = total * w / max(sum_w, w)
            burst = float(config.AUTOSCALE_FAIR_BURST)
            if charge + cost > fair_share + burst:
                # consequence of pressure, not evidence: see ceiling note
                self._shed_fairness += 1
                self._m_shed_fairness.inc()
                self._ring_shed.add()
                raise TenantOverShareError(
                    f"tenant {tenant!r} is past its weighted fair share "
                    f"({charge:.0f} recent queries vs share "
                    f"{fair_share:.0f} + burst {burst:.0f}) while the "
                    "door is contended",
                    retry_after_s=max(self._ewma_query_s * cost, 1.0))

    def _fair_charge_locked(self, tenant: str,  # guarded-by: _lock
                            cost: int) -> None:
        """Book ``cost`` admitted queries against ``tenant`` (caller holds
        ``self._lock``), decaying the tenant's prior charge to now first."""
        from rafiki_tpu import config

        if not config.AUTOSCALE_FAIR:
            return
        now = time.monotonic()
        state = self._fair.setdefault(tenant, [0.0, now])
        dt = now - state[1]
        if dt > 0:
            half_life = max(float(config.AUTOSCALE_FAIR_WINDOW_S), 0.5)
            state[0] *= 0.5 ** (dt / half_life)
        state[0] += cost
        state[1] = now

    def fair_shares(self) -> Dict[str, float]:
        """Snapshot of the decayed per-tenant admitted-query charges
        (operator view; /healthz + tests)."""
        with self._lock:
            return {t: round(s[0], 3) for t, s in self._fair.items()}

    def note_backend_shed(self) -> None:
        """Book a WHOLE-FLEET-FULL refusal: the door admitted the
        request, then every replica's bounded queue said no
        (``QueueFullError`` out of the predictor). The client saw the
        same 429 + Retry-After as a deadline shed, so it lands in the
        deadline-class books — without this the fleet-full path would be
        invisible to the shed counters, the shed-rate ring the
        autoscaler reads, and the fairness pressure window."""
        with self._lock:
            self._shed_deadline += 1
            self._m_shed_deadline.inc()
            self._ring_shed.add()
            self._last_shed_mono = time.monotonic()

    def release(self, tenant: Optional[str] = None) -> None:
        """Pair of :meth:`admit`. Callers that admitted with a ``tenant``
        must release with the same one (the in-flight ceiling's book)."""
        with self._lock:
            self._inflight = max(self._inflight - 1, 0)
            if tenant is not None:
                held = self._fair_inflight.get(tenant, 0) - 1
                if held > 0:
                    self._fair_inflight[tenant] = held
                else:
                    self._fair_inflight.pop(tenant, None)
            self._g_inflight.set(self._inflight)

    # -- feedback + observability ------------------------------------------

    def observe(self, latency_s: float, n_queries: int) -> None:
        """Feed one served request's latency back into the wait model,
        the door's latency histogram, and the EWMA-wait ring series."""
        if n_queries <= 0 or latency_s < 0:
            return
        per_query = latency_s / n_queries
        with self._lock:
            if self._ewma_query_s <= 0.0:
                self._ewma_query_s = per_query
            else:
                self._ewma_query_s += 0.2 * (per_query - self._ewma_query_s)
            ewma = self._ewma_query_s
        self._h_request.observe(latency_s)
        self._g_ewma.set(ewma)
        self._ring_wait.record(ewma)

    @property
    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    def stats(self) -> Dict[str, float]:
        with self._lock:
            return {
                "inflight": self._inflight,
                "max_inflight": self._cap(),
                "admitted": self._admitted,
                "shed_capacity": self._shed_capacity,
                "shed_deadline": self._shed_deadline,
                "shed_fairness": self._shed_fairness,
                "ewma_query_s": round(self._ewma_query_s, 6),
            }
