"""Deadline-aware admission control for the serving doors.

Both serving doors — the per-job ``PredictorServer`` and the admin's
``/predict/<app>`` route — sit on ``ThreadingHTTPServer``, which happily
spawns one handler thread per connection forever. Under overload that is
the metastable failure of "The Tail at Scale": every queued request is
eventually served (long after its client gave up), each one slower than
the last. This module is the shared front gate:

- a **bounded in-flight semaphore** (``RAFIKI_PREDICT_MAX_INFLIGHT``):
  requests beyond the cap are shed instantly with ``503`` — capacity is
  the model fleet, not the thread scheduler;
- an **estimated-wait check**: if the backlog already implies a wait
  longer than the request's own deadline, admitting it only burns model
  time on a doomed request — shed with ``429`` + ``Retry-After`` so
  well-behaved clients back off;
- **counters** (admitted/shed/in-flight + an EWMA of per-query service
  time) surfaced through ``/healthz`` and ``GET /fleet/health``.

Shed-code contract (docs/failure-model.md "Overload faults"): ``429``
means *retryable later* — the queue is full or the wait exceeds your
deadline, and ``Retry-After`` says when to come back; ``503`` means *no
capacity right now* — in-flight slots are gone, retry is the client's
call. Neither code is ever sent after work started; a shed request costs
the server microseconds.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, Optional


class ServerOverloadedError(RuntimeError):
    """The door's in-flight cap is exhausted (HTTP 503)."""

    def __init__(self, message: str, retry_after_s: float = 1.0) -> None:
        super().__init__(message)
        self.retry_after_s = max(float(retry_after_s), 0.0)


class DeadlineUnmeetableError(RuntimeError):
    """The estimated queue wait already exceeds the request's deadline
    (HTTP 429 + Retry-After): admitting it would spend model time on an
    answer nobody will read."""

    def __init__(self, message: str, retry_after_s: float = 1.0) -> None:
        super().__init__(message)
        self.retry_after_s = max(float(retry_after_s), 0.0)


def retry_after_headers(e: Exception) -> Dict[str, str]:
    """The Retry-After header (whole seconds, >= 1) from a shed error's
    estimate — THE one copy of the contract, used by every door."""
    return {"Retry-After": str(max(
        1, math.ceil(getattr(e, "retry_after_s", 1.0))))}


class AdmissionController:
    """One per serving door. Thread-safe; all operations are O(1) and
    lock-held for nanoseconds — this gate must stay cheap precisely when
    the server is busiest.

    ``door`` labels this controller's registry metrics (utils/metrics.py):
    per-door admitted/shed counters, an in-flight gauge, the EWMA-wait
    gauge, and the ``rafiki_request_seconds`` latency histogram fed by
    :meth:`observe` — the source of the bench's door-side p50/p95/p99.
    The JSON ``stats()`` shape is unchanged (per-controller ints,
    incremented at the same sites as the registry mirrors)."""

    def __init__(self, max_inflight: Optional[int] = None,
                 door: str = "predictor") -> None:
        #: None defers to RAFIKI_PREDICT_MAX_INFLIGHT lazily per admit
        self._max_inflight = max_inflight
        self._lock = threading.Lock()
        self._inflight = 0
        self._admitted = 0
        self._shed_capacity = 0   # 503s
        self._shed_deadline = 0   # 429s
        # EWMA of per-query service seconds, admission's unit of wait
        # estimation; 0.0 until the first observation (estimate disabled —
        # never shed on a guess)
        self._ewma_query_s = 0.0
        self.door = door
        from rafiki_tpu.utils.metrics import REGISTRY

        self._m_admitted = REGISTRY.counter(
            "rafiki_admission_admitted_total",
            "requests admitted through a serving door", ("door",)
        ).labels(door)
        shed = REGISTRY.counter(
            "rafiki_admission_shed_total",
            "requests shed at a serving door (reason: capacity=503, "
            "deadline=429)", ("door", "reason"))
        self._m_shed_capacity = shed.labels(door, "capacity")
        self._m_shed_deadline = shed.labels(door, "deadline")
        self._g_inflight = REGISTRY.gauge(
            "rafiki_admission_inflight",
            "requests currently in flight behind a serving door",
            ("door",)).labels(door)
        self._g_ewma = REGISTRY.gauge(
            "rafiki_admission_ewma_query_seconds",
            "EWMA of per-query service seconds (the wait-estimation "
            "unit)", ("door",)).labels(door)
        self._h_request = REGISTRY.histogram(
            "rafiki_request_seconds",
            "end-to-end served-request latency at a serving door",
            ("door",)).labels(door)
        # autoscaler-grade ring series (~1 s resolution, bounded window).
        # One ring per door: the admin door and every per-app predictor
        # door live in one process, and a shared ring would clobber their
        # samples into one interleaved series no control loop could read.
        self._ring_shed = REGISTRY.ring(f"shed_rate:{door}")
        self._ring_wait = REGISTRY.ring(f"ewma_wait_s:{door}")

    def _cap(self) -> int:
        if self._max_inflight is not None:
            return self._max_inflight
        from rafiki_tpu import config

        return int(config.PREDICT_MAX_INFLIGHT)

    # -- admission ---------------------------------------------------------

    def admit(self, timeout_s: float,
              backlog_depth: Optional[int] = None) -> None:
        """Claim one in-flight slot or raise a shed error. The caller MUST
        pair a successful admit with :meth:`release` (try/finally).

        ``backlog_depth`` is the least-loaded replica path's queue depth
        (``Predictor.min_backlog_depth``); with a service-time EWMA it
        yields the estimated wait this request would face."""
        with self._lock:
            cap = self._cap()
            if cap > 0 and self._inflight >= cap:
                self._shed_capacity += 1
                self._m_shed_capacity.inc()
                self._ring_shed.add()
                raise ServerOverloadedError(
                    f"serving door at capacity ({self._inflight}/{cap} "
                    f"in flight)",
                    retry_after_s=max(self._ewma_query_s, 1.0))
            est_wait = (backlog_depth * self._ewma_query_s
                        if backlog_depth and self._ewma_query_s > 0 else 0.0)
            if est_wait > timeout_s > 0:
                self._shed_deadline += 1
                self._m_shed_deadline.inc()
                self._ring_shed.add()
                raise DeadlineUnmeetableError(
                    f"estimated queue wait {est_wait:.2f}s exceeds the "
                    f"request deadline {timeout_s:.2f}s",
                    retry_after_s=math.ceil(est_wait))
            self._inflight += 1
            self._admitted += 1
            self._m_admitted.inc()
            self._g_inflight.inc()

    def release(self) -> None:
        with self._lock:
            self._inflight = max(self._inflight - 1, 0)
            self._g_inflight.set(self._inflight)

    # -- feedback + observability ------------------------------------------

    def observe(self, latency_s: float, n_queries: int) -> None:
        """Feed one served request's latency back into the wait model,
        the door's latency histogram, and the EWMA-wait ring series."""
        if n_queries <= 0 or latency_s < 0:
            return
        per_query = latency_s / n_queries
        with self._lock:
            if self._ewma_query_s <= 0.0:
                self._ewma_query_s = per_query
            else:
                self._ewma_query_s += 0.2 * (per_query - self._ewma_query_s)
            ewma = self._ewma_query_s
        self._h_request.observe(latency_s)
        self._g_ewma.set(ewma)
        self._ring_wait.record(ewma)

    @property
    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    def stats(self) -> Dict[str, float]:
        with self._lock:
            return {
                "inflight": self._inflight,
                "max_inflight": self._cap(),
                "admitted": self._admitted,
                "shed_capacity": self._shed_capacity,
                "shed_deadline": self._shed_deadline,
                "ewma_query_s": round(self._ewma_query_s, 6),
            }
