"""Predictor: fan-out queries to per-trial inference workers, gather, and
ensemble.

Parity with the reference's Predictor (reference
rafiki/predictor/predictor.py:14-87): queries go to every registered worker of
the inference job and the responses are ensembled per task. Differences:

- futures + condition variables replace the 0.25 s Redis poll (the reference's
  p50 floor, reference predictor.py:46-59);
- a real timeout/SLO exists (`PREDICT_TIMEOUT_S`; the reference had a TODO at
  predictor.py:45 and would wait forever on a dead worker) — workers that miss
  the deadline are dropped from the ensemble rather than stalling the request;
- ``predict_batch`` is implemented (the reference left it as a TODO at
  predictor.py:85-87).
"""

from __future__ import annotations

import logging
from typing import Any, List, Optional

from rafiki_tpu import config
from rafiki_tpu.cache.queue import Broker, QueryFuture
from rafiki_tpu.predictor.ensemble import ensemble_predictions

logger = logging.getLogger(__name__)


class Predictor:
    def __init__(self, inference_job_id: str, broker: Broker, task: Optional[str]):
        self._job_id = inference_job_id
        self._broker = broker
        self._task = task

    def predict(self, query: Any, timeout_s: Optional[float] = None) -> Any:
        return self.predict_batch([query], timeout_s)[0]

    def predict_batch(
        self, queries: List[Any], timeout_s: Optional[float] = None
    ) -> List[Any]:
        """Fan each query out to every worker, gather with a deadline,
        ensemble across the workers that answered."""
        import time as _time

        timeout_s = timeout_s if timeout_s is not None else config.PREDICT_TIMEOUT_S
        deadline = _time.monotonic() + timeout_s
        queues = self._broker.get_worker_queues(self._job_id)
        if not queues:
            raise RuntimeError(
                f"No inference workers registered for job {self._job_id}"
            )
        futures: List[List[QueryFuture]] = [
            [q.submit(query) for query in queries] for q in queues.values()
        ]
        worker_predictions: List[Optional[List[Any]]] = []
        for worker_futs in futures:
            preds: Optional[List[Any]] = []
            for fut in worker_futs:
                try:
                    # one deadline shared by the whole request, not a fresh
                    # timeout per future — a dead worker costs at most the SLO
                    remaining = max(deadline - _time.monotonic(), 0.0)
                    preds.append(fut.result(remaining))
                except Exception as e:
                    logger.warning("worker dropped from ensemble: %r", e)
                    preds = None
                    break
            worker_predictions.append(preds)
        answered = [p for p in worker_predictions if p is not None]
        if not answered:
            raise TimeoutError("No inference worker answered within the SLO")
        # transpose: ensemble expects [worker][query]
        return [
            ensemble_predictions([w[i] for w in answered], self._task)
            for i in range(len(queries))
        ]
