"""Predictor: route queries to the serving fleet, gather, and ensemble.

Parity with the reference's Predictor (reference
rafiki/predictor/predictor.py:14-87) — with the reference's two serving
defects fixed by design:

- the reference fanned every query to *every* registered worker, including
  replicas of the same trial (reference predictor.py:39-41), so replicas
  multiplied work instead of capacity. Here workers are grouped by trial:
  each request is ENSEMBLED across trials but LOAD-BALANCED (round-robin,
  with failover to sibling replicas) within a trial's replicas;
- futures + condition variables replace the 0.25 s Redis poll (the
  reference's p50 floor, reference predictor.py:46-59), and a real
  timeout/SLO exists (`PREDICT_TIMEOUT_S`; the reference had a TODO at
  predictor.py:45 and would wait forever on a dead worker) — trials whose
  replicas all miss the deadline are dropped from the ensemble rather than
  stalling the request;
- ``predict_batch`` is implemented (a reference TODO at predictor.py:85-87).
"""

from __future__ import annotations

import collections
import itertools
import logging
import random
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

from rafiki_tpu import config
from rafiki_tpu.cache.queue import (
    Broker,
    GenerationError,
    QueryFuture,
    QueueFullError,
    StreamMigratingError,
)
from rafiki_tpu.predictor.ensemble import _PROB_TASKS, ensemble_predictions

logger = logging.getLogger(__name__)

#: rollout lane labels (admin/rollout.py): while a rollout is in flight,
#: every request is served by exactly ONE version lane — the incumbent
#: fleet or the new-version replicas — never an ensemble across versions
LANE_INCUMBENT = "incumbent"
LANE_CANARY = "canary"


def _top_prob(pred: Any) -> Optional[float]:
    """The served answer's top class probability (probability tasks
    ensemble to one vector per query) — the drift monitor's confidence
    signal. None for anything that isn't a non-empty numeric vector."""
    try:
        if isinstance(pred, (list, tuple)) and pred:
            return float(max(pred))
    except (TypeError, ValueError):
        return None
    return None


class CrossVersionResumeError(GenerationError):
    """A journaled stream's model_version no longer has a routable
    replica (its version lane was rolled back, promoted away, or fully
    drained): resuming on a DIFFERENT version would splice two models'
    token distributions into one stream, so the resume is refused typed
    instead (docs/failure-model.md "Stream continuity")."""


class _JournalEntry:
    """One stream's door-side resume journal record: the original query
    (prompt + pinned sampling seed/params), every token delivered to the
    client so far, and the routing facts a resume needs (model_version,
    lane, current worker). ``tokens`` is appended only by the one door
    thread pumping the stream; the byte books and the cancelled/closed
    flags are shared with Predictor accounting and guarded by the
    predictor's ``_journal_lock``."""

    __slots__ = ("query", "tokens", "max_tokens", "deadline", "version",
                 "lane", "worker_id", "t0", "bytes", "resumable",
                 "attempts", "cancelled", "closed")

    def __init__(self, query: Dict[str, Any], worker_id: str,
                 lane: Optional[str], version: int,
                 deadline: float) -> None:
        self.query = query          # original submit, seed already pinned
        # lint: thread-confined(appended only by the door thread pumping this stream)
        self.tokens: List[int] = []
        self.deadline = deadline
        self.version = version
        self.lane = lane
        self.worker_id = worker_id  # lint: thread-confined(rebound by the pump thread on resume)
        self.t0 = time.monotonic()
        # bytes/resumable/cancelled/closed are shared with Predictor
        # accounting under the OWNING predictor's _journal_lock (an
        # external lock — see the class docstring for the contract)
        self.bytes = 0
        self.resumable = True
        self.attempts = 0           # lint: thread-confined(pump thread)
        self.cancelled = False
        self.closed = False


class Predictor:
    def __init__(self, inference_job_id: str, broker: Broker,
                 task: Optional[str],
                 worker_trials: Optional[Dict[str, str]] = None,
                 serving_version: int = 0):
        """``worker_trials`` maps worker service_id -> trial_id (built by the
        deploy path from the inference_job_worker rows). Workers absent from
        the map are treated as single-replica trials of their own — the
        fan-out-to-all behavior degrades gracefully, never silently drops.

        ``serving_version`` is the fleet's rollout generation (the
        ``model_version`` on the inference_job_worker rows; 0 for an
        initial deploy) — the prediction result cache keys on it, so a
        rebuilt Predictor (recovery adoption) must carry the adopted
        fleet's real version, and a completed rollout bumps it via
        :meth:`set_serving_version`."""
        self._job_id = inference_job_id
        self._broker = broker
        self._task = task
        self._worker_trials = dict(worker_trials or {})
        # elastic serving (admin/autoscaler.py): replicas join and leave
        # at runtime. _route_lock guards the trial map + the draining set;
        # predict_batch works on per-request snapshots, so a concurrent
        # scale action never mutates a request's routing mid-flight.
        self._route_lock = threading.Lock()
        # service_ids being gracefully drained: no NEW requests (first
        # submits or hedges) are routed to them, but their queues stay
        # open until flushed — zero in-flight requests dropped
        self._draining: set = set()
        self._rr = itertools.count()
        # overload-control counters (docs/failure-model.md "Overload
        # faults"), surfaced via the per-job /healthz and GET /fleet/health
        self._ol_lock = threading.Lock()
        self._overload = {
            "hedges": 0,             # failover batches actually issued
            "hedges_suppressed": 0,  # withheld: target replica saturated
            "trials_shed": 0,        # trials dropped: every replica full
            "requests_shed": 0,      # whole requests refused (all full)
        }
        # registry mirrors, labeled by job (utils/metrics.py) — same
        # increment site as the JSON counters so the views cannot drift
        from rafiki_tpu.utils.metrics import REGISTRY

        self._m_overload = {
            key: REGISTRY.counter(
                f"rafiki_predictor_{key}_total",
                f"predictor overload counter: {key}", ("job",)
            ).labels(inference_job_id)
            for key in self._overload
        }
        # per-JOB shed ring (~1 s resolution, utils/metrics.py Ring): the
        # autoscaler attributes overload to a tenant through this series —
        # the door-level shed_rate:<door> rings can't split a shared door
        # by job
        self._ring_shed = REGISTRY.ring(f"shed_rate:job:{inference_job_id}")
        # -- rollout version lanes (admin/rollout.py) ----------------------
        # While a rollout is in flight, requests split by a weighted
        # counter between the incumbent fleet and the new-version
        # replicas; each lane's outcomes (ok/error/shed + latency) feed
        # the SLO judge over a trailing window. Guarded by _route_lock.
        self._lane_new: Optional[set] = None
        self._lane_permille = 0
        self._lane_counter = itertools.count()
        # -- prediction result cache (predictor/result_cache.py) ----------
        # the cache keys on (digest, job, SERVED model version):
        # _serving_version is the incumbent fleet's rollout generation,
        # _lane_version the new version while a rollout lane is set.
        # Both guarded by _route_lock (they change exactly when lane/
        # routing state does).
        self._serving_version = int(serving_version)  # guarded-by: _route_lock
        self._lane_version: Optional[int] = None  # guarded-by: _route_lock
        # sampled duplicate-query probe for the cache-OFF shareable
        # signal (doctor): itertools.count is atomic enough for sampling
        self._share_rr = itertools.count()
        self._cache_degraded_logged = False
        # per-thread digest hand-off from admission_cost to the serve
        # path (one canonical-digest pass per request, not two)
        self._tls = threading.local()
        # (monotonic_ts, duration_s, outcome) per lane, judge-windowed
        self._lane_stats: Dict[str, collections.deque] = {
            LANE_INCUMBENT: collections.deque(maxlen=4096),
            LANE_CANARY: collections.deque(maxlen=4096),
        }
        # registry mirrors so the rollout verdict is readable off
        # GET /metrics too (docs/observability.md)
        self._m_lane_req = REGISTRY.counter(
            "rafiki_rollout_requests_total",
            "requests served per rollout version lane",
            ("job", "lane", "outcome"))
        self._m_lane_lat = REGISTRY.histogram(
            "rafiki_rollout_request_seconds",
            "request latency per rollout version lane", ("job", "lane"))
        # -- drift monitor tap (admin/drift.py; RAFIKI_DRIFT=1) ------------
        # one (wall_ts, digest, top_prob) tuple per served query, bounded:
        # request-handler threads append, the DriftController's tick
        # snapshots the trailing window
        self._drift_lock = threading.Lock()
        self._drift_samples: collections.deque = collections.deque(
            maxlen=4096)  # guarded-by: _drift_lock
        # -- stream continuity: door-side resume journal (docs/
        # failure-model.md "Stream continuity") ---------------------------
        # Per-stream _JournalEntry objects live inside their
        # _ResumableStream wrapper; the predictor keeps the aggregate
        # byte/stream books and the continuity counters here.
        self._journal_lock = threading.Lock()
        self._journal_bytes = 0    # guarded-by: _journal_lock
        self._journal_streams = 0  # guarded-by: _journal_lock
        self._continuity = {       # guarded-by: _journal_lock
            "resumes_migrating": 0,     # drain/rollout handoffs resumed
            "resumes_worker_death": 0,  # dead-replica streams resumed
            "resume_failures": 0,       # client-visible continuity loss
            "journal_overflows": 0,     # streams past RAFIKI_GEN_JOURNAL_MAX_KB
            "cross_version_refusals": 0,
        }
        self._m_resumes = REGISTRY.counter(
            "rafiki_gen_resumes_total",
            "generation streams resumed on a sibling replica, by trigger "
            "(migrating = typed drain/rollout handoff, worker_death = "
            "replica queue vanished mid-stream)", ("job", "reason"))
        self._g_journal = REGISTRY.gauge(
            "rafiki_gen_journal_bytes",
            "bytes held by the door-side generation resume journal",
            ("job",)).labels(inference_job_id)

    def _bump(self, key: str, n: int = 1) -> None:
        with self._ol_lock:
            self._overload[key] += n
        self._m_overload[key].inc(n)
        if key in ("trials_shed", "requests_shed"):
            self._ring_shed.add(n)

    # -- elastic replica membership (admin/autoscaler.py) -------------------

    def add_worker(self, worker_id: str, trial_id: str) -> None:
        """Runtime replica JOIN: route requests to a scaled-up worker the
        moment its queue registers with the broker."""
        with self._route_lock:
            self._worker_trials[worker_id] = trial_id
            self._draining.discard(worker_id)

    def retire_worker(self, worker_id: str) -> None:
        """Begin a graceful LEAVE: stop routing new submits (and hedges)
        to this replica while its queue drains. Idempotent."""
        with self._route_lock:
            self._draining.add(worker_id)

    def unretire_worker(self, worker_id: str) -> None:
        """Abort a LEAVE (a drain that failed mid-way): the replica is
        still placed and routed, so resume sending it traffic rather than
        leaving it retired-but-alive forever."""
        with self._route_lock:
            self._draining.discard(worker_id)

    def drop_worker(self, worker_id: str) -> None:
        """Complete a LEAVE after the drain: forget the replica."""
        with self._route_lock:
            self._worker_trials.pop(worker_id, None)
            self._draining.discard(worker_id)

    def draining_workers(self) -> set:
        with self._route_lock:
            return set(self._draining)

    # -- rollout version lanes (admin/rollout.py; docs/failure-model.md
    # "Rollout faults") ------------------------------------------------------

    def set_rollout_lane(self, new_workers, fraction: float,
                         new_version: Optional[int] = None) -> None:
        """Begin (or re-weight) version-lane routing: ``new_workers`` are
        the new-version replicas; ``fraction`` of requests route to them
        (deterministic weighted counter, not randomness). Starting a lane
        from scratch clears the per-lane outcome history so the judge
        never reads a previous rollout's window.

        ``new_version`` is the canary lane's model version (the rollout
        controller's ``to_version``): the prediction cache keys canary-
        lane traffic on it so a cached canary answer can never leak into
        the incumbent lane. ``None`` keeps the current lane version (the
        re-weight calls mid-rolling and the rollback's fraction-0 call)."""
        permille = max(0, min(int(round(float(fraction) * 1000)), 1000))
        with self._route_lock:
            fresh = self._lane_new is None
            self._lane_new = set(new_workers)
            self._lane_permille = permille
            if new_version is not None:
                self._lane_version = int(new_version)
            if fresh:
                for dq in self._lane_stats.values():
                    dq.clear()

    def clear_rollout_lane(self) -> None:
        """End version-lane routing (rollout done or rolled back): every
        routable replica serves every request again."""
        with self._route_lock:
            self._lane_new = None
            self._lane_permille = 0
            self._lane_version = None

    def set_serving_version(self, version: int) -> None:
        """The incumbent fleet's rollout generation moved (rollout DONE
        promotes ``to_version``): subsequent cache reads/fills key on the
        new version — entries of the replaced model become structurally
        unreachable even before the flush removes them."""
        with self._route_lock:
            self._serving_version = int(version)

    def serving_version(self) -> int:
        with self._route_lock:
            return self._serving_version

    def _lane_snapshot(self):
        with self._route_lock:
            return (set(self._lane_new) if self._lane_new is not None
                    else None), self._lane_permille

    def _lane_take_new(self, permille: int) -> bool:
        """Deterministic weighted lane choice, error-diffusion style:
        canary picks interleave evenly through the request stream (a
        plain ``counter % 1000 < permille`` would send the first
        ``permille`` requests to the canary in one solid burst — the
        judge window would see all-canary then all-incumbent)."""
        n = next(self._lane_counter)
        return (n + 1) * permille // 1000 > n * permille // 1000

    def _lane_record(self, lane: str, outcome: str, duration_s: float) -> None:
        # under the route lock: request-handler threads append here while
        # the rollout judge thread iterates the same deques in
        # rollout_stats(), and a deque mutated during iteration raises
        # RuntimeError — which would surface as a failed judge tick
        with self._route_lock:
            self._lane_stats[lane].append(
                (time.monotonic(), duration_s, outcome))
        self._m_lane_req.labels(self._job_id, lane, outcome).inc()
        if outcome == "ok":
            self._m_lane_lat.labels(self._job_id, lane).observe(duration_s)

    def rollout_stats(self, window_s: float) -> Dict[str, Dict[str, Any]]:
        """Per-lane outcome picture over the trailing ``window_s`` — the
        SLO judge's input: request/error/shed counts and the ok-latency
        p95 (sorted-window quantile; the registry histogram mirrors the
        same series for dashboards)."""
        cutoff = time.monotonic() - max(window_s, 0.0)
        out: Dict[str, Dict[str, Any]] = {}
        with self._route_lock:
            snapshots = {lane: list(dq)
                         for lane, dq in self._lane_stats.items()}
        for lane, entries_all in snapshots.items():
            entries = [e for e in entries_all if e[0] >= cutoff]
            oks = sorted(d for _, d, o in entries if o == "ok")
            errors = sum(1 for e in entries if e[2] == "error")
            shed = sum(1 for e in entries if e[2] == "shed")
            p95 = oks[min(int(len(oks) * 0.95), len(oks) - 1)] if oks \
                else None
            out[lane] = {"requests": len(entries), "ok": len(oks),
                         "errors": errors, "shed": shed, "p95_s": p95}
        return out

    def _route_snapshot(self):
        with self._route_lock:
            return dict(self._worker_trials), set(self._draining)

    def overload_stats(self) -> Dict[str, int]:
        with self._ol_lock:
            return dict(self._overload)

    def queue_depths(self) -> Dict[str, int]:
        """Per-worker inbox depth (queues without a depth signal report
        -1). The serving doors and /fleet/health read this as the job's
        live load picture."""
        out: Dict[str, int] = {}
        for wid, q in self._broker.get_worker_queues(self._job_id).items():
            depth = getattr(q, "depth", None)
            out[wid] = depth() if callable(depth) else -1
        return out

    def queue_stats(self) -> Dict[str, Dict[str, int]]:
        """Per-worker submit-side queue stats for queues that expose
        ``stats()`` — for the shm plane this is where the query ring's
        occupancy high-water mark (``ring_used_bytes_hw``, the
        RAFIKI_SHM_RING_BYTES sizing signal) actually lives: only the
        owner process pushes that ring. Surfaced via the serving door's
        /healthz."""
        out: Dict[str, Dict[str, int]] = {}
        for wid, q in self._broker.get_worker_queues(self._job_id).items():
            stats_fn = getattr(q, "stats", None)
            if callable(stats_fn):
                try:
                    out[wid] = stats_fn()
                except Exception:
                    logger.exception("queue stats probe failed for %s", wid)
        return out

    def backlog_depth(self) -> int:
        """The queue depth a NEW request would actually face: each trial
        answers via its least-loaded replica, and the request waits for
        every trial in the ensemble — so the binding backlog is the max
        across trials of the min across that trial's replicas."""
        depths = self.queue_depths()
        if not depths:
            return 0
        trials, draining = self._route_snapshot()
        groups: Dict[str, List[int]] = {}
        for wid, d in depths.items():
            # draining replicas take no new requests, so their depth is
            # not part of the wait a NEW request faces — unless they are
            # all that's left (the predict fan-out falls back the same
            # way); a queue the trial map doesn't know is a scaled-up
            # replica still WARMING (its worker registers the queue
            # before the model loads) and isn't routable yet either
            if d >= 0 and wid not in draining and (
                    not trials or wid in trials):
                groups.setdefault(trials.get(wid, wid), []).append(d)
        if not groups:
            for wid, d in depths.items():
                if d >= 0:
                    groups.setdefault(trials.get(wid, wid), []).append(d)
        return max((min(ds) for ds in groups.values()), default=0)

    def predict(self, query: Any, timeout_s: Optional[float] = None) -> Any:
        return self.predict_batch([query], timeout_s)[0]

    def generate(self, query: Dict[str, Any],
                 timeout_s: Optional[float] = None):
        """Route one generation request to a worker's slot scheduler and
        return a resumable token stream (:class:`_ResumableStream`, the
        :class:`~rafiki_tpu.cache.queue.TokenStream` surface).

        Generation routes to exactly ONE replica (a token stream cannot be
        ensembled across trials the way one-shot predictions are):
        round-robin over the routable, non-draining workers, walking past
        bounded queues that refuse — same failover shape as the first
        submit of :meth:`predict_batch`. The returned stream's deltas are
        the worker's; the streaming door owns stall detection. Raises
        QueueFullError when every queue refuses, TimeoutError when no
        slot admits the request inside its deadline.

        Stream continuity (docs/failure-model.md "Stream continuity"):
        the door journals the prompt, the pinned sampling seed/params,
        and every delivered token; if the stream dies of an INFRA fault
        (typed MIGRATING handback, or its replica's queue vanishing from
        the broker) the wrapper resumes it on a sibling of the SAME
        model version — prefill of prompt + committed tokens at the same
        seed, which PR 18's position-keyed RNG makes token-identical."""
        timeout_s = (timeout_s if timeout_s is not None
                     else config.PREDICT_TIMEOUT_S)
        deadline = time.monotonic() + timeout_s
        query = dict(query)
        try:
            sampled = float(query.get("temperature") or 0.0) > 0.0
        except (TypeError, ValueError):
            sampled = False
        if sampled and query.get("seed") is None:
            # pin the sampling seed DOOR-side before the first submit: a
            # worker-chosen seed dies with the worker, and PR 18's
            # position-keyed draws only make a resumed continuation
            # token-identical if the sibling replays the SAME seed
            query["seed"] = uuid.uuid4().int & 0x7FFF_FFFF
        stream, wid, lane, version = self._generate_submit(
            query, deadline, frozenset())
        entry = self._journal_open(query, wid, lane, version, deadline)
        return _ResumableStream(self, entry, stream)

    def _generate_submit(self, query: Dict[str, Any], deadline: float,
                         exclude: "frozenset[str]"):
        """One admission pass for a generation query: pick the lane,
        walk the routable replicas past full queues, wait for a slot to
        admit. Returns ``(stream, worker_id, lane, model_version)``.
        ``exclude`` drops specific replicas from the walk (a resume must
        never land back on the worker that just died)."""
        queues = self._broker.get_worker_queues(self._job_id)
        if not queues:
            raise RuntimeError(
                f"No inference workers registered for job {self._job_id}")
        trials, draining = self._route_snapshot()
        routable = [w for w in queues
                    if (not trials or w in trials) and w not in draining
                    and w not in exclude]
        if not routable:
            routable = [w for w in queues
                        if (not trials or w in trials) and w not in exclude] \
                or [w for w in queues if w not in exclude] or list(queues)
        # rollout lane split: a generation stream answers from ONE
        # version — canary-lane streams go only to new-version replicas
        lane_new, permille = self._lane_snapshot()
        lane = None
        if lane_new is not None:
            take_new = self._lane_take_new(permille)
            picked = [w for w in routable if (w in lane_new) == take_new]
            if picked:
                routable = picked
                lane = LANE_CANARY if take_new else LANE_INCUMBENT
            else:
                lane = (LANE_CANARY
                        if all(w in lane_new for w in routable)
                        else LANE_INCUMBENT)
        rr = next(self._rr) % len(routable)
        order = routable[rr:] + routable[:rr]
        fut = None
        timeout_s = max(deadline - time.monotonic(), 0.0)
        for wid in order:
            try:
                fut = queues[wid].submit_many(
                    [dict(query, max_duration_s=timeout_s)],
                    deadline=deadline)[0]
            except QueueFullError:
                continue
            break
        if fut is None:
            self._bump("requests_shed")
            if lane is not None:
                self._lane_record(lane, "shed", 0.0)
            raise QueueFullError(
                f"all serving queues for job {self._job_id} are full")
        # the worker resolves the future with the TokenStream the moment
        # a slot admits the request (prefill done, first token pushed)
        t0 = time.monotonic()
        try:
            stream = fut.result(max(deadline - time.monotonic(), 0.0))
        except Exception:
            if lane is not None:
                self._lane_record(lane, "error", time.monotonic() - t0)
            raise
        if lane is not None:
            self._lane_record(lane, "ok", time.monotonic() - t0)
        # the version this stream is PINNED to: a resume may only ever
        # target replicas serving the same model
        with self._route_lock:
            if (self._lane_new is not None and wid in self._lane_new
                    and self._lane_version is not None):
                version = self._lane_version
            else:
                version = self._serving_version
        return stream, wid, lane, version

    # -- stream continuity: resume journal + sibling resume (docs/
    # failure-model.md "Stream continuity") ---------------------------------

    def _journal_open(self, query: Dict[str, Any], worker_id: str,
                      lane: Optional[str], version: int,
                      deadline: float) -> _JournalEntry:
        entry = _JournalEntry(query, worker_id, lane, version, deadline)
        prompt = query.get("prompt_ids")
        n_prompt = len(prompt) if isinstance(prompt, (list, tuple)) else 0
        cost = 8 * n_prompt + 96  # ~8 B/token id + fixed record overhead
        with self._journal_lock:
            entry.bytes = cost
            self._journal_streams += 1
            self._journal_bytes += cost
            self._g_journal.set(self._journal_bytes)
        return entry

    def _journal_note(self, entry: _JournalEntry, delta) -> None:
        """Commit one delivered delta to the stream's journal. Past the
        RAFIKI_GEN_JOURNAL_MAX_KB byte cap the stream KEEPS STREAMING but
        loses resume eligibility (its bytes are released) — a bounded
        journal can never re-prefill what it did not keep."""
        n = len(delta.tokens)
        if n == 0:
            return
        with self._journal_lock:
            if entry.closed or not entry.resumable:
                return
            entry.tokens.extend(delta.tokens)
            add = 8 * n
            entry.bytes += add
            self._journal_bytes += add
            cap = int(config.GEN_JOURNAL_MAX_KB) * 1024
            if cap > 0 and entry.bytes > cap:
                entry.resumable = False
                entry.tokens = []
                self._journal_bytes -= entry.bytes
                entry.bytes = 0
                self._continuity["journal_overflows"] += 1
            self._g_journal.set(self._journal_bytes)

    def _journal_close(self, entry: _JournalEntry,
                       cancelled: bool = False) -> None:
        """Retire a journal entry (stream finished, errored terminally,
        or the client disconnected): release its bytes and, for a
        cancel, mark it so an in-flight resume/backoff aborts instead of
        re-prefilling for a listener that is gone."""
        with self._journal_lock:
            if cancelled:
                entry.cancelled = True
            if entry.closed:
                return
            entry.closed = True
            entry.tokens = []
            self._journal_streams -= 1
            self._journal_bytes -= entry.bytes
            entry.bytes = 0
            self._g_journal.set(self._journal_bytes)

    def _journal_fail(self, entry: _JournalEntry) -> None:
        """A stream died client-visibly (typed terminal fault, or resume
        exhausted): retire the entry and charge the loss to the stream's
        rollout lane so the SLO judge sees mid-stream deaths, not just
        admission outcomes."""
        with self._journal_lock:
            already = entry.closed
        self._journal_close(entry)
        if not already:
            with self._journal_lock:
                self._continuity["resume_failures"] += 1
            if entry.lane is not None:
                self._lane_record(entry.lane, "error", 0.0)

    def _resume_candidates(self, entry: _JournalEntry):
        """The replicas a journaled stream may resume on: routable,
        not draining, not the replica it just died on, and serving the
        entry's PINNED model_version — during a rollout the new-version
        lane and the incumbent fleet are disjoint resume domains.
        Raises :class:`CrossVersionResumeError` when the version has no
        replica left (typed: splicing versions is never an option)."""
        queues = self._broker.get_worker_queues(self._job_id)
        trials, draining = self._route_snapshot()
        with self._route_lock:
            lane_new = (set(self._lane_new)
                        if self._lane_new is not None else None)
            lane_version = self._lane_version
            serving = self._serving_version
        routable = [w for w in queues
                    if (not trials or w in trials) and w not in draining
                    and w != entry.worker_id]
        if lane_new is not None:
            if lane_version is not None and entry.version == lane_version \
                    and lane_version != serving:
                cands = [w for w in routable if w in lane_new]
            elif entry.version == serving:
                cands = [w for w in routable if w not in lane_new]
            else:
                cands = []
        else:
            cands = routable if entry.version == serving else []
        if not cands:
            with self._journal_lock:
                self._continuity["cross_version_refusals"] += 1
            raise CrossVersionResumeError(
                f"stream cannot resume: no routable sibling serves its "
                f"model_version {entry.version} (fleet serves "
                f"{serving}" + (f", canary lane {lane_version}"
                                if lane_version is not None else "") + ")")
        return cands, queues

    def _resume_stream(self, entry: _JournalEntry, reason: str):
        """Resume a journaled stream on a sibling: RESUME submit of
        prompt + committed tokens at the pinned seed, under bounded
        jittered retries (RAFIKI_GEN_RESUME_MAX across the stream's
        lifetime, backoff base RAFIKI_GEN_RESUME_BACKOFF_S), honoring
        the original request deadline and the journal TTL. Returns the
        new inner TokenStream; raises :class:`GenerationError` (typed)
        when the stream cannot be resumed."""
        max_attempts = int(config.GEN_RESUME_MAX)
        base = max(float(config.GEN_RESUME_BACKOFF_S), 0.0)
        with self._journal_lock:
            ok = entry.resumable and not entry.cancelled and not entry.closed
        if not ok:
            raise GenerationError(
                "stream is not resumable (journal overflowed "
                "RAFIKI_GEN_JOURNAL_MAX_KB, or the client is gone)")
        if max_attempts <= 0:
            raise GenerationError(
                "stream resume is disabled (RAFIKI_GEN_RESUME_MAX=0)")
        if time.monotonic() - entry.t0 > float(config.GEN_JOURNAL_TTL_S):
            raise GenerationError(
                "resume journal entry expired (RAFIKI_GEN_JOURNAL_TTL_S)")
        last_err: Optional[Exception] = None
        while entry.attempts < max_attempts:
            entry.attempts += 1
            if entry.attempts > 1:
                # jittered exponential backoff, capped by the deadline;
                # a client disconnect mid-backoff cancels the journal
                # entry, so re-check after every sleep
                delay = base * (2 ** (entry.attempts - 2)) \
                    * random.uniform(0.5, 1.0)
                delay = min(delay, entry.deadline - time.monotonic())
                if delay > 0:
                    time.sleep(delay)
            with self._journal_lock:
                if entry.cancelled or entry.closed:
                    raise GenerationError(
                        "stream resume abandoned: client disconnected")
                resume_tokens = list(entry.tokens)
            remaining = entry.deadline - time.monotonic()
            if remaining <= 0:
                raise GenerationError(
                    "request deadline passed before the stream could "
                    "be resumed")
            cands, queues = self._resume_candidates(entry)
            rr = next(self._rr) % len(cands)
            for wid in cands[rr:] + cands[:rr]:
                q = dict(entry.query)
                q["resume_tokens"] = resume_tokens
                q["max_duration_s"] = remaining
                try:
                    fut = queues[wid].submit_many(
                        [q], deadline=entry.deadline)[0]
                    stream = fut.result(
                        max(entry.deadline - time.monotonic(), 0.0))
                # lint: absorb(a sibling that refuses or fails the resume is walked past; the bounded retry loop owns giving up)
                except Exception as e:
                    last_err = e
                    continue
                entry.worker_id = wid
                self._m_resumes.labels(self._job_id, reason).inc()
                with self._journal_lock:
                    self._continuity[f"resumes_{reason}"] = (
                        self._continuity.get(f"resumes_{reason}", 0) + 1)
                logger.info(
                    "stream resumed on sibling %s (reason=%s, attempt "
                    "%d/%d, %d committed tokens)", wid, reason,
                    entry.attempts, max_attempts, len(resume_tokens))
                return stream
        detail = f": {last_err!r}" if last_err is not None else ""
        raise GenerationError(
            f"stream resume exhausted after {entry.attempts} attempt(s) "
            f"(RAFIKI_GEN_RESUME_MAX={max_attempts}){detail}")

    def gen_continuity_stats(self) -> Dict[str, int]:
        """The job's stream-continuity picture (fleet-health's
        serving.generation rollup + /healthz): resume counts by trigger,
        client-visible continuity losses, journal occupancy."""
        with self._journal_lock:
            out = dict(self._continuity)
            out["journal_streams"] = self._journal_streams
            out["journal_bytes"] = self._journal_bytes
        return out

    def predict_batch(
        self, queries: List[Any], timeout_s: Optional[float] = None,
        trace=None,
    ) -> List[Any]:
        """One replica per trial answers each request (round-robin with
        failover); the ensemble is across trials. ``trace`` (a sampled
        request's RequestTrace) rides the FIRST submit of each trial so
        worker-side spans land in the door's span tree; hedge batches are
        duplicate work and stay untraced.

        While a rollout lane is set (admin/rollout.py), each request is
        served by exactly ONE version lane — predictions are never
        ensembled across model versions. A canary-lane request whose new-
        version replica sheds or errors **fails over to the incumbent
        lane** (bounded blast radius: a bad canary costs the judge an
        error sample, never the client a request); incumbent-lane
        failures never fall back onto the version under judgment.

        With ``RAFIKI_PREDICT_CACHE=1`` (predictor/result_cache.py),
        repeated identical queries are answered from a bounded versioned
        cache before any worker queue is touched, and concurrent
        identical misses coalesce into one forward (single-flight). The
        cache path is taken per query, so a mixed request forwards only
        its misses — the batching-aware fill then lands one entry per
        resolved query."""
        timeout_s = timeout_s if timeout_s is not None else config.PREDICT_TIMEOUT_S
        deadline = time.monotonic() + timeout_s
        queues = self._broker.get_worker_queues(self._job_id)
        if not queues:
            raise RuntimeError(
                f"No inference workers registered for job {self._job_id}"
            )
        trials, draining = self._route_snapshot()
        routable = [w for w in queues
                    if not trials or w in trials] or list(queues)
        lane_new, permille = self._lane_snapshot()
        # ONE lane draw per request, shared by the cached and uncached
        # paths (drawing per sub-batch would skew the canary interleave),
        # and ONE lane split shared by the cache plan and the serving
        # path — the cache must key on the lane that will actually serve
        take_new = (self._lane_take_new(permille)
                    if lane_new is not None else False)
        split = self._lane_split(routable, lane_new, take_new)
        plan = self._cache_plan(split)
        if plan is None:
            # consume any digest stash admission_cost left on this thread
            # (the uncached serve path has no other consumer; the drift
            # tap reuses it when present, else hashes on demand)
            digests = self._take_digest_stash(queries)
            self._maybe_note_shareable(queries)
            preds, _fillable = self._serve_lanes(
                queries, queues, routable, trials, draining, deadline,
                trace, split)
            self._drift_note(queries, digests, preds)
            return preds
        return self._serve_cached(
            plan, queries, queues, routable, trials, draining, deadline,
            trace, split)

    @staticmethod
    def _lane_split(routable: List[str], lane_new: Optional[set],
                    take_new: bool):
        """The one routing decision for a laned request, shared by the
        cache plan and the serving path: ``None`` with no lane set, else
        ``(primary, fallback, lane, pure)`` — ``pure`` is False when the
        CANARY label is serving a set that may contain incumbents (the
        canary replica vanished and ``routable`` is all that's left), in
        which case nothing served here may be cached under the new
        version."""
        if lane_new is None:
            return None
        new_r = [w for w in routable if w in lane_new]
        old_r = [w for w in routable if w not in lane_new]
        if take_new and new_r:
            return new_r, old_r, LANE_CANARY, True
        if old_r:
            return old_r, [], LANE_INCUMBENT, True
        # nothing but new-version replicas left (tail of the rolling
        # phase): they serve everything
        return new_r or routable, [], LANE_CANARY, bool(new_r)

    def _serve_lanes(
        self, queries: List[Any], queues, routable: List[str],
        trials: Dict[str, str], draining: set, deadline: float, trace,
        split,
    ) -> "tuple[List[Any], bool]":
        """Route one (sub-)request through the version lanes (or the
        whole fan-out when no lane is set) and record the lane outcome.
        Returns ``(predictions, fillable)``: False when the answer must
        not be cached under the plan's version key — a trial was shed or
        SLO-dropped (a degraded ensemble must not be memorized for the
        TTL), or a canary-lane failure FAILED OVER to the incumbents
        (the old model's forward must never land under the new version's
        key)."""
        if split is None:
            return self._predict_on(
                queries, queues, routable, trials, draining, deadline,
                trace)
        primary, fallback, lane, _pure = split
        t0 = time.monotonic()
        try:
            preds, fillable = self._predict_on(
                queries, queues, primary, trials, draining, deadline,
                trace)
        except QueueFullError:
            self._lane_record(lane, "shed", time.monotonic() - t0)
            if lane == LANE_CANARY and fallback \
                    and time.monotonic() < deadline:
                preds, _ = self._predict_on(
                    queries, queues, fallback, trials, draining, deadline,
                    trace)
                return preds, False  # incumbent forward: never cacheable
            raise
        except Exception:
            self._lane_record(lane, "error", time.monotonic() - t0)
            if lane == LANE_CANARY and fallback \
                    and time.monotonic() < deadline:
                preds, _ = self._predict_on(
                    queries, queues, fallback, trials, draining, deadline,
                    trace)
                return preds, False  # incumbent forward: never cacheable
            raise
        self._lane_record(lane, "ok", time.monotonic() - t0)
        return preds, fillable

    # -- prediction result cache (predictor/result_cache.py; docs/
    # performance.md "Prediction caching & single-flight") -------------------

    def _cacheable_task(self) -> bool:
        """Caching needs the served answer to be a deterministic function
        of (query, model version). TEXT_GENERATION streams never ride
        predict_batch but a misrouted probe must still be refused; a
        non-probability task ensembled across SEVERAL trials answers with
        whichever trial happened to respond first — stochastic under
        failover/round-robin, so excluded."""
        from rafiki_tpu.constants import TaskType

        if self._task == TaskType.TEXT_GENERATION:
            return False
        if self._task in _PROB_TASKS:
            return True
        with self._route_lock:
            groups = set(self._worker_trials.values())
        return len(groups) <= 1

    def _cache_plan(self, split) -> "Optional[tuple[int, bool]]":
        """``None`` when this request must bypass the cache entirely,
        else ``(version, read_ok)`` — the model version to key on and
        whether cached answers may be SERVED. Canary-lane requests are
        fill-only (``read_ok=False``): the SLO judge needs real forwards
        to sample, and coalescing/serving from cache would starve it —
        their fills land under the lane's version, so they can never be
        read back by incumbent-lane traffic. An IMPURE canary split (the
        canary replica vanished and whatever is routable serves under
        the CANARY label) bypasses the cache outright: the serving set's
        version is unknowable, so neither key space may be read or
        filled."""
        if not config.PREDICT_CACHE or not self._cacheable_task():
            return None
        if split is not None:
            _primary, _fallback, lane, pure = split
            if lane == LANE_CANARY:
                if not pure:
                    return None
                with self._route_lock:
                    lane_version = self._lane_version
                    serving = self._serving_version
                return ((lane_version if lane_version is not None
                         else serving), False)
        with self._route_lock:
            return (self._serving_version, True)

    def _cache_op(self, fn, fallback):
        """Degrade guard around EVERY cache operation: a broken cache
        (RAFIKI_CHAOS site=cache, or any internal fault) serves the miss
        path, never a failed request."""
        try:
            return fn()
        # lint: absorb(a broken prediction cache degrades to miss-path serving, never fails a request)
        except Exception:
            from rafiki_tpu.predictor import result_cache

            if not self._cache_degraded_logged:
                self._cache_degraded_logged = True
                logger.warning(
                    "prediction cache degraded for job %s; serving the "
                    "miss path (logged once)", self._job_id,
                    exc_info=True)
            try:
                result_cache.get_cache().note_degraded()
            # lint: absorb(the degraded-counter bump is itself best-effort)
            except Exception:
                pass
            return fallback

    def _serve_cached(
        self, plan: "tuple[int, bool]", queries: List[Any], queues,
        routable: List[str], trials: Dict[str, str], draining: set,
        deadline: float, trace, split,
    ) -> List[Any]:
        """The cache-fronted serve: answer per-query hits from the
        versioned cache, coalesce concurrent identical misses behind one
        single-flight leader, forward ONLY the remaining misses as one
        sub-batch, then fill per-query entries from the resolved batch."""
        from rafiki_tpu.predictor import result_cache

        version, read_ok = plan
        cache = result_cache.get_cache()
        job = self._job_id
        epoch = self._cache_op(lambda: cache.epoch(job), 0)
        digests = self._request_digests(queries)
        results: List[Any] = [None] * len(queries)
        use_sf = read_ok and bool(config.PREDICT_SINGLEFLIGHT)
        followers: Dict[int, QueryFuture] = {}
        lead: Dict[str, List[int]] = {}  # digest -> this request's indices
        flights: Dict[str, Any] = {}     # digest -> flight this thread leads
        miss_idx: List[int] = []
        for i, d in enumerate(digests):
            if d is None:
                miss_idx.append(i)  # uncacheable: always a forward
                continue
            if d in lead:
                # duplicate inside one request: one forward, shared below
                lead[d].append(i)
                continue
            if read_ok:
                hit, value = self._cache_op(
                    lambda d=d: cache.lookup(job, version, d),
                    (False, None))
                if hit:
                    results[i] = value
                    continue
            if use_sf:
                role = self._cache_op(
                    lambda d=d: cache.join_flight(job, version, d), None)
                if role is not None:
                    leader, flight = role
                    if not leader:
                        followers[i] = flight.future
                        continue
                    flights[d] = flight
            lead[d] = [i]
            miss_idx.append(i)
        fillable = False
        if miss_idx:
            try:
                miss_preds, fillable = self._serve_lanes(
                    [queries[i] for i in miss_idx], queues, routable,
                    trials, draining, deadline, trace, split)
            except BaseException as e:
                # followers of this leader's flights must fail typed NOW,
                # not hang to their own deadlines
                for d, flight in flights.items():
                    cache.fail_flight(job, version, d, flight, e)
                raise
            for i, pred in zip(miss_idx, miss_preds):
                results[i] = pred
        for d, idxs in lead.items():
            value = results[idxs[0]]
            for j in idxs[1:]:
                results[j] = value
            if d in flights:
                cache.resolve_flight(job, version, d, flights[d], value)
            if fillable:
                self._cache_op(
                    lambda d=d, v=value: cache.fill(job, version, d, v,
                                                    epoch),
                    False)
        # followers LAST: every flight this thread leads is resolved
        # above, so two requests leading/following each other's digests
        # can never deadlock. A leader-side error re-raises here as a
        # per-waiter copy (QueryFuture semantics); a silent leader runs
        # this request into its own SLO timeout.
        for i, fut in followers.items():
            results[i] = fut.result(max(deadline - time.monotonic(), 0.0))
        self._drift_note(queries, digests, results)
        return results

    def _take_digest_stash(self, queries: List[Any]):
        """Consume the thread-local digest hand-off from
        :meth:`admission_cost` — cleared UNCONDITIONALLY (matching or
        not): a stash a shed request left behind must not outlive the
        thread's next predict. (Retention bound without this call: one
        request payload per live connection — ThreadingHTTPServer runs
        one thread per connection — until disconnect.)"""
        stash = getattr(self._tls, "digests", None)
        if stash is not None:
            self._tls.digests = None
            if stash[0] is queries:
                return stash[1]
        return None

    def _request_digests(self, queries: List[Any]) -> List[Optional[str]]:
        """Per-query canonical digests, computed ONCE per request: the
        door's :meth:`admission_cost` stashes its digests in a
        thread-local keyed by the very ``queries`` object (the door
        calls predict on the same handler thread with the same list), so
        the serve path never re-hashes the payload. The stash holds a
        strong reference to the list, so its identity cannot be recycled
        while the entry lives."""
        stashed = self._take_digest_stash(queries)
        if stashed is not None:
            return stashed
        from rafiki_tpu.cache import wire

        return [
            self._cache_op(lambda q=q: wire.canonical_digest(q), None)
            for q in queries]

    def admission_cost(self, queries: List[Any]) -> int:
        """The doors' misses-only admission/fairness cost: queries the
        cache will answer shed no load, so tenant fairness (PR 7) must
        not charge for them. Full cost while a rollout lane is set (the
        lane draw happens per request, later) and whenever the cache is
        off, excluded, or degraded."""
        lane_new, _permille = self._lane_snapshot()
        if lane_new is not None:
            return len(queries)
        plan = self._cache_plan(None)
        if plan is None:
            return len(queries)
        version, _read_ok = plan

        def peek() -> int:
            from rafiki_tpu.predictor import result_cache

            digests = self._request_digests(queries)
            # hand the digests to the serve path on this same thread —
            # predict_batch is the door's very next call with this list
            self._tls.digests = (queries, digests)
            return result_cache.get_cache().peek_misses(
                self._job_id, version, digests)

        return self._cache_op(peek, len(queries))

    def _maybe_note_shareable(self, queries: List[Any]) -> None:
        """Cache-OFF duplicate-traffic probe (sampled 1-in-16 so the
        uncached hot path never pays a digest per request): feeds the
        ``rafiki_cache_shareable_total`` counter the doctor reads as
        "identical-query traffic is being forwarded redundantly — turn
        the cache on"."""
        if config.PREDICT_CACHE or not queries:
            return
        if next(self._share_rr) % 16:
            return
        if not self._cacheable_task():
            return

        def probe() -> None:
            from rafiki_tpu.cache import wire
            from rafiki_tpu.predictor import result_cache

            result_cache.get_cache().note_shareable(
                self._job_id, wire.canonical_digest(queries[0]))

        self._cache_op(probe, None)

    # -- drift monitor tap (admin/drift.py; docs/failure-model.md
    # "Model drift faults") --------------------------------------------------

    def _drift_note(self, queries: List[Any],
                    digests: "Optional[List[Optional[str]]]",
                    preds: Optional[List[Any]]) -> None:
        """Feed the drift monitor's sample window: one (wall_ts, digest,
        top_prob) tuple per served query. A no-op unless RAFIKI_DRIFT=1,
        and even then strictly observational — any failure here is
        absorbed, never surfaced to the served request."""
        if not config.DRIFT or not queries:
            return
        try:
            if digests is None:
                from rafiki_tpu.cache import wire

                digests = [
                    self._cache_op(lambda q=q: wire.canonical_digest(q),
                                   None)
                    for q in queries]
            now = time.time()
            prob_task = self._task in _PROB_TASKS
            with self._drift_lock:
                for i, digest in enumerate(digests):
                    conf = None
                    if prob_task and preds is not None and i < len(preds):
                        conf = _top_prob(preds[i])
                    self._drift_samples.append((now, digest, conf))
        # lint: absorb(the drift tap is observational: a broken monitor feed must never fail a served request)
        except Exception:
            logger.debug("drift tap failed for job %s", self._job_id,
                         exc_info=True)

    def drift_window(self, window_s: float) -> List[tuple]:
        """Samples from the trailing ``window_s`` seconds (wall clock),
        oldest first — the DriftController's per-tick snapshot."""
        cut = time.time() - float(window_s)
        with self._drift_lock:
            return [s for s in self._drift_samples if s[0] >= cut]

    def _predict_on(
        self, queries: List[Any], queues, routable: List[str],
        trials: Dict[str, str], draining: set, deadline: float, trace,
    ) -> "tuple[List[Any], bool]":
        """Serve one request against the given routable worker set (the
        whole fan-out normally; one version lane during a rollout).
        Returns ``(predictions, complete)``; ``complete`` is False when
        any trial was shed or SLO-dropped from the ensemble (the cache
        must not memorize a degraded answer for the TTL)."""
        # group live workers by trial; with no trial map at all (legacy
        # standalone jobs) unknown workers stand alone, but when a map
        # exists an unmapped queue is a scaled-up replica still WARMING
        # (workers register their queue before the model loads) — routing
        # to it would park requests behind a model load, so it joins the
        # fan-out only when add_worker maps it. Draining replicas
        # (graceful scale-down) are left out of the fan-out so their
        # queues empty — but if a trial has ONLY draining replicas left,
        # they still serve it (drain is a routing preference, never a
        # way to lose a trial from the ensemble).
        groups: Dict[str, List[str]] = {}
        if draining:
            active = [w for w in routable if w not in draining]
            for wid in active:
                groups.setdefault(trials.get(wid, wid), []).append(wid)
            for wid in routable:
                if wid in draining and trials.get(wid, wid) not in groups:
                    groups.setdefault(trials.get(wid, wid), []).append(wid)
        else:
            for wid in routable:
                groups.setdefault(trials.get(wid, wid), []).append(wid)
        rr = next(self._rr)
        trial_predictions: List[Optional[List[Any]]] = []
        # submit the first attempt for every trial up front so replicas of
        # different trials run concurrently, then gather per trial
        orders = {
            trial: wids[rr % len(wids):] + wids[:rr % len(wids)]
            for trial, wids in groups.items()
        }
        # First submit walks the replica order past bounded queues that
        # refuse (QueueFullError): a full replica is just a load signal to
        # try its sibling. The order is rotated so failover/hedging starts
        # from whoever actually accepted; skipped-full replicas move to
        # the back (they may have drained by hedge time). A trial whose
        # EVERY replica refuses is shed from this request's ensemble; if
        # every trial sheds, the whole request is refused — that is the
        # doors' 429.
        inflight: Dict[str, List[QueryFuture]] = {}
        for trial, order in list(orders.items()):
            for k, wid in enumerate(order):
                try:
                    # trace kwarg only when sampled — unsampled traffic
                    # keeps the pre-trace call shape for queue fakes
                    inflight[trial] = queues[wid].submit_many(
                        queries, deadline=deadline,
                        **({"trace": trace} if trace is not None else {}))
                except QueueFullError:
                    continue
                orders[trial] = order[k:] + order[:k]
                break
            else:
                self._bump("trials_shed")
                logger.info("trial %s shed from this request: every "
                            "replica queue of %s is full", trial, order)
        if not inflight:
            self._bump("requests_shed")
            raise QueueFullError(
                f"all serving queues for job {self._job_id} are full")
        for trial, futs in inflight.items():
            preds = self._gather_with_failover(
                trial, orders[trial], queues, queries, futs, deadline)
            trial_predictions.append(preds)
        answered = [p for p in trial_predictions if p is not None]
        if not answered:
            raise TimeoutError("No inference worker answered within the SLO")
        # transpose: ensemble expects [trial][query]
        return [
            ensemble_predictions([w[i] for w in answered], self._task)
            for i in range(len(queries))
        ], len(answered) == len(groups)

    def _gather_with_failover(self, trial, order, queues, queries,
                              first_futs, deadline) -> Optional[List[Any]]:
        """Gather one trial's predictions, hedging across its replicas.

        The request deadline is split across the remaining replicas
        (remaining/k) so a *silently* dead replica — no error, just no
        answer — still leaves budget to try a sibling. Hedged batches are
        never abandoned: once more than one batch is in flight, a poll loop
        sweeps ALL of them, so a healthy-but-slow first replica that
        answers after its hedge fired still serves the request within the
        SLO.

        Hedging is load-aware: a sibling whose queue depth exceeds
        ``RAFIKI_PREDICT_HEDGE_SUPPRESS_DEPTH`` never receives the hedge
        batch — when replicas are slow *because the job is overloaded*,
        hedges are duplicate work that make every queue deeper, the
        metastable "hedge storm" of Dean & Barroso's tail-latency paper.
        A suppressed hedge keeps sweeping the batches already in flight
        instead."""
        issued: List[List[QueryFuture]] = [list(first_futs)]
        attempt = 0
        while True:
            attempts_left = len(order) - attempt
            if attempts_left <= 0:
                break
            attempt_deadline = min(
                deadline,
                time.monotonic()
                + max(deadline - time.monotonic(), 0.0) / attempts_left)
            if len(issued) == 1:
                # common case: one batch in flight — block directly, no
                # polling overhead on the fast path
                try:
                    return [
                        f.result(max(attempt_deadline - time.monotonic(), 0.0))
                        for f in issued[0]
                    ]
                except Exception as e:
                    logger.info("replica %s failed (%r); failing over",
                                order[attempt], e)
                    if isinstance(e, TimeoutError):
                        # silent replica: keep its futures in the sweep pool
                        pass
                    else:
                        issued.pop()
            else:
                preds = self._sweep(issued, attempt_deadline)
                if preds is not None:
                    return preds
            attempt += 1
            if attempt < len(order) and time.monotonic() < deadline:
                hedge = self._try_hedge(
                    queues[order[attempt]], order[attempt], queries, deadline)
                if hedge is not None:
                    issued.append(hedge)
        # final sweep: any in-flight batch may still land before the SLO
        preds = self._sweep(issued, deadline) if issued else None
        if preds is None:
            logger.warning("trial %s dropped from ensemble: no replica of %s "
                           "answered within the SLO", trial, order)
        return preds

    def _try_hedge(self, queue, worker_id: str, queries: List[Any],
                   deadline: float) -> Optional[List[QueryFuture]]:
        """Issue one failover batch unless the target replica is already
        saturated (queue depth over the suppression threshold, or its
        bounded queue refuses outright). Returns the hedge futures, or
        None when the hedge was suppressed."""
        threshold = int(config.PREDICT_HEDGE_SUPPRESS_DEPTH)
        depth_fn = getattr(queue, "depth", None)
        if (threshold > 0 and callable(depth_fn)
                and depth_fn() > threshold):
            self._bump("hedges_suppressed")
            logger.info(
                "hedge to replica %s suppressed: queue depth %d over the "
                "suppression threshold %d", worker_id, depth_fn(), threshold)
            return None
        try:
            futs = queue.submit_many(queries, deadline=deadline)
        except QueueFullError:
            self._bump("hedges_suppressed")
            logger.info("hedge to replica %s suppressed: queue full",
                        worker_id)
            return None
        self._bump("hedges")
        return futs

    @staticmethod
    def _sweep(issued: List[List[QueryFuture]],
               until: float) -> Optional[List[Any]]:
        """Poll every in-flight batch until one completes or `until`.

        20 ms granularity — only reached on the failover path, where a
        replica already blew its share of the SLO."""
        while True:
            for futs in list(issued):
                try:
                    return [f.result(0.0) for f in futs]
                except TimeoutError:
                    continue  # not ready yet — keep it in the pool
                # lint: absorb(failed replica leaves the hedge pool; survivors or the SLO timeout answer)
                except Exception:
                    issued.remove(futs)  # replica answered with an error
            if not issued or time.monotonic() >= until:
                return None
            time.sleep(min(0.02, max(until - time.monotonic(), 0.0)))


class _ResumableStream:
    """Door-side stream continuity (docs/failure-model.md "Stream
    continuity"): the stream handle :meth:`Predictor.generate` returns.
    Journals every delta it delivers and, when the stream dies of an
    INFRA-class fault, transparently resumes it on a sibling replica:

    - a typed MIGRATING handback (:class:`StreamMigratingError` — the
      replica is draining for scale-down or rollout retirement), or
    - the replica's death (``next_delta`` timed out AND the worker's
      queue is gone from the broker — a SIGKILL'd worker unregisters on
      the way down, and a genuinely vanished host is indistinguishable
      from that door-side).

    A timeout while the worker is still registered is a genuine decode
    stall and re-raises for the door's typed stall handling; a plain
    :class:`GenerationError` is a model-class fault and is never
    retried (resuming poison replays poison). Exposes the TokenStream
    surface (``next_delta``/``cancel``/``seq_id``) so the streaming
    doors and clients need no changes."""

    def __init__(self, predictor: Predictor, entry: _JournalEntry,
                 inner) -> None:
        self._p = predictor
        self._entry = entry
        self._inner = inner  # lint: thread-confined(rebound only by the door thread pumping this stream)

    @property
    def seq_id(self):
        return self._inner.seq_id

    def cancel(self) -> None:
        """Client gone: retire the journal entry FIRST so a resume
        backoff in flight aborts, then cancel the live worker slot."""
        self._p._journal_close(self._entry, cancelled=True)
        self._inner.cancel()

    def next_delta(self, timeout: Optional[float] = None):
        while True:
            try:
                delta = self._inner.next_delta(timeout=timeout)
            except StopIteration:
                self._p._journal_close(self._entry)
                raise
            except StreamMigratingError:
                self._resume_or_raise("migrating")
                continue
            except TimeoutError:
                if self._worker_alive():
                    raise  # genuine stall: the door owns the typed frame
                self._resume_or_raise("worker_death")
                continue
            except GenerationError:
                self._p._journal_fail(self._entry)
                raise
            self._p._journal_note(self._entry, delta)
            if delta.finished:
                self._p._journal_close(self._entry)
            return delta

    def _worker_alive(self) -> bool:
        queues = self._p._broker.get_worker_queues(self._p._job_id)
        return self._entry.worker_id in queues

    def _resume_or_raise(self, reason: str) -> None:
        """Swap the inner stream for a sibling's resumed one, or retire
        the journal and surface a typed terminal fault. Cross-version
        refusals keep their own type (:class:`CrossVersionResumeError`);
        a MIGRATING handback must never leak to the client as such."""
        try:
            self._inner = self._p._resume_stream(self._entry, reason)
        except GenerationError as e:
            self._p._journal_fail(self._entry)
            if isinstance(e, StreamMigratingError):
                raise GenerationError(str(e)) from e
            raise
        except Exception as e:
            self._p._journal_fail(self._entry)
            raise GenerationError(
                f"stream died ({reason}) and could not be resumed: "
                f"{e!r}") from e
