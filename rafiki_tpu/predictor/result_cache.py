"""Bounded, versioned prediction result cache with single-flight
coalescing — the data plane's "stop doing the work at all" tier.

With the binary wire codec, batched workers, and elastic replicas in
place, the remaining serving lever is not executing redundant forwards:
under a Zipfian traffic mix, identical queries should pay ONE model
forward, not N. This module is that tier, answered at the Predictor
BEFORE a worker queue is ever touched:

- **Keying.** Queries are content-hashed through the canonical wire
  encoding (``cache/wire.canonical_digest`` — the binary v1 frame for
  array payloads, sorted-key canonical JSON otherwise) into a digest;
  entries are keyed ``(inference_job_id, served model_version,
  digest)``. The version component is what makes staleness structural:
  a rollout's new version writes and reads a different key space, so a
  cached canary answer can never be served to an incumbent-lane request
  however the flush timing races.

- **Bounds.** One TTL (``RAFIKI_PREDICT_CACHE_TTL_S``) plus a byte cap
  (``RAFIKI_PREDICT_CACHE_MAX_BYTES``) enforced LRU — the cache can
  never grow past its budget however hot the traffic.

- **Single-flight.** Concurrent identical *in-flight* misses share one
  :class:`~rafiki_tpu.cache.queue.QueryFuture`: the first requester
  (the leader) executes the real forward and resolves the flight; the
  followers wait on it and are counted ``coalesced``. A stampede of K
  identical cold queries costs exactly one worker batch.

- **Invalidation.** ``flush_job`` drops a job's entries and bumps its
  *fill epoch*; fills carry the epoch observed at miss time and are
  dropped when it moved — a forward that resolved against the
  pre-flush fleet can never repopulate the cache after a deploy,
  rollback, or recovery adoption invalidated it. Call sites:
  ``ServicesManager._teardown_serving`` (stop/redeploy),
  ``ServicesManager.adopt_inference_job`` (recovery adoption),
  ``RolloutController`` (rollout DONE keeps only the new version;
  rollback drops everything).

- **Degradation.** Every operation asks ``RAFIKI_CHAOS site=cache``
  first, and the Predictor absorbs ANY cache exception into the miss
  path — a broken cache serves real forwards, never a failed request.

Locking protocol (concurrency analyzer, docs/static-analysis.md): one
``_lock`` guards every piece of shared state — the entry map, the byte
total, the per-job epochs, and the single-flight registry. Public
methods take the lock for O(1)-ish critical sections and never call
user code or block while holding it; flight waiters block on the
flight's own QueryFuture *outside* the lock. Registry metric objects
are internally locked by utils/metrics.py and are incremented outside
``_lock`` where convenient.
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

from rafiki_tpu.cache.queue import QueryFuture
from rafiki_tpu.utils import chaos

logger = logging.getLogger(__name__)

#: byte-estimate floor per entry: the key tuple, OrderedDict slot, and
#: list cell cost real memory even for a tiny prediction
_ENTRY_OVERHEAD = 256


class CacheChaosError(RuntimeError):
    """A ``RAFIKI_CHAOS site=cache`` rule fired on a cache operation.
    Only ever raised INTO the predictor's absorb-and-degrade guard —
    the drill that proves a broken cache never fails a request."""


class _Flight:
    """One in-flight single-flight entry: the leader's pending result.
    The object itself is the leader's resolution token — resolve/fail
    complete THIS flight's future whether or not it is still registered
    (a flush may have detached it; its waiters must still be answered)."""

    __slots__ = ("future",)

    def __init__(self) -> None:
        self.future = QueryFuture()


def _estimate_bytes(value: Any, depth: int = 0) -> int:
    """Cheap recursive size estimate of a JSON-native prediction (the
    ensemble layer strips numpy before results reach the cache). Depth-
    bounded: a pathological nesting just over-counts via the fallback."""
    if depth > 6:
        return 64
    if value is None or isinstance(value, bool):
        return 8
    if isinstance(value, (int, float)):
        return 16
    if isinstance(value, str):
        return 48 + len(value)
    if isinstance(value, bytes):
        return 48 + len(value)
    if isinstance(value, dict):
        return 64 + sum(_estimate_bytes(k, depth + 1)
                        + _estimate_bytes(v, depth + 1)
                        for k, v in value.items())
    if isinstance(value, (list, tuple)):
        return 64 + sum(_estimate_bytes(v, depth + 1) for v in value)
    nbytes = getattr(value, "nbytes", None)  # stray ndarray
    if isinstance(nbytes, int):
        return 64 + nbytes
    return 128


class ResultCache:
    """Process-wide prediction result cache (one per process, like the
    metrics registry — both serving doors of every job in this admin
    share it; the job id in the key keeps tenants apart)."""

    def __init__(self, max_bytes: Optional[int] = None,
                 ttl_s: Optional[float] = None) -> None:
        #: None defers to the RAFIKI_PREDICT_CACHE_* knobs lazily per
        #: operation, so a live deployment's next request picks up a
        #: retune without re-importing
        self._max_bytes = max_bytes
        self._ttl_s = ttl_s
        self._lock = threading.Lock()
        # (job, version, digest) -> [value, nbytes, expires_at_monotonic]
        self._entries: "collections.OrderedDict[Tuple[str, int, str], list]" \
            = collections.OrderedDict()  # guarded-by: _lock
        self._bytes = 0  # guarded-by: _lock
        # incremental per-job entry counts so stats() never walks the
        # whole entry map under _lock (the serving hot path shares it)
        self._job_entries: Dict[str, int] = {}  # guarded-by: _lock
        # job -> fill epoch; bumped by flush_job so a fill computed
        # against a pre-flush fleet is dropped instead of resurrecting
        # stale answers
        self._epochs: Dict[str, int] = {}  # guarded-by: _lock
        # (job, version, digest) -> _Flight (single-flight registry)
        self._flights: Dict[Tuple[str, int, str], _Flight] = {}  # guarded-by: _lock
        from rafiki_tpu.utils.metrics import REGISTRY

        self._m_hits = REGISTRY.counter(
            "rafiki_cache_hits_total",
            "prediction cache hits (per tenant job)", ("job",))
        self._m_misses = REGISTRY.counter(
            "rafiki_cache_misses_total",
            "prediction cache misses (per tenant job)", ("job",))
        self._m_coalesced = REGISTRY.counter(
            "rafiki_cache_coalesced_total",
            "identical in-flight queries answered by a shared "
            "single-flight forward instead of their own", ("job",))
        self._m_evictions = REGISTRY.counter(
            "rafiki_cache_evictions_total",
            "prediction cache entries evicted "
            "(reason: ttl|bytes|flush)", ("reason",))
        self._m_bytes = REGISTRY.gauge(
            "rafiki_cache_bytes",
            "estimated bytes held by the prediction result cache")
        self._m_shareable = REGISTRY.counter(
            "rafiki_cache_shareable_total",
            "sampled duplicate-query observations while the prediction "
            "cache is OFF (the doctor's enable-the-cache signal)",
            ("job",))
        self._m_errors = REGISTRY.counter(
            "rafiki_cache_errors_total",
            "cache operations absorbed into the miss path (chaos or "
            "internal faults; serving degraded, never failed)")
        # duplicate-digest probe for the cache-off shareable signal:
        # bounded per-job recent-digest windows (see note_shareable)
        self._share_seen: Dict[str, "collections.OrderedDict[str, None]"] \
            = {}  # guarded-by: _lock

    # -- knobs (lazy) --------------------------------------------------------

    def _cap_bytes(self) -> int:
        if self._max_bytes is not None:
            return int(self._max_bytes)
        from rafiki_tpu import config

        return int(config.PREDICT_CACHE_MAX_BYTES)

    def _ttl(self) -> float:
        if self._ttl_s is not None:
            return float(self._ttl_s)
        from rafiki_tpu import config

        return float(config.PREDICT_CACHE_TTL_S)

    def _chaos(self, job: str, op: str) -> None:
        rule = chaos.hit(chaos.SITE_CACHE, f"{job}/{op}")
        if rule is None:
            return
        if rule.action == chaos.ACTION_DELAY:
            chaos.sleep_for(rule)
            return
        raise CacheChaosError(
            f"chaos-injected cache {op} failure for job {job}")

    # -- epochs --------------------------------------------------------------

    def epoch(self, job: str) -> int:
        """The job's current fill epoch — read BEFORE serving a miss,
        passed back to :meth:`fill`; a flush in between invalidates the
        fill."""
        with self._lock:
            return self._epochs.get(job, 0)

    # -- lookup / fill -------------------------------------------------------

    def lookup(self, job: str, version: int, digest: str
               ) -> Tuple[bool, Any]:
        """``(hit, value)``. A TTL-expired entry is evicted here (reason
        ``ttl``) and reads as a miss. Counts the per-job hit/miss
        metrics; chaos may raise — callers degrade to the miss path."""
        self._chaos(job, "lookup")
        key = (job, int(version), digest)
        now = time.monotonic()
        expired = False
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None and entry[2] <= now:
                self._drop_locked(key, entry)
                expired = True
                entry = None
            if entry is None:
                hit = False
                value = None
            else:
                self._entries.move_to_end(key)
                hit, value = True, entry[0]
            total = self._bytes
        if expired:
            self._m_evictions.labels("ttl").inc()
            self._m_bytes.set(total)
        (self._m_hits if hit else self._m_misses).labels(job).inc()
        return hit, value

    def peek_misses(self, job: str, version: int,
                    digests: Iterable[Optional[str]]) -> int:
        """How many of ``digests`` would MISS right now — the doors'
        misses-only admission cost (tenant fairness charges what will
        actually reach a worker). Read-only: no metrics, no LRU touch,
        no chaos — this runs before admission on every request and must
        stay nanoseconds."""
        now = time.monotonic()
        misses = 0
        seen = set()
        with self._lock:
            for d in digests:
                if d is None:
                    misses += 1
                    continue
                if d in seen:
                    # within-request duplicates coalesce into ONE forward
                    # on the serve path — charge what actually reaches a
                    # worker
                    continue
                seen.add(d)
                entry = self._entries.get((job, int(version), d))
                if entry is None or entry[2] <= now:
                    misses += 1
        return misses

    def fill(self, job: str, version: int, digest: str, value: Any,
             epoch: int) -> bool:
        """Insert one served prediction (the batching-aware fill: each
        resolved query of a batch lands as its own entry). Dropped when
        the job's epoch moved past ``epoch`` (a flush invalidated the
        fleet this forward ran against) or when the TTL/byte budget is
        zero. Returns True when the entry landed."""
        self._chaos(job, "fill")
        ttl = self._ttl()
        cap = self._cap_bytes()
        if ttl <= 0 or cap <= 0:
            return False
        nbytes = _ENTRY_OVERHEAD + _estimate_bytes(value)
        if nbytes > cap:
            return False  # one giant prediction must not wipe the cache
        key = (job, int(version), digest)
        expires = time.monotonic() + ttl
        with self._lock:
            if self._epochs.get(job, 0) != epoch:
                return False
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old[1]
            else:
                self._job_entries[job] = self._job_entries.get(job, 0) + 1
            self._entries[key] = [value, nbytes, expires]
            self._bytes += nbytes
            evicted = 0
            while self._bytes > cap and self._entries:
                k, e = self._entries.popitem(last=False)
                self._bytes -= e[1]
                self._dec_job_entries_locked(k[0])
                evicted += 1
            total = self._bytes
        if evicted:
            self._m_evictions.labels("bytes").inc(evicted)
        self._m_bytes.set(total)
        return True

    def _drop_locked(self, key, entry) -> None:  # guarded-by: _lock
        self._entries.pop(key, None)
        self._bytes -= entry[1]
        self._dec_job_entries_locked(key[0])

    def _dec_job_entries_locked(self, job: str) -> None:  # guarded-by: _lock
        n = self._job_entries.get(job, 0) - 1
        if n > 0:
            self._job_entries[job] = n
        else:
            self._job_entries.pop(job, None)

    # -- single-flight -------------------------------------------------------

    def join_flight(self, job: str, version: int, digest: str
                    ) -> Tuple[bool, _Flight]:
        """``(is_leader, flight)``. The leader keeps the flight object
        and MUST later call :meth:`resolve_flight` or
        :meth:`fail_flight` with it — followers block on
        ``flight.future`` (outside any cache lock) and are counted
        ``coalesced``. Chaos may raise; callers degrade to leaderless
        (everyone forwards independently)."""
        self._chaos(job, "join")
        key = (job, int(version), digest)
        with self._lock:
            flight = self._flights.get(key)
            if flight is None:
                flight = _Flight()
                self._flights[key] = flight
                return True, flight
        self._m_coalesced.labels(job).inc()
        return False, flight

    def resolve_flight(self, job: str, version: int, digest: str,
                       flight: _Flight, value: Any) -> None:
        """Leader-side completion: hand ``value`` to every follower of
        THIS flight and retire it from the registry — but only when the
        registry still holds this very object (a flush may have detached
        it, and a NEW leader's flight under the same key must not be
        evicted by the old leader's completion)."""
        with self._lock:
            key = (job, int(version), digest)
            if self._flights.get(key) is flight:
                del self._flights[key]
        flight.future.set_result(value)

    def fail_flight(self, job: str, version: int, digest: str,
                    flight: _Flight, error: BaseException) -> None:
        """Leader-side failure: this flight's followers re-raise a
        per-waiter copy of the leader's error (QueryFuture semantics)
        instead of hanging to their deadline."""
        with self._lock:
            key = (job, int(version), digest)
            if self._flights.get(key) is flight:
                del self._flights[key]
        flight.future.set_error(error)

    # -- invalidation --------------------------------------------------------

    def flush_job(self, job: str, keep_version: Optional[int] = None,
                  reason: str = "flush") -> int:
        """Drop the job's entries — all of them, or (``keep_version``)
        every version EXCEPT the one that remains valid (rollout DONE
        keeps the just-promoted version's warm entries). Always bumps the
        job's fill epoch, so in-flight fills that observed the pre-flush
        fleet are dropped on arrival, and DETACHES the job's in-flight
        single-flight entries — their leaders still answer the followers
        already waiting (the leader holds the flight object), but a
        request arriving after the flush starts a fresh forward instead
        of coalescing onto one from the invalidated fleet. Returns the
        evicted entry count."""
        keep = None if keep_version is None else int(keep_version)
        with self._lock:
            self._epochs[job] = self._epochs.get(job, 0) + 1
            victims = [k for k in self._entries
                       if k[0] == job and (keep is None or k[1] != keep)]
            for k in victims:
                self._bytes -= self._entries.pop(k)[1]
                self._dec_job_entries_locked(job)
            for k in [k for k in self._flights if k[0] == job]:
                del self._flights[k]
            # the duplicate-probe window dies with the job too (a
            # long-lived admin cycling jobs must not accumulate them)
            self._share_seen.pop(job, None)
            total = self._bytes
        if victims:
            self._m_evictions.labels("flush").inc(len(victims))
        self._m_bytes.set(total)
        logger.info("prediction cache: flushed %d entr%s of job %s (%s%s)",
                    len(victims), "y" if len(victims) == 1 else "ies",
                    job[:8], reason,
                    f", kept v{keep}" if keep is not None else "")
        return len(victims)

    # -- cache-off shareable signal ------------------------------------------

    def note_shareable(self, job: str, digest: Optional[str]) -> None:
        """Sampled duplicate-query probe while the cache is OFF: the
        predictor hands every Nth request's first-query digest here; a
        digest already inside the job's bounded recent window counts one
        ``rafiki_cache_shareable_total`` — the doctor's signal that
        identical-query traffic is being forwarded redundantly."""
        if digest is None:
            return
        with self._lock:
            seen = self._share_seen.setdefault(
                job, collections.OrderedDict())
            dup = digest in seen
            seen[digest] = None
            seen.move_to_end(digest)
            while len(seen) > 128:
                seen.popitem(last=False)
        if dup:
            self._m_shareable.labels(job).inc()

    def note_degraded(self) -> None:
        """Count one absorbed cache fault (the predictor's degrade
        guard calls this — the drill's observable)."""
        self._m_errors.inc()

    # -- observability -------------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        """The /fleet/health "prediction_cache" section: global bounds +
        occupancy, plus per-job entry counts and live hit rates read off
        the registry counters."""
        # O(jobs), never a walk of the entry map under _lock — the
        # serving hot path shares that lock and a /fleet/health poll
        # must not stall it behind an O(entries) scan
        with self._lock:
            entries = len(self._entries)
            total = self._bytes
            flights = len(self._flights)
            per_job = dict(self._job_entries)
        jobs: Dict[str, Any] = {}
        for job, n in per_job.items():
            hits, misses = self.job_totals(job)
            served = hits + misses
            jobs[job] = {
                "entries": n,
                "hits": hits,
                "misses": misses,
                "hit_rate": round(hits / served, 3) if served else None,
            }
        from rafiki_tpu import config

        return {
            "enabled": bool(config.PREDICT_CACHE),
            "entries": entries,
            "bytes": total,
            "max_bytes": self._cap_bytes(),
            "ttl_s": self._ttl(),
            "inflight_flights": flights,
            "jobs": jobs,
        }

    def job_totals(self, job: str) -> Tuple[int, int]:
        """(hits, misses) counter totals for one job — the autoscaler's
        hit-rate signal and the stats() view read these."""
        return (int(self._m_hits.labels(job).value()),
                int(self._m_misses.labels(job).value()))

    def clear(self) -> None:
        """Test hook: drop every entry, epoch, and flight."""
        with self._lock:
            self._entries.clear()
            self._bytes = 0
            self._job_entries.clear()
            self._epochs.clear()
            flights = list(self._flights.values())
            self._flights.clear()
            self._share_seen.clear()
        for f in flights:
            f.future.set_error(RuntimeError("prediction cache cleared"))
        self._m_bytes.set(0)


#: the process-wide instance (both serving doors of every job share it;
#: job-scoped keys and flushes keep tenants apart)
_CACHE = ResultCache()


def get_cache() -> ResultCache:
    return _CACHE
