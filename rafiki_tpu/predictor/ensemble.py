"""Prediction ensembling across the best trials of a train job
(reference rafiki/predictor/ensemble.py:6-33).

IMAGE_CLASSIFICATION / TEXT_CLASSIFICATION: predictions are per-class
probability vectors — ensemble by elementwise mean. Other tasks: take the
first worker's predictions. All outputs are JSON-native (numpy stripped).
"""

from __future__ import annotations

from typing import Any, List, Optional

import numpy as np

_PROB_TASKS = {"IMAGE_CLASSIFICATION", "TEXT_CLASSIFICATION"}


def _to_json_native(value: Any) -> Any:
    if isinstance(value, np.ndarray):
        return value.tolist()
    if isinstance(value, (np.integer,)):
        return int(value)
    if isinstance(value, (np.floating,)):
        return float(value)
    if isinstance(value, dict):
        return {k: _to_json_native(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_to_json_native(v) for v in value]
    return value


def ensemble_predictions(
    worker_predictions: List[List[Any]], task: Optional[str]
) -> List[Any]:
    """Combine per-worker prediction lists (one list per model worker, one
    entry per query) into a single prediction list."""
    worker_predictions = [p for p in worker_predictions if p is not None]
    if not worker_predictions:
        return []
    if task in _PROB_TASKS:
        try:
            stacked = np.asarray(worker_predictions, dtype=np.float64)
            return _to_json_native(stacked.mean(axis=0))
        except (ValueError, TypeError):
            pass  # ragged/non-numeric predictions: fall through
    return _to_json_native(worker_predictions[0])
