"""Per-inference-job predictor HTTP listener.

The reference published each inference job's predictor on its own host
port (/root/reference/rafiki/admin/services_manager.py:379-384,
predictor/app.py:23-31), so serving traffic never shared a socket with
the control plane. Parity here: when ``RAFIKI_PREDICTOR_PORTS=1`` (or
``predictor_ports=True`` on the Admin), ServicesManager binds one of
these per deployed inference job; POST /predict traffic then bypasses
the admin server entirely. The admin /predict/<app> route keeps working
either way — this is an extra front door, not a move.

Auth parity with the admin route: the same stateless JWTs
(utils/auth.py) are accepted, so a client token works on both doors;
set ``auth=False`` for a trusted-network deployment (the reference's
predictor app had no auth at all).
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional

from rafiki_tpu.utils.auth import UnauthorizedError, decode_token
from rafiki_tpu.utils.reqfields import LowLatencyHandler

logger = logging.getLogger(__name__)


class PredictorServer:
    """One jsonified POST /predict + GET /healthz listener over one
    Predictor (predictor/predictor.py)."""

    def __init__(self, predictor, app: str, host: str = "127.0.0.1",
                 port: int = 0, auth: bool = True):
        self.predictor = predictor
        self.app = app
        self.host = host
        self.port = port
        self.auth = auth
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "PredictorServer":
        server = self

        class Handler(LowLatencyHandler):
            protocol_version = "HTTP/1.1"
            timeout = 300

            def do_GET(self):
                if self.path.split("?", 1)[0].rstrip("/") == "/healthz":
                    server._respond(self, 200, {
                        "app": server.app, "status": "ok"})
                else:
                    server._respond(self, 404, {"error": "no such route"})

            def do_POST(self):
                server._predict(self)

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name=f"predictor-{self.app}")
        self._thread.start()
        logger.info("predictor for %s listening on %s:%d",
                    self.app, self.host, self.port)
        return self

    def stop(self) -> None:
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()

    # -- handling ----------------------------------------------------------

    def _predict(self, handler: BaseHTTPRequestHandler) -> None:
        if handler.path.split("?", 1)[0].rstrip("/") != "/predict":
            return self._respond(handler, 404, {"error": "no such route"})
        try:
            if self.auth:
                token = (handler.headers.get("Authorization")
                         or "").removeprefix("Bearer ")
                decode_token(token)  # any authenticated user may predict
            from rafiki_tpu import config as _config
            from rafiki_tpu.utils.reqfields import read_bounded_body

            raw, berr = read_bounded_body(
                handler, _config.PREDICT_MAX_BODY_MB)
            if berr:
                return self._respond(
                    handler, berr[0],
                    {"error": f"{berr[1]} (PREDICT_MAX_BODY_MB)"})
            # media types are case-insensitive (RFC 9110); params follow ';'
            ctype = ((handler.headers.get("Content-Type") or "")
                     .split(";")[0].strip().lower())
            body: Dict[str, Any] = {}
            if ctype == "application/x-npy":
                # binary ndarray queries: first axis is the batch. JSON
                # costs ~20 bytes AND a float parse per element — for a
                # 3072-float image query that is the serving door's CPU,
                # not the model. Responses stay JSON (predictions are
                # small). allow_pickle=False: this door is pre-auth'd but
                # still untrusted input.
                import io

                import numpy as _np

                try:
                    arr = _np.load(io.BytesIO(raw), allow_pickle=False)
                except Exception as e:  # malformed/pickled: client error
                    return self._respond(handler, 400, {
                        "error": f"bad npy body: {e}"})
                if arr.ndim < 1 or arr.shape[0] == 0:
                    return self._respond(handler, 400, {
                        "error": "npy body must have a leading batch axis"})
                queries = list(arr)
            else:
                body = json.loads(raw or b"{}")
                if not isinstance(body, dict):
                    return self._respond(handler, 400, {
                        "error": "body must be a JSON object like "
                                 '{"queries": [...]}'})
                queries = body.get("queries")
            if not isinstance(queries, list) or not queries:
                return self._respond(handler, 400, {
                    "error": "body must carry a non-empty 'queries' list"})
            from rafiki_tpu.utils.reqfields import parse_timeout_s

            # binary bodies have no JSON fields — the timeout rides a
            # header there (validated by the same rule either way)
            timeout_value = (handler.headers.get("X-Rafiki-Timeout-S")
                             if ctype == "application/x-npy"
                             else body.get("timeout_s"))
            timeout_s, terr = parse_timeout_s(
                timeout_value, default=_config.PREDICT_TIMEOUT_S,
                label=("X-Rafiki-Timeout-S header"
                       if ctype == "application/x-npy" else "timeout_s"))
            if terr:
                return self._respond(handler, 400, {"error": terr})
            preds = self.predictor.predict_batch(
                queries, timeout_s=timeout_s)
            self._respond(handler, 200, {"data": {"predictions": preds}})
        except UnauthorizedError as e:
            self._respond(handler, 401, {"error": str(e)})
        except json.JSONDecodeError as e:
            self._respond(handler, 400, {"error": f"bad JSON body: {e}"})
        except TimeoutError as e:
            self._respond(handler, 504, {"error": str(e)})
        except RuntimeError as e:
            # no workers / job being torn down
            self._respond(handler, 503, {"error": str(e)})
        except Exception:
            logger.exception("predict failed on dedicated port for %s",
                             self.app)
            self._respond(handler, 500, {"error": "internal server error"})

    @staticmethod
    def _respond(handler, code: int, payload: Dict[str, Any]) -> None:
        data = json.dumps(payload).encode()
        handler.send_response(code)
        handler.send_header("Content-Type", "application/json")
        handler.send_header("Content-Length", str(len(data)))
        handler.end_headers()
        handler.wfile.write(data)
