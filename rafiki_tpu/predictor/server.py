"""Per-inference-job predictor HTTP listener.

The reference published each inference job's predictor on its own host
port (/root/reference/rafiki/admin/services_manager.py:379-384,
predictor/app.py:23-31), so serving traffic never shared a socket with
the control plane. Parity here: when ``RAFIKI_PREDICTOR_PORTS=1`` (or
``predictor_ports=True`` on the Admin), ServicesManager binds one of
these per deployed inference job; POST /predict traffic then bypasses
the admin server entirely. The admin /predict/<app> route keeps working
either way — this is an extra front door, not a move.

Auth parity with the admin route: the same stateless JWTs
(utils/auth.py) are accepted, so a client token works on both doors;
set ``auth=False`` for a trusted-network deployment (the reference's
predictor app had no auth at all).
"""

from __future__ import annotations

import json
import logging
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional

from rafiki_tpu.cache.queue import FrameTooLargeError, QueueFullError
from rafiki_tpu.predictor.admission import (
    AdmissionController,
    DeadlineUnmeetableError,
    ServerOverloadedError,
    retry_after_headers,
)
from rafiki_tpu.utils.auth import UnauthorizedError, decode_token
from rafiki_tpu.utils.reqfields import LowLatencyHandler

logger = logging.getLogger(__name__)


def _generate_cost(prompt_len: int, max_tokens: int) -> int:
    """Admission cost of one /generate request, in the units of the
    resource that actually gates the generation worker: KV-pool BLOCKS
    under the paged allocator (ceil((prompt + decode budget) / block
    tokens) — a long prompt holds pages even while producing few tokens,
    so prompt length must charge), or the decode budget itself under the
    legacy contiguous ring (every slot costs max_context there, so only
    residency TIME differentiates requests)."""
    from rafiki_tpu import config as _config

    if bool(_config.GEN_KV_PAGED):
        bt = max(int(_config.GEN_KV_BLOCK_TOKENS), 1)
        return max(-(-(prompt_len + max_tokens) // bt), 1)
    return max(max_tokens, 1)


class PredictorServer:
    """One jsonified POST /predict + GET /healthz listener over one
    Predictor (predictor/predictor.py).

    Overload control (docs/failure-model.md "Overload faults"): every
    predict passes the door's AdmissionController first — a bounded
    in-flight gate plus a deadline-aware estimated-wait check — and worker
    queues underneath are bounded, so excess traffic is shed instantly
    with ``429`` + ``Retry-After`` (backlog: retry later) or ``503`` (no
    capacity) instead of accumulating ThreadingHTTPServer handler threads
    until the host dies."""

    def __init__(self, predictor, app: str, host: str = "127.0.0.1",
                 port: int = 0, auth: bool = True):
        self.predictor = predictor
        self.app = app
        self.host = host
        self.port = port
        self.auth = auth
        # door label feeds the registry (admitted/shed counters + the
        # rafiki_request_seconds histogram the bench reads percentiles
        # from); the JSON stats() in /healthz stay per-door as before
        self.admission = AdmissionController(door=f"predictor:{app}")
        #: epoch seconds of the listener bind — a restarted admin rebinds
        #: an ADOPTED job's door on a fresh port (control-plane recovery),
        #: and a monitor that sees started_at jump knows the door moved
        #: (rather than silently aiming at the dead process's port)
        self.started_at: Optional[float] = None
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._stop_lock = threading.Lock()
        self._stopped = False
        self._draining = False

    def start(self) -> "PredictorServer":
        server = self

        class Handler(LowLatencyHandler):
            protocol_version = "HTTP/1.1"
            timeout = 300

            def do_GET(self):
                path = self.path.split("?", 1)[0].rstrip("/")
                if path == "/healthz":
                    server._healthz(self)
                elif path == "/metrics":
                    server._metrics(self)
                else:
                    server._respond(self, 404, {"error": "no such route"})

            def do_POST(self):
                path = self.path.split("?", 1)[0].rstrip("/")
                if path == "/generate":
                    server._generate(self)
                else:
                    server._predict(self)

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        self.started_at = time.time()
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name=f"predictor-{self.app}")
        self._thread.start()
        logger.info("predictor for %s listening on %s:%d",
                    self.app, self.host, self.port)
        return self

    def stop(self, drain_timeout_s: Optional[float] = None) -> None:
        """Graceful drain: stop accepting, let in-flight handlers finish
        (bounded by ``drain_timeout_s``, default RAFIKI_PREDICT_DRAIN_S),
        then close the socket and join the serve thread. Idempotent — the
        teardown paths (operator stop, all-replicas-dead refresh, deploy
        rollback) may race onto a double stop."""
        with self._stop_lock:
            if self._stopped:
                return
            self._stopped = True
            self._draining = True
        httpd, thread = self._httpd, self._thread
        if httpd is None:
            return
        from rafiki_tpu import config

        if drain_timeout_s is None:
            drain_timeout_s = float(config.PREDICT_DRAIN_S)
        httpd.shutdown()  # stop the accept loop; handler threads live on
        deadline = time.monotonic() + max(drain_timeout_s, 0.0)
        while (self.admission.inflight > 0
               and time.monotonic() < deadline):
            time.sleep(0.01)
        leftover = self.admission.inflight
        if leftover:
            logger.warning(
                "predictor %s closed with %d handler(s) still in flight "
                "after the %.1fs drain window", self.app, leftover,
                drain_timeout_s)
        httpd.server_close()
        if thread is not None:
            thread.join(timeout=5.0)
        self._draining = False

    # -- handling ----------------------------------------------------------

    def _healthz(self, handler: BaseHTTPRequestHandler) -> None:
        """Liveness + load: ``status`` is ``degraded`` when the serving
        plane is live-but-empty (zero worker queues registered — the door
        answers but no replica can), which the fleet-health monitor must
        be able to tell apart from healthy. Also carries the overload
        picture: queue depths, admission counters, hedge suppression."""
        depths: Dict[str, int] = {}
        depth_fn = getattr(self.predictor, "queue_depths", None)
        if callable(depth_fn):
            try:
                depths = depth_fn()
            except Exception:
                logger.exception("healthz queue-depth probe failed")
        overload_fn = getattr(self.predictor, "overload_stats", None)
        status = "ok"
        if self._draining:
            status = "draining"
        elif callable(depth_fn) and not depths:
            status = "degraded"
        payload: Dict[str, Any] = {
            "app": self.app,
            "status": status,
            "started_at": self.started_at,
            "workers": len(depths),
            "queue_depths": depths,
            "admission": self.admission.stats(),
        }
        # per-replica warm state (worker/warmup.py): cold/warm verdict +
        # last-boot compile seconds for every in-process replica in this
        # door's fan-out; replicas in other processes report the same
        # fields through their stats rows (GET /fleet/health workers)
        try:
            from rafiki_tpu.worker.warmup import warmup_stats

            reports = warmup_stats()
            replicas = {
                sid: {"warm": bool(r.get("warm")),
                      "compile_s": r.get("compile_s", 0.0),
                      "cache_hits": r.get("cache_hits", 0)}
                for sid, r in reports.items() if sid in depths}
            if replicas:
                payload["replicas"] = replicas
        # lint: absorb(/healthz must answer even when the warm-state probe crashes)
        except Exception:
            logger.exception("healthz warm-state probe failed")
        if callable(overload_fn):
            payload["overload"] = overload_fn()
        qstats_fn = getattr(self.predictor, "queue_stats", None)
        if callable(qstats_fn):
            try:
                qstats = qstats_fn()
            # lint: absorb(/healthz must answer even when a stats hook crashes)
            except Exception:
                qstats = {}
            if qstats:
                # submit-side ring picture (shm plane): this is where
                # ring_used_bytes_hw — the RAFIKI_SHM_RING_BYTES sizing
                # signal — is actually measured
                payload["queues"] = qstats
        self._respond(handler, 200, payload)

    def _predict(self, handler: BaseHTTPRequestHandler) -> None:
        from rafiki_tpu import config as _config
        from rafiki_tpu.utils.reqfields import read_bounded_body

        # body first: a refusal (404/401) that leaves it unread would
        # desync HTTP/1.1 keep-alive framing for the pooled connection
        raw, berr = read_bounded_body(
            handler, _config.PREDICT_MAX_BODY_MB)
        if berr:
            return self._respond(
                handler, berr[0],
                {"error": f"{berr[1]} (PREDICT_MAX_BODY_MB)"})
        if handler.path.split("?", 1)[0].rstrip("/") != "/predict":
            return self._respond(handler, 404, {"error": "no such route"})
        try:
            if self.auth:
                token = (handler.headers.get("Authorization")
                         or "").removeprefix("Bearer ")
                decode_token(token)  # any authenticated user may predict
            # media types are case-insensitive (RFC 9110); params follow ';'
            ctype = ((handler.headers.get("Content-Type") or "")
                     .split(";")[0].strip().lower())
            body: Dict[str, Any] = {}
            if ctype == "application/x-npy":
                # binary ndarray queries: first axis is the batch. JSON
                # costs ~20 bytes AND a float parse per element — for a
                # 3072-float image query that is the serving door's CPU,
                # not the model. Responses are negotiated separately via
                # Accept: application/x-npy (see below).
                # allow_pickle=False: this door is pre-auth'd but still
                # untrusted input.
                import io

                import numpy as _np

                try:
                    arr = _np.load(io.BytesIO(raw), allow_pickle=False)
                # lint: absorb(hostile npy bytes answer 400, never a 500)
                except Exception:
                    return self._respond(handler, 400, {
                        "error": "bad npy body (expected a valid, "
                                 "non-pickled .npy array)"})
                if arr.ndim < 1 or arr.shape[0] == 0:
                    return self._respond(handler, 400, {
                        "error": "npy body must have a leading batch axis"})
                queries = list(arr)
            else:
                body = json.loads(raw or b"{}")
                if not isinstance(body, dict):
                    return self._respond(handler, 400, {
                        "error": "body must be a JSON object like "
                                 '{"queries": [...]}'})
                queries = body.get("queries")
            if not isinstance(queries, list) or not queries:
                return self._respond(handler, 400, {
                    "error": "body must carry a non-empty 'queries' list"})
            cap = int(_config.PREDICT_QUEUE_DEPTH)
            if cap > 0 and len(queries) > cap:
                # bigger than any queue can EVER hold: a permanent
                # condition — 400, never the retryable 429 (a well-behaved
                # client would retry a 429 forever)
                return self._respond(handler, 400, {
                    "error": f"request carries {len(queries)} queries but "
                             f"the per-worker queue cap is {cap} "
                             "(RAFIKI_PREDICT_QUEUE_DEPTH) — split the "
                             "request"})
            from rafiki_tpu.utils.reqfields import parse_timeout_s

            # binary bodies have no JSON fields — the timeout rides a
            # header there (validated by the same rule either way)
            timeout_value = (handler.headers.get("X-Rafiki-Timeout-S")
                             if ctype == "application/x-npy"
                             else body.get("timeout_s"))
            timeout_s, terr = parse_timeout_s(
                timeout_value, default=_config.PREDICT_TIMEOUT_S,
                label=("X-Rafiki-Timeout-S header"
                       if ctype == "application/x-npy" else "timeout_s"))
            if terr:
                return self._respond(handler, 400, {"error": terr})
            # request tracing (utils/trace.py): honor an incoming
            # X-Rafiki-Trace header's sampling bit or draw against
            # RAFIKI_TRACE_SAMPLE; the unsampled path costs one header
            # read. The context rides queue entries / wire frames / the
            # fleet relay so one sampled request yields one span tree
            # door -> worker -> door.
            from rafiki_tpu.utils import trace as rtrace

            rt = rtrace.start_trace(
                handler.headers.get(rtrace.TRACE_HEADER))
            # admission: claim an in-flight slot AND prove the backlog
            # leaves room to answer inside this request's own deadline —
            # shed here costs microseconds; admitting a doomed request
            # costs model time
            backlog_fn = getattr(self.predictor, "backlog_depth", None)
            backlog = backlog_fn() if callable(backlog_fn) else None
            t_adm = time.monotonic()
            # tenant/cost feed the weighted-fair gate; on this per-job
            # door there is one tenant, so the gate is a no-op — the
            # accounting still shows in /healthz fair_shares. With the
            # prediction cache on, cost is the MISSES-ONLY estimate
            # (predictor/result_cache.py): cache-served queries shed no
            # load onto the worker fleet, so fairness must not charge
            # for them.
            cost_fn = getattr(self.predictor, "admission_cost", None)
            cost = (cost_fn(queries) if callable(cost_fn)
                    else len(queries))
            self.admission.admit(timeout_s, backlog_depth=backlog,
                                 tenant=self.app, cost=cost)
            t0 = time.monotonic()
            if rt is not None:
                rt.add_span("admission_wait", t_adm, t0)
            try:
                # trace kwarg only when sampled: unsampled traffic keeps
                # the pre-trace call shape (duck-typed predictor fakes)
                preds = self.predictor.predict_batch(
                    queries, timeout_s=timeout_s,
                    **({"trace": rt} if rt is not None else {}))
            finally:
                self.admission.release(tenant=self.app)
            e2e_s = time.monotonic() - t0
            self.admission.observe(e2e_s, len(queries))
            # Accept negotiation: a client that asked for
            # application/x-npy gets the predictions back as ONE binary
            # .npy body — the response-leg mirror of the binary request
            # door (JSON float text was the remaining serialization tax
            # on an end-to-end binary predict). Ragged/non-numeric
            # predictions fall back to JSON; the client sniffs the
            # response Content-Type either way.
            trace_headers = ({rtrace.TRACE_HEADER: rt.ctx.to_header()}
                             if rt is not None else None)
            if self._accepts_npy(handler):
                import io

                import numpy as _np

                arr = None
                try:
                    arr = _np.asarray(preds)
                # lint: absorb(un-arrayable predictions take the JSON response path)
                except Exception:
                    pass
                if arr is not None and arr.dtype != object:
                    buf = io.BytesIO()
                    _np.save(buf, arr, allow_pickle=False)
                    t_resp = time.monotonic()
                    self._respond_bytes(
                        handler, 200, buf.getvalue(), "application/x-npy",
                        headers=trace_headers)
                    self._finish_trace(rt, t0, t_resp)
                    return
            t_resp = time.monotonic()
            self._respond(handler, 200, {"data": {"predictions": preds}},
                          headers=trace_headers)
            self._finish_trace(rt, t0, t_resp)
        except UnauthorizedError as e:
            self._respond(handler, 401, {"error": str(e)})
        except json.JSONDecodeError as e:
            self._respond(handler, 400, {"error": f"bad JSON body: {e}"})
        except FrameTooLargeError as e:
            # the request's wire frame can never fit the shm ring: a
            # PERMANENT condition — 413, never the retryable 429
            self._respond(handler, 413, {"error": str(e)})
        except (QueueFullError, DeadlineUnmeetableError) as e:
            # backlog shed: retryable, and Retry-After says when (full
            # worker queues / estimated wait past the client's deadline)
            self._respond(handler, 429, {"error": str(e)},
                          headers=retry_after_headers(e))
        except ServerOverloadedError as e:
            # no capacity: the door's in-flight slots are gone
            self._respond(handler, 503, {"error": str(e)},
                          headers=retry_after_headers(e))
        except TimeoutError as e:
            self._respond(handler, 504, {"error": str(e)})
        except RuntimeError as e:
            # no workers / job being torn down
            self._respond(handler, 503, {"error": str(e)})
        except Exception:
            logger.exception("predict failed on dedicated port for %s",
                             self.app)
            self._respond(handler, 500, {"error": "internal server error"})

    # -- generative serving: the streaming door -----------------------------

    def _generate(self, handler: BaseHTTPRequestHandler) -> None:
        """POST /generate — the token-streaming door
        (docs/serving-generation.md). The request is one JSON object
        ``{"prompt_ids": [...], "max_tokens": N, "timeout_s": T}`` plus
        optional sampling knobs ``temperature`` / ``top_k`` / ``top_p`` /
        ``seed`` (temperature=0 = greedy; a fixed seed makes a sampled
        stream reproducible — worker/generation.py validates them typed);
        the response is chunked transfer, one delta per chunk: JSON
        lines by default, or length-prefixed v3 wire token-delta frames
        when the client sent ``Accept: application/x-rafiki-wire``
        (binary peers OPT IN — an old client never sees the new message
        kind). Admission charges the request its ESTIMATED DECODE COST,
        not 1 — see :func:`_generate_cost`: KV-pool BLOCKS under the
        paged allocator (prompt + budget, the resource that actually
        gates worker admission), ``max_tokens`` under the legacy ring.
        Either way a 256-token stream occupies decode memory ~256 times
        longer than a one-shot predict, and the fairness/backlog books
        must see that.

        Fault contract: every pre-stream refusal is an ordinary status
        code (400/401/429/503/504); once streaming begins the status is
        already 200, so mid-stream faults — an injured worker, a stalled
        decode step past RAFIKI_GEN_STREAM_TIMEOUT_S — end the response
        with a TYPED terminal error frame, never a silent hang."""
        from rafiki_tpu.utils.metrics import REGISTRY
        from rafiki_tpu.worker.generation import GenerationRequestError

        # release() must pair ONLY with a successful admit(): a request
        # refused before (or BY) admission never incremented the
        # in-flight book, and decrementing for it would leak capacity
        # another stream is holding — the cap would over-admit under a
        # shed burst
        held = [False]

        def release():
            if held[0]:
                held[0] = False
                self.admission.release(tenant=self.app)

        from rafiki_tpu import config as _config
        from rafiki_tpu.utils.reqfields import (
            parse_timeout_s,
            read_bounded_body,
        )

        # body first: a 401/413 with the body unread would desync the
        # keep-alive connection (see _predict)
        raw, berr = read_bounded_body(
            handler, _config.PREDICT_MAX_BODY_MB)
        if berr:
            return self._respond(
                handler, berr[0],
                {"error": f"{berr[1]} (PREDICT_MAX_BODY_MB)"})
        try:
            if self.auth:
                token = (handler.headers.get("Authorization")
                         or "").removeprefix("Bearer ")
                decode_token(token)
            body = json.loads(raw or b"{}")
            if not isinstance(body, dict):
                return self._respond(handler, 400, {
                    "error": "body must be a JSON object like "
                             '{"prompt_ids": [...]}'})
            timeout_s, terr = parse_timeout_s(
                body.get("timeout_s"), default=_config.PREDICT_TIMEOUT_S,
                label="timeout_s")
            if terr:
                return self._respond(handler, 400, {"error": terr})
            try:
                max_tokens = int(body.get(
                    "max_tokens", _config.GEN_MAX_TOKENS))
            except (TypeError, ValueError):
                return self._respond(handler, 400, {
                    "error": "max_tokens must be an integer"})
            query = {"prompt_ids": body.get("prompt_ids"),
                     "max_tokens": max_tokens}
            # sampling knobs ride the query to the worker, whose
            # _parse_query owns full validation (typed
            # GenerationRequestError -> 400 below); non-numeric junk is
            # refused HERE so it never costs an admission slot
            for key, cast in (("temperature", float), ("top_k", int),
                              ("top_p", float), ("seed", int)):
                if body.get(key) is not None:
                    try:
                        query[key] = cast(body[key])
                    except (TypeError, ValueError):
                        return self._respond(handler, 400, {
                            "error": f"{key} must be a number"})
            backlog_fn = getattr(self.predictor, "backlog_depth", None)
            backlog = backlog_fn() if callable(backlog_fn) else None
            # cost = the estimated decode footprint, not 1 (see docstring)
            prompt_ids = body.get("prompt_ids")
            prompt_len = (len(prompt_ids)
                          if isinstance(prompt_ids, (list, tuple)) else 0)
            self.admission.admit(timeout_s, backlog_depth=backlog,
                                 tenant=self.app,
                                 cost=_generate_cost(prompt_len,
                                                     max_tokens))
            held[0] = True
            t0 = time.monotonic()
            stream = self.predictor.generate(query, timeout_s=timeout_s)
            binary = self._accepts_wire(handler)
            REGISTRY.histogram(
                "rafiki_gen_door_ttft_seconds",
                "admission-to-first-token latency at the streaming door "
                "(includes queue wait and prefill)").observe(
                    time.monotonic() - t0)
            n_tokens = self._stream_deltas(handler, stream, binary)
            self.admission.observe(time.monotonic() - t0,
                                   max(n_tokens, 1))
        except UnauthorizedError as e:
            self._respond(handler, 401, {"error": str(e)})
        except json.JSONDecodeError as e:
            self._respond(handler, 400, {"error": f"bad JSON body: {e}"})
        except GenerationRequestError as e:
            self._respond(handler, 400, {"error": str(e)})
        except (QueueFullError, DeadlineUnmeetableError) as e:
            if isinstance(e, QueueFullError):
                # whole-fleet-full: every replica's bounded queue
                # refused the new stream AFTER door admission — book the
                # shed so the admission metrics see it (the classifier
                # door's semantics, mirrored)
                self.admission.note_backend_shed()
            self._respond(handler, 429, {"error": str(e)},
                          headers=retry_after_headers(e))
        except ServerOverloadedError as e:
            self._respond(handler, 503, {"error": str(e)},
                          headers=retry_after_headers(e))
        except TimeoutError as e:
            # no slot admitted the request inside its own deadline
            self._respond(handler, 504, {"error": str(e)})
        except RuntimeError as e:
            self._respond(handler, 503, {"error": str(e)})
        except Exception:
            logger.exception("generate failed on dedicated port for %s",
                             self.app)
            self._respond(handler, 500, {"error": "internal server error"})
        finally:
            release()

    def _stream_deltas(self, handler, stream, binary: bool) -> int:
        """Pump one TokenStream into a chunked HTTP response; returns the
        token count served. Runs AFTER the 200 status line, so every
        failure mode in here must end the stream with a terminal frame
        (and cancel the worker-side slot), never an exception that slams
        the socket shut mid-chunk without a typed goodbye."""
        from rafiki_tpu import config as _config
        from rafiki_tpu.cache import wire
        from rafiki_tpu.cache.queue import GenerationError

        handler.send_response(200)
        handler.send_header(
            "Content-Type",
            wire.CONTENT_TYPE if binary else "application/x-ndjson")
        handler.send_header("Transfer-Encoding", "chunked")
        handler.send_header("Cache-Control", "no-store")
        # one stream per connection: clients drop the socket after the
        # terminal delta, so offering keep-alive only produces a noisy
        # reset in the server log when they do
        handler.send_header("Connection", "close")
        handler.close_connection = True
        handler.end_headers()

        def chunk(payload: bytes) -> bool:
            try:
                handler.wfile.write(
                    ("%x\r\n" % len(payload)).encode() + payload + b"\r\n")
                handler.wfile.flush()
                return True
            # lint: absorb(client gone mid-stream: status already sent; cancel frees the slot)
            except (BrokenPipeError, ConnectionResetError, OSError):
                stream.cancel()
                return False

        def emit(delta) -> bool:
            if binary:
                frame = wire.encode_token_delta(
                    stream.seq_id, delta.tokens, finished=delta.finished,
                    reason=delta.reason, error=delta.error)
                return chunk(len(frame).to_bytes(4, "little") + frame)
            return chunk(json.dumps(delta.to_json()).encode() + b"\n")

        stall_s = max(float(_config.GEN_STREAM_TIMEOUT_S), 0.1)
        served = 0
        from rafiki_tpu.cache.queue import TokenDelta

        # the pump waits one stall window per delta; the request's OVERALL
        # deadline is enforced worker-side (max_duration_s -> eviction
        # with reason "deadline"), so a live-but-slow stream is never cut
        # by the door while tokens keep arriving
        while True:
            try:
                delta = stream.next_delta(timeout=stall_s)
            except StopIteration:
                break
            # lint: absorb(mid-stream at 200: the typed terminal frame IS the error path)
            except TimeoutError:
                # the stalled-decode drill: the worker went mute on this
                # sequence — typed terminal frame, then tell the slot
                # scheduler to evict it
                emit(TokenDelta([], finished=True, reason="error",
                                error=f"decode stalled (no token within "
                                      f"{stall_s:.1f}s)"))
                stream.cancel()
                break
            # lint: absorb(mid-stream at 200: the typed terminal frame IS the error path)
            except GenerationError as e:
                emit(TokenDelta([], finished=True, reason="error",
                                error=str(e)))
                break
            served += len(delta.tokens)
            if not emit(delta):
                return served
            if delta.finished:
                break
        try:
            handler.wfile.write(b"0\r\n\r\n")
            handler.wfile.flush()
        # lint: absorb(client gone at stream end: nothing left to answer)
        except (BrokenPipeError, ConnectionResetError, OSError):
            stream.cancel()
        return served

    @staticmethod
    def _accepts_wire(handler) -> bool:
        """Accept check for the binary token-delta stream (same lite rule
        as :meth:`_accepts_npy`): the client must NAME the wire media
        type — old clients never see the v3 message kind."""
        from rafiki_tpu.cache import wire

        accept = handler.headers.get("Accept") or ""
        return any(
            part.split(";")[0].strip().lower() == wire.CONTENT_TYPE
            for part in accept.split(","))

    def _metrics(self, handler: BaseHTTPRequestHandler) -> None:
        """GET /metrics: Prometheus text exposition of the process
        registry (?format=json for the JSON snapshot + ring series).
        Unauthenticated like /healthz — counters only, standard scraper
        contract."""
        from rafiki_tpu.utils.metrics import serve_http

        serve_http(handler, (handler.path.split("?", 1) + [""])[1])

    def _finish_trace(self, rt, t0: float, t_resp: float) -> None:
        """Close out a sampled request: the respond span, per-phase
        latency histograms, and — past RAFIKI_TRACE_SLOW_MS — a JSON-lines
        exemplar under LOGS_DIR. Never raises (telemetry must not fail a
        request that was already served)."""
        if rt is None:
            return
        try:
            from rafiki_tpu.utils import trace as rtrace
            from rafiki_tpu.utils.metrics import REGISTRY

            now = time.monotonic()
            rt.add_span("respond", t_resp, now)
            phase_h = REGISTRY.histogram(
                "rafiki_phase_seconds",
                "per-phase latency of sampled predict requests",
                ("phase",))
            for name, secs in rt.phase_durations().items():
                phase_h.labels(name).observe(secs)
            e2e_s = now - t0
            if e2e_s >= rtrace.slow_threshold_s():
                rtrace.record_exemplar(rt, e2e_s,
                                       door=f"predictor:{self.app}")
        except Exception:
            logger.debug("trace finish failed", exc_info=True)

    @staticmethod
    def _accepts_npy(handler) -> bool:
        """RFC 9110-lite Accept check: any listed media range equal to
        application/x-npy (params ignored, case-insensitive) opts the
        response into binary. No q-value algebra — this is a two-format
        door, not a content-negotiation engine."""
        accept = handler.headers.get("Accept") or ""
        return any(
            part.split(";")[0].strip().lower() == "application/x-npy"
            for part in accept.split(","))

    @staticmethod
    def _respond(handler, code: int, payload: Dict[str, Any],
                 headers: Optional[Dict[str, str]] = None) -> None:
        from rafiki_tpu.utils.jsonutil import json_default

        # json_default: predictions may carry stray numpy scalars/rows
        # when a binary-era worker answers a JSON client
        data = json.dumps(payload, default=json_default).encode()
        handler.send_response(code)
        handler.send_header("Content-Type", "application/json")
        handler.send_header("Content-Length", str(len(data)))
        for k, v in (headers or {}).items():
            handler.send_header(k, v)
        handler.end_headers()
        handler.wfile.write(data)

    @staticmethod
    def _respond_bytes(handler, code: int, data: bytes,
                       content_type: str,
                       headers: Optional[Dict[str, str]] = None) -> None:
        handler.send_response(code)
        handler.send_header("Content-Type", content_type)
        handler.send_header("Content-Length", str(len(data)))
        for k, v in (headers or {}).items():
            handler.send_header(k, v)
        handler.end_headers()
        handler.wfile.write(data)
