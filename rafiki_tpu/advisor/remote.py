"""Advisor sessions over HTTP — the out-of-process worker's view.

The reference's train workers talked to a separate advisor Flask service
over HTTP (reference rafiki/worker/train.py:207-215, advisor/app.py:17-50).
Here the advisor store lives inside the Admin process and is exposed on the
admin REST API (`/advisors/*`, admin/http.py); `RemoteAdvisorStore` adapts
that API to the in-process `AdvisorStore` interface the TrainWorker consumes
— so parallel worker *processes* of one sub-train-job still coordinate
through the single shared GP (the fix for reference train.py:213's
uncoordinated parallel HPO carries over to multi-process placement).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from rafiki_tpu.client.client import Client
from rafiki_tpu.sdk.knob import serialize_knob_config


class _RemoteAdvisor:
    """Duck-types BaseAdvisor for the one call TrainWorker makes on it."""

    def __init__(self, client: Client, advisor_id: str):
        self._client = client
        self._id = advisor_id

    def feedback(self, knobs: Dict[str, Any], score: float) -> None:
        self._client.feedback_knobs(self._id, knobs, float(score))


class RemoteAdvisorStore:
    """AdvisorStore facade over the admin REST API (duck-typed; the
    TrainWorker never imports the concrete class)."""

    def __init__(self, client: Client):
        self._client = client

    def create_advisor(self, knob_config: Dict[str, Any],
                       advisor_id: Optional[str] = None) -> str:
        return self._client.create_advisor(
            serialize_knob_config(knob_config), advisor_id=advisor_id)

    def propose(self, advisor_id: str) -> Dict[str, Any]:
        return self._client.propose_knobs(advisor_id)

    def feedback(self, advisor_id: str, knobs: Dict[str, Any],
                 score: float) -> Dict[str, Any]:
        return self._client.feedback_knobs(advisor_id, knobs, float(score))

    def get(self, advisor_id: str) -> _RemoteAdvisor:
        return _RemoteAdvisor(self._client, advisor_id)

    def replay_feedback(self, advisor_id: str, items) -> bool:
        return self._client.replay_advisor_feedback(advisor_id, items)

    def report_rung(self, advisor_id: str, trial_id: str, resource: int,
                    value: float, min_resource: int = 1, eta: int = 3,
                    mode: str = "min") -> bool:
        return self._client.report_rung(
            advisor_id, trial_id, resource, value,
            min_resource=min_resource, eta=eta, mode=mode)

    def delete_advisor(self, advisor_id: str) -> None:
        self._client.delete_advisor(advisor_id)
