"""Advisor sessions over HTTP — the out-of-process worker's view.

The reference's train workers talked to a separate advisor Flask service
over HTTP (reference rafiki/worker/train.py:207-215, advisor/app.py:17-50).
Here the advisor store lives inside the Admin process and is exposed on the
admin REST API (`/advisors/*`, admin/http.py); `RemoteAdvisorStore` adapts
that API to the in-process `AdvisorStore` interface the TrainWorker consumes
— so parallel worker *processes* of one sub-train-job still coordinate
through the single shared GP (the fix for reference train.py:213's
uncoordinated parallel HPO carries over to multi-process placement).

Control-plane crash tolerance: the admin may die and restart UNDER a
running worker (docs/failure-model.md "Control-plane faults" — the worker
is exactly what boot reconciliation adopts). Advisor calls therefore ride
out transport failures and the recovering-503 with bounded backoff
(``RAFIKI_ADVISOR_RETRY_S``, default 60 s; 0 disables) instead of
erroring the executor on the first connection-refused.
"""

from __future__ import annotations

import logging
import os
import random
import time
from typing import Any, Dict, List, Optional

import requests

from rafiki_tpu.client.client import AdminRecoveringError, Client, RafikiError
from rafiki_tpu.sdk.knob import serialize_knob_config

logger = logging.getLogger(__name__)


def _retry_window_s() -> float:
    return float(os.environ.get("RAFIKI_ADVISOR_RETRY_S", "60"))


def _ride_out(fn, what: str):
    """Run one advisor API call, riding out a dead/restarting admin:
    transport failures and the recovering 503 retry with jittered backoff
    until the window closes, then the last error propagates (the worker's
    own crash handling takes over).

    Retrying the mutating calls is a deliberate tradeoff: a request whose
    response was lost AFTER the admin applied it re-applies on retry. A
    duplicate GP observation is tolerable noise (worker/train.py makes
    the same call on its replay path), and ASHA rung reports are
    idempotent per (trial, rung) (advisor/asha.py records each rung
    once) — whereas NOT retrying kills the executor on the first
    connection blip, which is the failure this wrapper exists to stop."""
    deadline = time.monotonic() + _retry_window_s()
    delay = 0.2
    while True:
        try:
            return fn()
        except (requests.RequestException, AdminRecoveringError) as e:
            if time.monotonic() >= deadline:
                raise
            logger.warning(
                "advisor call %s failed (%s: %s); admin may be "
                "restarting — retrying for up to RAFIKI_ADVISOR_RETRY_S",
                what, type(e).__name__, e)
            time.sleep(delay * random.uniform(0.5, 1.5))
            delay = min(delay * 2, 5.0)


class _RemoteAdvisor:
    """Duck-types BaseAdvisor for the calls TrainWorker makes on it."""

    def __init__(self, client: Client, advisor_id: str):
        self._client = client
        self._id = advisor_id

    def feedback(self, knobs: Dict[str, Any], score: float) -> None:
        _ride_out(
            lambda: self._client.feedback_knobs(self._id, knobs,
                                                float(score)),
            "feedback")

    def feedback_infeasible(self, knobs: Dict[str, Any],
                            kind: str = "USER") -> None:
        _ride_out(
            lambda: self._client.feedback_infeasible_knobs(
                self._id, knobs, kind=kind),
            "feedback_infeasible")


class RemoteAdvisorStore:
    """AdvisorStore facade over the admin REST API (duck-typed; the
    TrainWorker never imports the concrete class)."""

    def __init__(self, client: Client):
        self._client = client
        # None = unknown, False = the admin answered an API error on a
        # batch route (pre-batch-API admin; probed once, then remembered)
        self._batch_api: Optional[bool] = None

    def create_advisor(self, knob_config: Dict[str, Any],
                       advisor_id: Optional[str] = None) -> str:
        return _ride_out(
            lambda: self._client.create_advisor(
                serialize_knob_config(knob_config), advisor_id=advisor_id),
            "create_advisor")

    def propose(self, advisor_id: str) -> Dict[str, Any]:
        return _ride_out(
            lambda: self._client.propose_knobs(advisor_id), "propose")

    def propose_batch(self, advisor_id: str, k: int) -> List[Dict[str, Any]]:
        """K proposals in one round trip. A mixed-version fleet (new
        worker, old admin without the /propose_batch route) degrades to
        K single proposals — the admin's shared GP still spreads them
        via its pending fantasies, the worker just pays K round trips."""
        k = max(int(k), 1)
        if self._batch_api is False:
            return [self.propose(advisor_id) for _ in range(k)]
        try:
            out = _ride_out(
                lambda: self._client.propose_knobs_batch(advisor_id, k),
                "propose_batch")
            self._batch_api = True
            return out
        except AdminRecoveringError:
            raise  # a recovering admin is not an OLD admin — let it retry
        except RafikiError as e:
            # latch the no-batch-API verdict ONLY on a missing route
            # (404): a transient refusal (503 overload shed, a flaky 500)
            # must not silently downgrade every later round to K serial
            # proposals — re-raise and let the caller handle this round
            if getattr(e, "status", None) != 404:
                raise
            self._batch_api = False
            logger.info(
                "admin has no batched advisor API (%s); falling back to "
                "single proposals for this session", e)
            return [self.propose(advisor_id) for _ in range(k)]

    def feedback_batch(self, advisor_id: str, items) -> int:
        if self._batch_api is False:
            for knobs, score in items:
                self.feedback(advisor_id, knobs, float(score))
            return len(items)
        try:
            out = int(_ride_out(
                lambda: self._client.feedback_knobs_batch(advisor_id, items),
                "feedback_batch"))
            self._batch_api = True
            return out
        except AdminRecoveringError:
            raise
        except RafikiError as e:
            if getattr(e, "status", None) != 404:
                raise  # transient refusal, not a pre-batch-API admin
            self._batch_api = False
            logger.info(
                "admin has no batched advisor API (%s); falling back to "
                "single feedback calls for this session", e)
            for knobs, score in items:
                self.feedback(advisor_id, knobs, float(score))
            return len(items)

    def feedback(self, advisor_id: str, knobs: Dict[str, Any],
                 score: float) -> Dict[str, Any]:
        return _ride_out(
            lambda: self._client.feedback_knobs(advisor_id, knobs,
                                                float(score)),
            "feedback")

    def get(self, advisor_id: str) -> _RemoteAdvisor:
        return _RemoteAdvisor(self._client, advisor_id)

    def feedback_infeasible(self, advisor_id: str, knobs: Dict[str, Any],
                            kind: str = "USER",
                            trial_id: Optional[str] = None) -> int:
        """Scoreless-failure signal (trial fault taxonomy) over the
        admin API — same ride-out semantics as feedback: re-applying on
        a lost response adds one duplicate penalty point, which the GP
        tolerates."""
        return _ride_out(
            lambda: self._client.feedback_infeasible_knobs(
                advisor_id, knobs, kind=kind, trial_id=trial_id),
            "feedback_infeasible")

    def replay_feedback(self, advisor_id: str, items,
                        infeasible=None) -> bool:
        return _ride_out(
            lambda: self._client.replay_advisor_feedback(
                advisor_id, items, infeasible=infeasible),
            "replay_feedback")

    def report_rung(self, advisor_id: str, trial_id: str, resource: int,
                    value: float, min_resource: int = 1, eta: int = 3,
                    mode: str = "min") -> bool:
        return _ride_out(
            lambda: self._client.report_rung(
                advisor_id, trial_id, resource, value,
                min_resource=min_resource, eta=eta, mode=mode),
            "report_rung")

    def delete_advisor(self, advisor_id: str) -> None:
        # teardown is best-effort: never worth stalling a stop on
        self._client.delete_advisor(advisor_id)
