"""Advisor sessions: propose/feedback over knob configs.

Parity with the reference's advisor layer (reference
rafiki/advisor/advisor.py:8-62 and advisor/service.py:15-79): a ``BaseAdvisor``
contract, a GP-backed default, and a sessionized store keyed by advisor id.
The store is thread-safe (the reference instead forced its Flask advisor app
single-threaded, reference scripts/start_advisor.py:10).
"""

from __future__ import annotations

import logging
import threading
import uuid
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from rafiki_tpu.advisor.gp import BayesOpt
from rafiki_tpu.sdk.knob import (
    KnobConfig,
    knob_config_dims,
    knobs_from_unit,
    knobs_to_unit,
)


def _jsonify(value: Any) -> Any:
    """Simplify numpy scalars into JSON-native types (reference
    rafiki/advisor/advisor.py:44-62 did the same for BTB proposals)."""
    if isinstance(value, np.integer):
        return int(value)
    if isinstance(value, np.floating):
        return float(value)
    if isinstance(value, np.bool_):
        return bool(value)
    if isinstance(value, dict):
        return {k: _jsonify(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_jsonify(v) for v in value]
    return value


class BaseAdvisor:
    """Contract: propose a knob assignment; feed back its achieved score.

    ``observation_count`` is part of the contract: the store's
    ``replay_feedback`` empty-only guard depends on every advisor type
    reporting how many observations it holds."""

    def __init__(self, knob_config: KnobConfig):
        self.knob_config = knob_config

    def propose(self) -> Dict[str, Any]:
        raise NotImplementedError

    def propose_batch(self, k: int) -> List[Dict[str, Any]]:
        """K knob assignments to evaluate CONCURRENTLY (the vectorized
        trial runner drains one batch per vmapped program). The base
        implementation loops ``propose`` — correct for any advisor type,
        since each advisor is responsible for making sequential proposals
        self-avoiding — so subclasses override only to batch more
        cleverly (the GP spreads the batch via its pending-point
        fantasies in one lock hold)."""
        return [self.propose() for _ in range(max(int(k), 1))]

    def feedback(self, knobs: Dict[str, Any], score: float) -> None:
        raise NotImplementedError

    def feedback_batch(
        self, items: List[Tuple[Dict[str, Any], float]]) -> int:
        """Record a batch of (knobs, score) observations — the return leg
        of ``propose_batch``. Applied member-by-member (each observation
        retires its own pending fantasy); returns how many were
        applied."""
        for knobs, score in items:
            self.feedback(knobs, float(score))
        return len(items)

    def feedback_infeasible(self, knobs: Dict[str, Any],
                            kind: str = "USER") -> None:
        """The trial at ``knobs`` failed WITHOUT a usable score (trial
        fault taxonomy: USER crash, TIMEOUT, INVALID_SCORE). Optional
        signal — the base implementation ignores it, so advisor types
        that can't use it stay valid; advisors that can (the GP) steer
        their proposal distribution away from the region."""

    @property
    def observation_count(self) -> int:
        raise NotImplementedError

    @property
    def infeasible_count(self) -> int:
        return 0


class Advisor(BaseAdvisor):
    """GP Bayesian-optimization advisor (the default).

    Thread-safe: one instance is shared by all parallel workers of a
    sub-train-job, with in-flight proposals fantasized (constant liar) so
    concurrent trials explore different regions.
    """

    def __init__(self, knob_config: KnobConfig, seed: int = 0):
        super().__init__(knob_config)
        self._opt = BayesOpt(knob_config_dims(knob_config), seed=seed)
        self._lock = threading.Lock()

    def propose(self) -> Dict[str, Any]:
        with self._lock:
            return self._propose_locked()

    def _propose_locked(self) -> Dict[str, Any]:
        u = self._opt.suggest(register_pending=False)
        knobs = knobs_from_unit(self.knob_config, u)
        # register the *quantized* point (integer/categorical knobs round
        # to a grid) so feedback's re-encoding retires it by value
        self._opt.mark_pending(knobs_to_unit(self.knob_config, knobs))
        return _jsonify(knobs)

    def propose_batch(self, k: int) -> List[Dict[str, Any]]:
        """K proposals under ONE lock hold, spread by the constant-liar
        fantasy machinery: each draw registers its quantized point as
        pending, so the next draw's EI already sees it fantasized at the
        observed minimum and explores elsewhere (the same mechanism that
        spreads concurrent workers, and that PR 5 extended to infeasible
        points). One lock hold keeps a concurrent sibling worker from
        interleaving draws into the middle of this batch."""
        with self._lock:
            return [self._propose_locked() for _ in range(max(int(k), 1))]

    def feedback(self, knobs: Dict[str, Any], score: float) -> None:
        u = knobs_to_unit(self.knob_config, knobs)
        with self._lock:
            self._opt.observe(u, float(score))

    def feedback_infeasible(self, knobs: Dict[str, Any],
                            kind: str = "USER") -> None:
        u = knobs_to_unit(self.knob_config, knobs)
        with self._lock:
            self._opt.mark_infeasible(u)

    @property
    def history(self) -> List[Tuple[np.ndarray, float]]:
        return list(zip(self._opt.observed_X, self._opt.observed_y))

    @property
    def observation_count(self) -> int:
        return len(self._opt.observed_y)

    @property
    def infeasible_count(self) -> int:
        return len(self._opt.infeasible_X)


class RandomAdvisor(BaseAdvisor):
    """Uniform random search baseline."""

    def __init__(self, knob_config: KnobConfig, seed: int = 0):
        super().__init__(knob_config)
        self._rng = np.random.default_rng(seed)
        self._dims = knob_config_dims(knob_config)
        self._n_observed = 0

    def propose(self) -> Dict[str, Any]:
        return _jsonify(knobs_from_unit(self.knob_config, self._rng.random(self._dims)))

    def propose_batch(self, k: int) -> List[Dict[str, Any]]:
        # one rng draw for the whole batch (random search needs no
        # spreading machinery — uniform draws are already independent)
        u = self._rng.random((max(int(k), 1), self._dims))
        return [_jsonify(knobs_from_unit(self.knob_config, row))
                for row in u]

    def feedback(self, knobs: Dict[str, Any], score: float) -> None:
        self._n_observed += 1

    def feedback_infeasible(self, knobs: Dict[str, Any],
                            kind: str = "USER") -> None:
        # random search has no model to steer; count for observability
        self._n_infeasible = getattr(self, "_n_infeasible", 0) + 1

    @property
    def observation_count(self) -> int:
        return self._n_observed

    @property
    def infeasible_count(self) -> int:
        return getattr(self, "_n_infeasible", 0)


class AdvisorStore:
    """Sessionized advisor registry (reference rafiki/advisor/service.py kept
    an in-memory dict behind Flask; here it's an explicit thread-safe store
    usable in-process or behind the admin HTTP API)."""

    _TYPES = {"GP": Advisor, "RANDOM": RandomAdvisor}

    def __init__(self) -> None:
        self._advisors: Dict[str, BaseAdvisor] = {}
        self._schedulers: Dict[str, Any] = {}  # advisor_id -> AshaScheduler
        self._lock = threading.Lock()

    def create_advisor(
        self,
        knob_config: KnobConfig,
        advisor_id: Optional[str] = None,
        advisor_type: str = "GP",
    ) -> str:
        advisor_id = advisor_id or uuid.uuid4().hex
        with self._lock:
            if advisor_id not in self._advisors:
                self._advisors[advisor_id] = self._TYPES[advisor_type](knob_config)
        return advisor_id

    def get(self, advisor_id: str) -> BaseAdvisor:
        with self._lock:
            if advisor_id not in self._advisors:
                raise KeyError(f"No such advisor: {advisor_id}")
            return self._advisors[advisor_id]

    def propose(self, advisor_id: str) -> Dict[str, Any]:
        return self.get(advisor_id).propose()

    def propose_batch(self, advisor_id: str, k: int) -> List[Dict[str, Any]]:
        """K concurrent proposals (the vectorized trial runner's drain).
        Advisors predating the batch API fall back to K single proposals
        — old advisor types keep working behind a new store."""
        advisor = self.get(advisor_id)
        fn = getattr(advisor, "propose_batch", None)
        if fn is not None:
            return fn(k)
        return [advisor.propose() for _ in range(max(int(k), 1))]

    def feedback_batch(
        self,
        advisor_id: str,
        items: List[Tuple[Dict[str, Any], float]],
    ) -> int:
        """Record a batch of (knobs, score) pairs member-by-member;
        returns how many observations were applied. Same pre-batch-API
        fallback as ``propose_batch``."""
        advisor = self.get(advisor_id)
        fn = getattr(advisor, "feedback_batch", None)
        if fn is not None:
            return int(fn(items))
        for knobs, score in items:
            advisor.feedback(knobs, float(score))
        return len(items)

    def feedback(self, advisor_id: str, knobs: Dict[str, Any], score: float) -> Dict[str, Any]:
        """Record a score; returns the next proposal (matching the
        reference's feedback-returns-next-proposal API, reference
        advisor/service.py:62-70)."""
        advisor = self.get(advisor_id)
        advisor.feedback(knobs, score)
        return advisor.propose()

    def feedback_infeasible(
        self,
        advisor_id: str,
        knobs: Dict[str, Any],
        kind: str = "USER",
        trial_id: Optional[str] = None,
    ) -> int:
        """Record a scoreless failure at ``knobs`` (trial fault taxonomy
        USER/TIMEOUT/INVALID_SCORE): the advisor steers its proposals
        away, and — when ``trial_id`` is given — the session's ASHA
        scheduler forgets the trial's rung records so a crashed trial's
        partial metrics can't set promotion bars for healthy ones.
        Returns the session's infeasible count (observability)."""
        advisor = self.get(advisor_id)
        advisor.feedback_infeasible(knobs, kind)
        if trial_id is not None:
            with self._lock:
                sched = self._schedulers.get(advisor_id)
            if sched is not None:
                sched.forget(trial_id)
        return advisor.infeasible_count

    def replay_feedback(
        self,
        advisor_id: str,
        items: List[Tuple[Dict[str, Any], float]],
        infeasible: Optional[List[Tuple[Dict[str, Any], str]]] = None,
    ) -> bool:
        """Seed a FRESH advisor session with already-scored (knobs, score)
        pairs — how a restarted worker rebuilds the GP from the completed
        trials already in the store. Atomic and empty-only: if the session
        has any observations (it survived, or a sibling already replayed),
        this is a no-op returning False, so concurrent restarts can't
        double-feed the optimizer. (Workers also feed back BEFORE marking a
        trial COMPLETED, so a trial visible as COMPLETED implies its score
        is already in a surviving session — the guard and that ordering
        together close the double-feed window.)

        ``infeasible`` — (knobs, fault_kind) pairs from USER/TIMEOUT/
        INVALID_SCORE-errored trials — rides the same guard: a fresh
        session relearns which regions crash, not just which scored."""
        with self._lock:
            advisor = self._advisors.get(advisor_id)
            if advisor is None:
                raise KeyError(f"No such advisor: {advisor_id}")
            # infeasible points count toward "not fresh" too: a session
            # that survived with ONLY infeasible history (every early
            # trial crashed) must not re-accumulate duplicates on each
            # worker restart of a crash-looping job
            if advisor.observation_count > 0 \
                    or getattr(advisor, "infeasible_count", 0) > 0:
                return False
            for knobs, score in items:
                advisor.feedback(knobs, float(score))
            for knobs, kind in infeasible or []:
                advisor.feedback_infeasible(knobs, str(kind))
            return True

    def report_rung(self, advisor_id: str, trial_id: str, resource: int,
                    value: float, min_resource: int = 1, eta: int = 3,
                    mode: str = "min") -> bool:
        """ASHA early-stop check: record an intermediate metric for a trial
        and return whether it should continue (advisor/asha.py). The
        scheduler shares the advisor session's lifecycle, so parallel
        workers of one sub-train-job compete within one rung population —
        like the shared GP."""
        from rafiki_tpu.advisor.asha import AshaScheduler

        with self._lock:
            if advisor_id not in self._advisors:
                raise KeyError(f"No such advisor: {advisor_id}")
            sched = self._schedulers.get(advisor_id)
            if sched is None:
                sched = self._schedulers[advisor_id] = AshaScheduler(
                    min_resource=min_resource, eta=eta, mode=mode)
            elif (sched.min_resource, sched.eta, sched.mode) != (
                    max(int(min_resource), 1), int(eta), mode):
                # the scheduler is shared per session and configured by
                # whoever reports first; a divergent caller (worker
                # restarted with a changed budget against a live admin)
                # competes under the existing ladder — say so, don't
                # silently ignore the requested parameters
                logging.getLogger(__name__).warning(
                    "ASHA params (%s,%s,%s) differ from session %s's "
                    "live scheduler (%s,%s,%s); using the existing one",
                    min_resource, eta, mode, advisor_id,
                    sched.min_resource, sched.eta, sched.mode)
        return sched.report(trial_id, resource, value)

    def delete_advisor(self, advisor_id: str) -> None:
        with self._lock:
            self._advisors.pop(advisor_id, None)
            self._schedulers.pop(advisor_id, None)
