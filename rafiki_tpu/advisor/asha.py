"""Asynchronous successive halving (ASHA) for early-stopping HPO trials.

A capability the reference lacks entirely: its trials always train to their
full epoch budget (reference worker/train.py:37-132 has no intermediate
signal at all). Here, models that report per-epoch metrics through their
``ModelLogger`` (which every SDK-trainer template does via ``fit(log=...)``)
get rung-based early stopping: at exponentially spaced resource levels
(``min_resource * eta^k`` epochs), a trial continues only while its metric
is competitive with what other trials of the same sub-train-job achieved at
the same rung. Poor knob draws stop after 1-2 epochs instead of burning
their whole budget, so the same trial-count budget explores several times
more of the search space per chip-hour.

This is the asynchronous variant (Li et al., "A System for Massively
Parallel Hyperparameter Tuning", MLSys 2020 — public algorithm): decisions
are made per-report against the rung's current population, with no
synchronized bracket barrier — workers never wait for each other, which is
the property that matters for parallel executors.

Promotion rule: at each rung the trial's value must sit in the top
``1/eta`` fraction of all values recorded at that rung so far. While a rung
has seen fewer than ``eta`` values there is not enough evidence to kill
anything, so reports pass (the permissive async variant — without it, the
second trial of a job dies merely for being worse than the first).
"""

from __future__ import annotations

import math
import threading
from typing import Dict, List


class AshaScheduler:
    """Shared per sub-train-job; thread-safe (parallel workers report
    concurrently, like the shared GP advisor)."""

    def __init__(self, min_resource: int = 1, eta: int = 3,
                 mode: str = "min"):
        if eta < 2:
            raise ValueError(f"eta must be >= 2, got {eta}")
        if mode not in ("min", "max"):
            raise ValueError(f"mode must be 'min' or 'max', got {mode!r}")
        self.min_resource = max(int(min_resource), 1)
        self.eta = int(eta)
        self.mode = mode
        self._lock = threading.Lock()
        # rung resource -> {trial_id: value}; keyed by trial so a trial
        # that later ERRORS can be forgotten (its partial metrics must
        # not set promotion bars for healthy trials — see forget())
        self._rungs: Dict[int, Dict[str, float]] = {}
        self._recorded: Dict[str, set] = {}        # trial -> rungs recorded

    def _rungs_reached(self, resource: int) -> List[int]:
        out, r = [], self.min_resource
        while r <= resource:
            out.append(r)
            r *= self.eta
        return out

    def report(self, trial_id: str, resource: int, value: float) -> bool:
        """Record `value` achieved by `trial_id` at `resource` (e.g. epochs
        completed). Returns True to continue training, False to stop.

        The value is recorded only at the HIGHEST rung this report newly
        reaches — a rung's population must hold values measured *at* that
        resource. Backfilling skipped lower rungs (a trial resumed from a
        late checkpoint after the scheduler restarted, or a template that
        reports every N > 1 epochs) with a later, better value would set an
        unbeatable bar that kills healthy fresh trials; those rungs are
        marked seen without a record instead."""
        value = float(value)
        if not math.isfinite(value):
            return False  # NaN/inf loss: this trial is going nowhere
        with self._lock:
            seen = self._recorded.setdefault(trial_id, set())
            new_rungs = [r for r in self._rungs_reached(int(resource))
                         if r not in seen]
            seen.update(new_rungs)
            if not new_rungs:
                return True  # between rungs: no decision point
            rung = new_rungs[-1]
            if int(resource) != rung:
                # the measurement was taken past the rung's resource (sparse
                # reporter, or a resume that overshot): recording it would
                # bias the rung with a later-epoch value, so skip — a rung
                # population holds only values measured AT its resource
                return True
            values = self._rungs.setdefault(rung, {})
            values[trial_id] = value
            if len(values) < self.eta:
                return True  # not enough evidence at this rung yet
            ranked = sorted(values.values(), reverse=(self.mode == "max"))
            top_k = max(int(math.ceil(len(ranked) / self.eta)), 1)
            threshold = ranked[top_k - 1]
            return (value <= threshold if self.mode == "min"
                    else value >= threshold)

    def forget(self, trial_id: str) -> None:
        """Erase a trial's rung records (trial fault taxonomy: the trial
        ERRORED after reporting — a USER crash or invalid score). Its
        recorded values may be garbage from a template already failing,
        and a dead trial must not occupy top-1/eta slots that kill
        healthy fresh trials competing at the same rungs."""
        with self._lock:
            for values in self._rungs.values():
                values.pop(trial_id, None)
            self._recorded.pop(trial_id, None)
