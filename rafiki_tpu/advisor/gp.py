"""Minimal, dependency-free Gaussian-process Bayesian optimization core.

Operates purely on the unit cube [0,1]^d; knob-type handling lives in
rafiki_tpu.sdk.knob (each knob encodes itself). Maximizes expected
improvement. Pending (proposed-but-unscored) points are fantasized with the
constant-liar strategy so concurrent proposals spread out instead of
colliding — the coordination the reference lacked entirely.
"""

from __future__ import annotations

import math
from typing import List, Optional

import numpy as np


def _matern52(X1: np.ndarray, X2: np.ndarray, lengthscale: float) -> np.ndarray:
    d = np.sqrt(
        np.maximum(
            ((X1[:, None, :] - X2[None, :, :]) ** 2).sum(-1), 0.0
        )
    )
    r = math.sqrt(5.0) * d / lengthscale
    return (1.0 + r + r * r / 3.0) * np.exp(-r)


class GaussianProcess:
    """GP with Matérn-5/2 kernel, standardized targets, and a small
    marginal-likelihood grid search over the lengthscale."""

    NOISE = 1e-6

    def __init__(self) -> None:
        self.X: Optional[np.ndarray] = None
        self.y: Optional[np.ndarray] = None
        self._chol: Optional[np.ndarray] = None
        self._alpha: Optional[np.ndarray] = None
        self._ls = 0.3
        self._y_mean = 0.0
        self._y_std = 1.0

    def fit(self, X: np.ndarray, y: np.ndarray) -> None:
        self.X = np.asarray(X, dtype=np.float64)
        y = np.asarray(y, dtype=np.float64)
        self._y_mean = float(y.mean())
        self._y_std = float(y.std()) or 1.0
        self.y = (y - self._y_mean) / self._y_std
        best_ll, best_ls = -np.inf, self._ls
        for ls in (0.1, 0.2, 0.3, 0.5, 1.0):
            ll = self._marginal_ll(ls)
            if ll > best_ll:
                best_ll, best_ls = ll, ls
        self._ls = best_ls
        K = _matern52(self.X, self.X, self._ls) + self.NOISE * np.eye(len(self.X))
        self._chol = np.linalg.cholesky(K)
        self._alpha = np.linalg.solve(
            self._chol.T, np.linalg.solve(self._chol, self.y)
        )

    def _marginal_ll(self, ls: float) -> float:
        assert self.X is not None and self.y is not None
        K = _matern52(self.X, self.X, ls) + self.NOISE * np.eye(len(self.X))
        try:
            L = np.linalg.cholesky(K)
        except np.linalg.LinAlgError:
            return -np.inf
        alpha = np.linalg.solve(L.T, np.linalg.solve(L, self.y))
        return float(
            -0.5 * self.y @ alpha - np.log(np.diag(L)).sum()
        )

    def predict(self, Xs: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """Posterior mean and stddev at query points (de-standardized)."""
        assert self.X is not None and self._chol is not None
        Ks = _matern52(np.asarray(Xs, dtype=np.float64), self.X, self._ls)
        mu = Ks @ self._alpha
        v = np.linalg.solve(self._chol, Ks.T)
        var = np.maximum(1.0 + self.NOISE - (v * v).sum(0), 1e-12)
        return (
            mu * self._y_std + self._y_mean,
            np.sqrt(var) * self._y_std,
        )


def _norm_pdf(z: np.ndarray) -> np.ndarray:
    return np.exp(-0.5 * z * z) / math.sqrt(2 * math.pi)


def _norm_cdf(z: np.ndarray) -> np.ndarray:
    from math import erf

    return 0.5 * (1.0 + np.vectorize(erf)(z / math.sqrt(2)))


def expected_improvement(
    mu: np.ndarray, sigma: np.ndarray, best: float, xi: float = 0.01
) -> np.ndarray:
    imp = mu - best - xi
    z = imp / sigma
    return imp * _norm_cdf(z) + sigma * _norm_pdf(z)


class BayesOpt:
    """Sequential maximizer over [0,1]^d with pending-point fantasies."""

    N_CANDIDATES = 2048

    def __init__(self, dims: int, seed: int = 0):
        self.dims = dims
        self.rng = np.random.default_rng(seed)
        self.observed_X: List[np.ndarray] = []
        self.observed_y: List[float] = []
        self.pending_X: List[np.ndarray] = []
        # points that FAILED without a score (crashing template, timeout,
        # NaN evaluate — the trial fault taxonomy's infeasible kinds):
        # fantasized below the observed minimum so EI steers away from
        # the region instead of re-proposing it (Vizier-style infeasible
        # handling, Golovin et al. 2017). DEDUPLICATED on a quantized
        # grid and capped: quarantine re-proposals and restart replays
        # feed near-identical points repeatedly, and thousands of
        # clustered penalty rows would bloat the O(n^3) fit and wreck
        # kernel conditioning without adding information.
        self.infeasible_X: List[np.ndarray] = []
        self._infeasible_cells: set = set()

    @property
    def n_warmup(self) -> int:
        return max(3, self.dims)

    def suggest(self, register_pending: bool = True) -> np.ndarray:
        """Next point to evaluate. Random during warmup; EI afterwards, with
        pending points fantasized at the current minimum (constant liar).

        With ``register_pending=False`` the caller is expected to call
        ``mark_pending`` itself (e.g. after quantizing the point to the knob
        grid, so the later ``observe`` can retire it by value)."""
        if self.dims == 0:
            return np.zeros(0)
        if len(self.observed_X) < self.n_warmup:
            x = self._warmup_draw()
        else:
            X = np.array(self.observed_X)
            y = np.array(self.observed_y)
            # the constant-liar level for in-flight points comes from the
            # OBSERVED minimum, taken before the penalty rows join y —
            # a sibling's pending point is "probably mediocre", not
            # "probably crashes"
            lie = float(y.min())
            if self.infeasible_X:
                # penalty fantasies: infeasible points enter the fit at
                # one spread below the observed minimum — low enough
                # that EI never chases the region, finite enough that
                # the GP stays well-conditioned
                bad = lie - (float(y.std()) or 1.0)
                X = np.vstack([X, np.array(self.infeasible_X)])
                y = np.concatenate(
                    [y, np.full(len(self.infeasible_X), bad)])
            if self.pending_X:
                X = np.vstack([X, np.array(self.pending_X)])
                y = np.concatenate([y, np.full(len(self.pending_X), lie)])
            gp = GaussianProcess()
            gp.fit(X, y)
            cand = self.rng.random((self.N_CANDIDATES, self.dims))
            # include jittered copies of the incumbent for local refinement
            best_x = self.observed_X[int(np.argmax(self.observed_y))]
            local = np.clip(
                best_x + 0.05 * self.rng.standard_normal((64, self.dims)), 0, 1
            )
            cand = np.vstack([cand, local])
            mu, sigma = gp.predict(cand)
            ei = expected_improvement(mu, sigma, float(np.max(self.observed_y)))
            x = cand[int(np.argmax(ei))]
        if register_pending:
            self.mark_pending(x)
        return x

    def _warmup_draw(self) -> np.ndarray:
        """Random warmup point; with infeasible history, the draw is the
        candidate FARTHEST from any infeasible point among a small pool —
        warmup must not keep landing in a known-crashing basin while the
        GP has too little data to learn it."""
        if not self.infeasible_X:
            return self.rng.random(self.dims)
        cand = self.rng.random((16, self.dims))
        inf = np.array(self.infeasible_X)
        d_min = np.sqrt(
            ((cand[:, None, :] - inf[None, :, :]) ** 2).sum(-1)).min(1)
        return cand[int(np.argmax(d_min))]

    def mark_pending(self, x: np.ndarray) -> None:
        self.pending_X.append(np.asarray(x, dtype=np.float64))

    INFEASIBLE_GRID = 16   # dedup resolution per dimension
    INFEASIBLE_CAP = 512   # hard bound; beyond it the oldest drop

    def mark_infeasible(self, x: np.ndarray) -> None:
        """Record a point that failed without a usable score. Retires
        the matching pending fantasy like ``observe`` does — the trial
        is finished, just not scored. A point in an already-penalized
        grid cell still retires its fantasy but adds no new row."""
        x = np.asarray(x, dtype=np.float64)
        if self.pending_X:
            d = [float(((p - x) ** 2).sum()) for p in self.pending_X]
            self.pending_X.pop(int(np.argmin(d)))
        cell = tuple(np.round(x * self.INFEASIBLE_GRID).astype(int)
                     .tolist())
        if cell in self._infeasible_cells:
            return
        self._infeasible_cells.add(cell)
        self.infeasible_X.append(x)
        if len(self.infeasible_X) > self.INFEASIBLE_CAP:
            old = self.infeasible_X.pop(0)
            self._infeasible_cells.discard(
                tuple(np.round(old * self.INFEASIBLE_GRID).astype(int)
                      .tolist()))

    def observe(self, x: np.ndarray, y: float) -> None:
        x = np.asarray(x, dtype=np.float64)
        self.observed_X.append(x)
        self.observed_y.append(float(y))
        # Retire one fantasy per real observation: the nearest pending point.
        # (Feedback may arrive for points proposed elsewhere or quantized to a
        # knob grid, so exact matching would leak fantasies forever.)
        if self.pending_X:
            d = [float(((p - x) ** 2).sum()) for p in self.pending_X]
            self.pending_X.pop(int(np.argmin(d)))
